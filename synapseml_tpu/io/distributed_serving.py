"""Distributed serving: per-worker HTTP servers + driver routing front.

Reference: ``streaming/DistributedHTTPSource.scala:88-203`` — every executor
runs a ``JVMSharedServer`` and requests are served wherever they land, with
the driver service collecting worker endpoints
(``DriverServiceUtils``, ``continuous/HTTPSourceV2.scala:132-202``). Here:

  * ``worker_main`` — one OS process per partition-worker, running
    ``serve_pipeline`` on its own port and registering (host, port) with the
    driver registry;
  * ``WorkerRegistry`` — the driver-side registration endpoint (worker list =
    the routing table);
  * ``RoutingFront`` — the one public port: forwards each request round-robin
    to a live worker, skipping dead ones (the shared-server role).

``serve_pipeline_distributed`` wires all three and returns the front.
"""

from __future__ import annotations

import collections
import hashlib
import http.client
import json
import os
import pickle
import queue
import random
import socket
import subprocess
import sys
import threading
import time
import itertools
import urllib.error
import urllib.request
import uuid
import weakref
from http.server import BaseHTTPRequestHandler

from ..core import batching as cb
from ..core import faults as _faults
from ..core import observability as obs
from ..core.resilience import CircuitBreaker, resilience_measures
# the fleet plane owns the model-path and priority-class conventions; one
# definition each (fleet modules import io lazily, so no cycle)
from ..fleet.admission import priority_of as _priority_of
from ..fleet.residency import model_from_path as _model_of_path
from .serving import NoDelayHTTPServer

__all__ = ["WorkerRegistry", "RoutingFront", "RoutingClient",
           "serve_pipeline_distributed", "worker_main", "llm_worker_main",
           "deregister_worker", "collect_distributed_trace"]


def deregister_worker(registry_address: str, info: dict,
                      timeout_s: float = 10.0) -> bool:
    """POST a worker's registration info to the registry's ``/deregister``
    endpoint — the ONE graceful-removal call both worker entrypoints
    (``worker_main`` here, ``fleet_worker_main``) and the in-process fleet
    launcher share, so the deregister contract cannot drift between them.
    ``registry_address`` may be the ``/register`` URL or the bare registry
    address (the handler only branches on a ``deregister`` suffix).
    Best-effort: an unreachable registry returns False, never raises —
    the caller is about to exit either way."""
    base = str(registry_address).rstrip("/")
    dereg = (base[:-len("/register")] if base.endswith("/register")
             else base) + "/deregister"
    try:
        urllib.request.urlopen(urllib.request.Request(
            dereg, data=json.dumps(info).encode(), method="POST",
            headers={"Content-Type": "application/json"}),
            timeout=timeout_s).read()
        return True
    except (urllib.error.URLError, OSError):
        return False

_BREAKER_STATE_NUM = {CircuitBreaker.CLOSED: 0.0,
                      CircuitBreaker.HALF_OPEN: 1.0,
                      CircuitBreaker.OPEN: 2.0}

# distinct label per RoutingFront/RoutingClient instance: two live owners
# sharing a worker endpoint must not emit duplicate series (a Prometheus
# scrape rejects identical label sets)
_BREAKER_OWNER_IDS = itertools.count(1)


def _register_breaker_gauge(owner, plane: str,
                            instance: str | None = None) -> None:
    """Pull-time ``synapseml_breaker_state`` gauge per worker endpoint
    (0=closed, 1=half-open, 2=open) for a RoutingFront/RoutingClient.
    Weakref'd: a collected owner silently stops exporting. ``instance``
    lets one owner share ITS id across several collectors (the front's
    breaker + split gauges must correlate on a dashboard)."""
    ref = weakref.ref(owner)
    reg = obs.get_registry()
    if instance is None:
        instance = str(next(_BREAKER_OWNER_IDS))

    def collect():
        o = ref()
        if o is None:  # owner collected: self-unregister so a long session
            reg.unregister_collector(collect)  # doesn't accumulate dead fns
            return
        for endpoint, state in o.breaker_states().items():
            yield obs.Sample(
                "synapseml_breaker_state",
                {"plane": plane, "endpoint": endpoint, "instance": instance},
                _BREAKER_STATE_NUM.get(state, -1.0),
                help="per-worker circuit breaker state "
                     "(0=closed, 1=half-open, 2=open)")

    reg.register_collector(collect)


# hot routing-path metric handles (see HandleCache: one identity check per
# request instead of registry get-or-create lock traffic)
_ROUTE_METRICS = obs.HandleCache(lambda reg: {
    "pick_ms": reg.histogram(
        "synapseml_route_pick_ms",
        "time to pick the first candidate worker").labels(),
    "retries": reg.counter(
        "synapseml_route_retries_total",
        "rerouted forwards after a worker failure").labels(),
    "worker_failures": reg.counter(
        "synapseml_route_worker_failures_total",
        "forward attempts that failed, per worker", ("worker",)),
    "request_ms": reg.histogram(
        "synapseml_route_request_duration_ms",
        "routed request latency, per worker", ("worker",)),
    "unroutable": reg.counter(
        "synapseml_route_unroutable_total",
        "requests that exhausted every worker").labels(),
    # deployment plane: per-version series (canary observability) — the
    # acceptance surface for registry/deploy.py rollout decisions
    "version_requests": reg.counter(
        "synapseml_route_version_requests_total",
        "routed requests per pipeline version", ("version", "status")),
    "version_ms": reg.histogram(
        "synapseml_route_version_request_ms",
        "routed request latency per pipeline version", ("version",)),
    "shadow_requests": reg.counter(
        "synapseml_route_shadow_requests_total",
        "shadow-traffic duplicates per version", ("version", "status")),
    "shadow_delta_ms": reg.histogram(
        "synapseml_route_shadow_latency_delta_ms",
        "shadow latency minus primary latency for the same request",
        ("version",)),
    # continuous-batching coalescer: how full the same-path groups run and
    # how much padding the workers' bucket ladder will spend on them
    "bucket_occupancy": reg.histogram(
        "synapseml_route_bucket_occupancy",
        "requests per coalesced same-path group released to one worker",
        ("version",), buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)),
    "padded_rows": reg.counter(
        "synapseml_route_padded_rows_total",
        "rows of bucket padding the released group sizes imply",
        ("version",)),
    "real_rows": reg.counter(
        "synapseml_route_real_rows_total",
        "real request rows released through the coalescer", ("version",)),
})


class _VersionStats:
    """Monotonic per-version counters + a bounded latency window, kept by
    the RoutingFront so the auto-rollback controller (registry/deploy.py)
    and the fleet autoscaler can diff outcomes without scraping the
    Prometheus text format. The fleet plane adds per-PRIORITY state: how
    many requests of each class are in flight through the front right now
    (the front-side queue depth) and how many the admission controller
    shed (monotonic, reconcilable with client-observed 429s)."""

    __slots__ = ("ok", "err", "shadow_ok", "shadow_err", "latencies_ms",
                 "inflight", "shed")

    def __init__(self):
        self.ok = 0
        self.err = 0
        self.shadow_ok = 0
        self.shadow_err = 0
        self.latencies_ms = collections.deque(maxlen=256)
        self.inflight = {"interactive": 0, "bulk": 0}
        self.shed = {"interactive": 0, "bulk": 0}

    def snapshot(self) -> dict:
        lat = list(self.latencies_ms)
        out = {"ok": self.ok, "err": self.err,
               "shadow_ok": self.shadow_ok, "shadow_err": self.shadow_err,
               "n_latencies": len(lat),
               "inflight": dict(self.inflight), "shed": dict(self.shed)}
        if lat:
            lat.sort()
            out["p50_ms"] = round(lat[len(lat) // 2], 3)
            out["p95_ms"] = round(lat[min(len(lat) - 1,
                                          int(len(lat) * 0.95))], 3)
        return out


def _version_of(w: dict) -> str:
    """A worker registration's pipeline version label (canary routing /
    per-version metrics); unlabeled fleets collapse to one series."""
    return str(w.get("version") or "unversioned")


def _hosts_model(w: dict, model: str) -> bool:
    """Does this worker registration advertise ``model``? (Single-model
    fleet workers register ``model``; multi-model residency workers may
    register a ``models`` list.)"""
    if w.get("model") == model:
        return True
    models = w.get("models")
    return isinstance(models, (list, tuple)) and model in models


def _model_aware(w: dict) -> bool:
    """Does this registration carry ANY model info (single-model ``model``
    or multi-model ``models``)?"""
    return w.get("model") is not None \
        or isinstance(w.get("models"), (list, tuple))


def _eligible_for_model(w: dict, model: str, fleet_labeled: bool) -> bool:
    """Can this worker SERVE ``model`` at all? A single-model worker
    registered for a DIFFERENT model is ineligible — forwarding a /m/B
    request to model A's pipeline would return A's prediction with a 200,
    a silent wrong answer worse than a 503. Multi-model residency workers
    (a ``models`` list, even empty — they load on demand) stay eligible.
    Model-less legacy registrations are eligible ONLY on an unlabeled
    fleet (``fleet_labeled`` False — pre-fleet deployments that happen to
    use /m/ paths keep working); once any worker advertises model info,
    an unlabeled worker serving who-knows-what must not catch model
    traffic the labeled workers dropped."""
    if _hosts_model(w, model):
        return True
    if isinstance(w.get("models"), (list, tuple)):
        return True
    return w.get("model") is None and not fleet_labeled


_PREFIX_SIG_TOKENS = 16   # token-id requests: sig over the first 16 ids
# (one default KV block's worth — long enough to separate unrelated
# prompts, short enough that family members diverging after a shared
# system-prompt head still hash to the SAME worker)
_PREFIX_SIG_CHARS = 256   # text requests: sig over the first 256 chars


def _prefix_sig(body) -> "str | None":
    """Stable signature of a generation request's prompt HEAD — the
    rendezvous key for prefix-affinity routing. Hashing only the head (a
    block's worth of tokens / a system-prompt's worth of text) is the
    point: requests that SHARE a prefix but diverge later must map to the
    same worker, so the divergent tail stays out of the key. Non-JSON and
    non-generation bodies return None (no affinity, plain rotation)."""
    if body is None:
        return None
    if isinstance(body, (bytes, bytearray)):
        try:
            body = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None
    if not isinstance(body, dict):
        return None
    ids = body.get("input_ids")
    if isinstance(ids, (list, tuple)) and ids:
        try:
            head = ",".join(str(int(t)) for t in ids[:_PREFIX_SIG_TOKENS])
        except (TypeError, ValueError):
            return None
        return hashlib.md5(f"ids|{head}".encode()).hexdigest()
    prompt = body.get("prompt")
    if isinstance(prompt, str) and prompt:
        return hashlib.md5(
            f"txt|{prompt[:_PREFIX_SIG_CHARS]}".encode()).hexdigest()
    return None


def _register_split_gauge(front, instance: str) -> None:
    """Pull-time ``synapseml_route_split_weight`` gauge per version: the
    active canary/traffic split, visible on ``/metrics`` so dashboards see
    rollout state without scraping admin endpoints. Weakref'd like the
    breaker gauge; a cleared split simply stops exporting. ``instance``
    is the owning front's id — the same label its breaker gauge carries."""
    ref = weakref.ref(front)
    reg = obs.get_registry()

    def collect():
        o = ref()
        if o is None:
            reg.unregister_collector(collect)
            return
        for version, weight in (o.traffic_split() or {}).items():
            yield obs.Sample(
                "synapseml_route_split_weight",
                {"version": version, "instance": instance}, weight,
                help="active traffic-split weight per pipeline version "
                     "(normalized; absent = no split active)")

    reg.register_collector(collect)


def _nodelay_connection(host: str, port: int,
                        timeout_s: float) -> http.client.HTTPConnection:
    """Persistent client connection with TCP_NODELAY (see NoDelayHTTPServer:
    keep-alive + Nagle + delayed ACK = ~40 ms per small request otherwise)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return conn


class WorkerRegistry:
    """Driver-side worker registration (DriverServiceUtils analog): workers
    POST {host, port, pid}; the routing table is the registered list. A
    re-registration from the same (host, port) replaces the old entry, so a
    restarted worker rejoins cleanly. ``POST .../deregister`` removes the
    entry — a gracefully DRAINED worker (fleet plane, ``/admin/drain``)
    leaves the table deliberately, so its disappearance is no longer
    indistinguishable from a crash."""

    def __init__(self):
        self._workers: list[dict] = []
        self._lock = threading.Lock()
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                info = json.loads(self.rfile.read(n))
                key = (info.get("host"), info.get("port"))
                with registry._lock:
                    registry._workers = [
                        w for w in registry._workers
                        if (w.get("host"), w.get("port")) != key]
                    if not self.path.rstrip("/").endswith("deregister"):
                        registry._workers.append(info)
                body = b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = NoDelayHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def workers(self) -> list[dict]:
        with self._lock:
            return list(self._workers)

    def remove_pid(self, pid: int) -> None:
        """Drop a worker whose process is known dead (supervisor callback)."""
        with self._lock:
            self._workers = [w for w in self._workers if w.get("pid") != pid]

    def wait_for(self, n: int, timeout_s: float = 60.0) -> list[dict]:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            w = self.workers()
            if len(w) >= n:
                return w
            time.sleep(0.05)
        raise TimeoutError(f"only {len(self.workers())}/{n} workers registered")

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class _ConnPool:
    """Persistent per-worker HTTP connections (keep-alive): forwarding a
    request costs one loopback write/read, not a TCP handshake + teardown —
    the difference between the round-3 1.5 ms routed p50 and sub-ms."""

    def __init__(self, timeout_s: float, max_idle_per_key: int = 32):
        self._idle: dict[tuple, list] = {}
        self._lock = threading.Lock()
        self._timeout_s = timeout_s
        self._max_idle = max_idle_per_key

    def get(self, key: tuple):
        """(connection, fresh) — a pooled keep-alive connection when one is
        idle, else a freshly connected TCP_NODELAY one (raises OSError when
        the worker is unreachable). An active fault plan (``core/faults.py``)
        may inject a connect failure / crash / blackhole here — the hook sits
        before the pool so injected faults hit pooled connections too."""
        plan = _faults.active_fault_plan()
        if plan is not None:
            plan.on_connect(key)
        with self._lock:
            stack = self._idle.get(key)
            if stack:
                return stack.pop(), False
        return _nodelay_connection(key[0], key[1], self._timeout_s), True

    def put(self, key: tuple, conn) -> None:
        with self._lock:
            stack = self._idle.setdefault(key, [])
            if len(stack) < self._max_idle:
                stack.append(conn)
                return
        conn.close()

    def clear(self, key: tuple) -> None:
        with self._lock:
            stack = self._idle.pop(key, [])
        for c in stack:
            c.close()

    def close(self) -> None:
        with self._lock:
            conns = [c for stack in self._idle.values() for c in stack]
            self._idle.clear()
        for c in conns:
            c.close()


def _pooled_request(pool: _ConnPool, key: tuple, method: str, path: str,
                    body, headers: dict | None):
    """(status, payload) over a pooled keep-alive connection.

    A stale pooled connection (worker restarted / idle-closed) drops every
    idle connection for the key and retries ONCE on a fresh one; a fresh
    connection failing means the worker is genuinely unreachable, and the
    exception propagates to the caller. Shared by the RoutingFront proxy
    and the RoutingClient so the retry semantics cannot diverge."""
    for _ in range(2):
        conn, fresh = None, True
        try:
            conn, fresh = pool.get(key)
            conn.request(method, path, body=body, headers=headers or {})
            r = conn.getresponse()
            payload = r.read()
        except (http.client.HTTPException, OSError):
            if conn is not None:
                conn.close()
            if fresh:
                raise
            pool.clear(key)
            continue
        if r.will_close:
            conn.close()
        else:
            pool.put(key, conn)
        return r.status, payload
    raise ConnectionError(f"worker {key} failed on a fresh connection")


class _CoalesceGroup:
    """One batch-in-flight of same-path requests: all members forward to the
    same candidate ordering, so the chosen worker's continuous-batching
    scheduler drains them as one bucket-sized batch."""

    __slots__ = ("path", "count", "closed", "release", "lock", "candidates",
                 "desperate")

    def __init__(self, path: str):
        self.path = path
        self.count = 0
        self.closed = False
        self.release = threading.Event()
        self.lock = threading.Lock()
        self.candidates = None
        self.desperate = False


class _RequestCoalescer:
    """Groups same-path requests arriving within ``window_s`` so they land
    on the SAME worker back-to-back instead of round-robining one row to
    every worker in the fleet. The first joiner (leader) holds the group
    open until a full bucket's worth (``max_group``) joins or the window
    expires; followers ride the leader's release. Occupancy and the padding
    the workers' bucket ladder will spend on each released group are
    exported per version (``synapseml_route_bucket_occupancy`` /
    ``_padded_rows_total`` / ``_real_rows_total``)."""

    def __init__(self, window_s: float, max_group: int = 64):
        self.window_s = float(window_s)
        self.max_group = int(max_group)
        self._lock = threading.Lock()
        self._open: dict[str, _CoalesceGroup] = {}

    def join(self, path: str) -> _CoalesceGroup:
        with self._lock:
            group = self._open.get(path)
            leader = group is None or group.closed
            if leader:
                group = self._open[path] = _CoalesceGroup(path)
            group.count += 1
            if group.count >= self.max_group:
                group.closed = True
                if self._open.get(path) is group:
                    del self._open[path]
                group.release.set()
        if leader:
            group.release.wait(self.window_s)
            with self._lock:
                group.closed = True
                if self._open.get(path) is group:
                    del self._open[path]
            group.release.set()
        else:
            # followers outwait the leader slightly; a lost wakeup degrades
            # to forwarding solo, never to a dropped request
            group.release.wait(self.window_s + 0.25)
        return group


# survivable-LLM plane: journal/migration/hedging metric handles
_JOURNAL_METRICS = obs.HandleCache(lambda reg: {
    "resubmits": reg.counter(
        "synapseml_llm_resubmits_total",
        "journaled generations resubmitted to another worker (mode: "
        "import = adopted a migrated KV snapshot, resume = re-prefilled "
        "over prompt + already-relayed tokens after a crash)", ("mode",)),
    "replays": reg.counter(
        "synapseml_llm_journal_replays_total",
        "terminal results replayed from the front journal for a retried "
        "idempotency key — the dedup that makes a retried non-streaming "
        "request generate at most once").labels(),
    "hedges": reg.counter(
        "synapseml_llm_hedges_total",
        "hedged generation attempts fired after a stuck prefill, by "
        "arbitration outcome (won = the hedge produced the stream, "
        "lost = the primary recovered first)", ("outcome",)),
})


class _ClientGone(Exception):
    """The front->client socket died while relaying a journaled stream."""


class _JournalEntry:
    """One journaled generation: everything the RoutingFront needs to
    splice a migrated stream or re-create a crashed one on another worker
    without the client noticing. ``relayed`` is the next expected GLOBAL
    token index — worker chunks carry ``seq`` (the token's global index),
    so any chunk below ``relayed`` is a duplicate from a resume overlap
    and is dropped before it reaches the client."""

    __slots__ = ("key", "digest", "body", "client_stream", "relayed",
                 "emitted_ids", "uid", "worker", "done", "result", "status",
                 "mailbox", "deadline", "lock", "inflight", "winner")

    def __init__(self, key: str, digest: str, body: dict,
                 client_stream: bool, deadline: float | None):
        self.key = key
        self.digest = digest              # sha256 of the client body
        self.body = body                  # original client payload
        self.client_stream = client_stream
        self.relayed = 0
        self.emitted_ids: list[int] = []  # every token id relayed so far
        self.uid = None                   # origin engine uid (sampling
        #                                   streams fold on it)
        self.worker = None                # endpoint currently assigned
        self.done = False
        self.result = None                # terminal record, replayable
        self.status = 200
        self.mailbox = None               # migrated KV snapshot, if any
        self.deadline = deadline          # absolute monotonic, or None
        self.lock = threading.Lock()
        self.inflight = False
        self.winner = None                # hedge arbitration: attempt id


class _StreamJournal:
    """Bounded per-request journal keyed by idempotency key. DONE entries
    evict LRU-first past ``max_entries``; live entries are never evicted
    (evicting one would orphan a client mid-stream)."""

    def __init__(self, max_entries: int = 1024):
        self._entries: "collections.OrderedDict[str, _JournalEntry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._max = int(max_entries)

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: str) -> "_JournalEntry | None":
        with self._lock:
            return self._entries.get(key)

    def admit(self, key: str, digest: str, body: dict, client_stream: bool,
              deadline: float | None):
        """(entry, verdict) — verdict ``new`` starts a generation,
        ``replay`` returns the recorded terminal result (retried key, same
        prompt), ``conflict`` rejects a key that is still in flight (a
        concurrent duplicate must not race the original's stream)."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                if not e.done and e.inflight:
                    return e, "conflict"
                if e.digest == digest and e.done:
                    self._entries.move_to_end(key)
                    return e, "replay"
                # same key, different prompt (or a dead unfinished entry):
                # the reuse is a NEW request — replace the record
            e = _JournalEntry(key, digest, body, client_stream, deadline)
            self._entries[key] = e
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                victim = next((k for k, v in self._entries.items()
                               if v.done and k != key), None)
                if victim is None:
                    break
                del self._entries[victim]
            return e, "new"


def _register_journal_gauge(front, instance: str) -> None:
    """Pull-time ``synapseml_llm_journal_depth`` gauge (weakref'd like the
    breaker gauge: a collected front silently stops exporting)."""
    ref = weakref.ref(front)
    reg = obs.get_registry()

    def collect():
        o = ref()
        if o is None:
            reg.unregister_collector(collect)
            return
        j = o._journal
        if j is not None:
            yield obs.Sample(
                "synapseml_llm_journal_depth", {"instance": instance},
                float(j.depth()),
                help="journaled generations held by the routing front "
                     "(bounded; done entries evict LRU-first)")

    reg.register_collector(collect)


class _StreamWriter:
    """Relays worker chunks to ONE client with seq-dedup and hedge
    arbitration. Every delivery runs under the entry lock: the first
    attempt to land a chunk claims the stream (first-writer-wins); the
    losing attempt is told so and closes its worker connection. Dedup is
    by global token index, so interleaved writes from a resumed attempt
    overlapping a dying one still reach the client exactly once, in
    order."""

    def __init__(self, handler, entry: _JournalEntry):
        self._h = handler
        self.entry = entry
        self.began = False

    def _begin(self) -> None:
        h = self._h
        h.send_response(200)
        h.send_header("Content-Type", "application/x-ndjson")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()
        self.began = True

    def _write(self, obj) -> None:
        data = (json.dumps(obj) + "\n").encode()
        self._h.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self._h.wfile.flush()

    def deliver(self, chunk: dict, attempt_id: int) -> str:
        """'ok' | 'dup' | 'lost'; raises _ClientGone on a dead client."""
        e = self.entry
        with e.lock:
            if e.winner is None:
                e.winner = attempt_id
            elif e.winner != attempt_id:
                return "lost"
            if chunk.get("uid") is not None:
                e.uid = chunk["uid"]
            done = bool(chunk.get("done"))
            seq = chunk.get("seq")
            if not done and "token" in chunk:
                if seq is not None and seq < e.relayed:
                    return "dup"
                e.emitted_ids.append(chunk["token"])
                e.relayed = (seq + 1) if seq is not None else e.relayed + 1
            elif done:
                if e.done:
                    return "dup"
                e.done = True
                e.result = chunk
            if e.client_stream:
                if not self.began:
                    self._begin()
                try:
                    self._write(chunk)
                except OSError:
                    raise _ClientGone from None
            return "ok"

    def finish_stream(self) -> None:
        if not self.began:
            return
        try:
            self._h.wfile.write(b"0\r\n\r\n")
            self._h.wfile.flush()
        except OSError:
            pass


class RoutingFront:
    """One public port; round-robin forwarding to live workers over
    PERSISTENT (keep-alive) worker connections; ``GET /routes`` returns the
    live routing table as JSON so clients can switch to direct per-worker
    connections (serve-where-it-lands, the ``DistributedHTTPSource`` model
    where requests are served wherever they land).

    Reliability semantics (the reference's serve-where-it-lands plane never
    loses workers permanently, ``DistributedHTTPSource.scala:88-203``), built
    on per-worker ``core.resilience.CircuitBreaker``s:

    * connect failures AND timeouts trip the worker's breaker OPEN (the
      any-failure configuration: threshold 0, window 1); an open breaker is
      skipped for ``resurrect_after_s`` seconds, after which it moves to
      HALF-OPEN and the worker is probed again (time-based resurrection — a
      slow-but-alive worker is excluded only briefly, while a blackholed one
      stops stalling every rotation by ``timeout_s``); any successful reply
      closes the breaker immediately;
    * when every worker's breaker is open the least-recently-failed one is
      probed anyway (the front degrades to retrying, never to a permanent
      503);
    * with a ``registry``, the routing table refreshes from it on every
      request, so workers registered AFTER startup (restarts, scale-up) are
      routed to immediately; a static ``workers`` list is merged in (the
      registry entry wins on a (host, port) collision);
    * ``GET /stats`` reports the ``distributed_serving`` resilience counters
      (retries, breaker opens, deadline expiries, injected faults) plus the
      live per-worker breaker states.

    Deployment plane (``registry/deploy.py``): workers may register with a
    ``version``; ``set_traffic_split({"v1": 0.9, "v2": 0.1})`` routes each
    request to a version drawn by weight (canary), falling back to any live
    worker when the drawn version has none (a dying canary degrades to the
    stable fleet, never to a 503); ``set_shadow(version)`` duplicates
    requests to a worker of that version in the background, discards the
    response, and records latency/error deltas. Per-version request/latency
    /error series land in the PR-2 metrics registry; ``version_stats()``
    snapshots monotonic per-version counters for the auto-rollback
    controller. ``POST /admin/split`` applies a split/shadow over HTTP.
    """

    def __init__(self, workers: list[dict] | None = None, port: int = 0,
                 timeout_s: float = 60.0, registry: "WorkerRegistry" = None,
                 resurrect_after_s: float = 2.0,
                 max_inflight_shadows: int = 8,
                 coalesce_window_ms: float = 0.0,
                 coalesce_max_group: int = 64,
                 admission=None,
                 route_by_model: bool = False,
                 route_by_prefix: bool = False,
                 journal: bool = False,
                 journal_max_entries: int = 1024,
                 hedge_after_s: float | None = None,
                 max_stream_attempts: int = 4):
        if workers is None and registry is None:
            raise ValueError("RoutingFront needs workers and/or a registry")
        # survivable-LLM plane (opt in for LLM fleets): a bounded
        # per-request journal makes every generation resumable — worker
        # death mid-stream resubmits to a healthy worker (re-prefill over
        # prompt + relayed tokens), a live drain splices the migrated KV
        # snapshot in via /admin/migrate, retried idempotency keys replay
        # the recorded terminal instead of generating twice, and a stuck
        # prefill hedges to a second worker (first-writer-wins)
        self._journal = (_StreamJournal(journal_max_entries)
                         if journal else None)
        self._hedge_after_s = hedge_after_s
        self._max_stream_attempts = int(max_stream_attempts)
        # same-path coalescing toward bucket-sized worker batches (0 = off,
        # the latency-neutral default; enable for throughput-bound fleets)
        self._coalescer = (_RequestCoalescer(coalesce_window_ms / 1000.0,
                                             coalesce_max_group)
                           if coalesce_window_ms > 0 else None)
        self._static_workers = list(workers or [])
        self._registry = registry
        self._resurrect_after_s = resurrect_after_s
        self._breakers: dict[tuple, CircuitBreaker] = {}  # (host, port) ->
        self._rr = 0
        self._lock = threading.Lock()
        self._pool = _ConnPool(timeout_s)
        # deployment plane state: canary split, shadow target, per-version
        # accounting (all guarded by _deploy_lock; the split rng is seedable
        # for deterministic tests)
        self._deploy_lock = threading.Lock()
        self._split: dict[str, float] | None = None
        self._shadow: tuple[str, float] | None = None  # (version, fraction)
        self._split_rng = random.Random()
        self._version_stats: dict[str, _VersionStats] = {}
        self._shadow_sem = threading.Semaphore(max_inflight_shadows)
        # fleet plane: the admission controller (per-model token buckets,
        # priority classes, p99 shedding — fleet/admission.py) consulted
        # BEFORE any worker is picked, and model-segment routing: a
        # ``/m/<model>`` path prefers workers advertising that model
        # (rendezvous-ordered when none do, so multi-model residency
        # workers pack stably instead of thrashing their LRU)
        self._admission = admission
        self.route_by_model = bool(route_by_model)
        # prefix-affinity routing (LLM fleets with the engine prefix cache):
        # generation requests rendezvous-order workers by a hash of the
        # prompt HEAD, so requests sharing a system/RAG/few-shot prefix
        # pack onto the same worker and hit its cached KV pages instead of
        # spreading the prefix across the fleet. Composes UNDER model
        # affinity (a worker hosting the named model still wins).
        self.route_by_prefix = bool(route_by_prefix)
        # continual plane: a RequestLogger attached via set_request_logger
        # records every forwarded exchange AFTER the reply is written —
        # sampled + bounded (shed-before-delay), the flywheel's feedstock
        self._request_logger = None
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # client connections persist too

            def log_message(self, *a):
                pass

            def _reply(self, status: int, payload: bytes = b"",
                       extra: dict | None = None) -> None:
                self.send_response(status)
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                if payload:
                    self.wfile.write(payload)

            def _forward(self, method: str):
                # drain the body FIRST — replying with unread body bytes on
                # a keep-alive connection desyncs the next request
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else None
                if self.path == "/routes":  # served here, not forwarded
                    table = json.dumps(front._table()).encode()
                    self._reply(200, table,
                                {"Content-Type": "application/json"})
                    return
                if self.path == "/stats":  # resilience counters + breakers
                    adm = front._admission
                    stats = json.dumps({
                        "resilience": resilience_measures(
                            "distributed_serving").to_dict(),
                        "breakers": front.breaker_states(),
                        "traffic_split": front.traffic_split(),
                        "shadow": front.shadow(),
                        "versions": front.version_stats(),
                        "admission": (adm.stats()
                                      if adm is not None else None)}).encode()
                    self._reply(200, stats,
                                {"Content-Type": "application/json"})
                    return
                if self.path == "/admin/split":  # deployment plane over HTTP
                    status, reply = front._admin_split(method, body)
                    self._reply(status, json.dumps(reply).encode(),
                                {"Content-Type": "application/json"})
                    return
                if self.path == "/admin/migrate":  # drain handoff mailbox
                    status, reply = front._admin_migrate(body)
                    self._reply(status, json.dumps(reply).encode(),
                                {"Content-Type": "application/json"})
                    return
                if method == "POST" and self.path.startswith("/retrieval/"):
                    # retrieval plane: shard fan-out + top-k merge AT the
                    # front (a /m/<index> request would land on ONE holder;
                    # /retrieval/<index> queries every shard's holder)
                    status, reply, hdrs = front._retrieval_fanout(
                        self.path, body)
                    hdrs["Content-Type"] = "application/json"
                    self._reply(status, json.dumps(reply).encode(), hdrs)
                    return
                # GET-gated like io/serving.py: a POST to a pipeline path
                # that happens to be named /metrics still forwards
                if method == "GET" and self.path == "/metrics":
                    payload, ctype = obs.prometheus_exposition()
                    self._reply(200, payload, {"Content-Type": ctype})
                    return
                if method == "GET" and self.path == "/trace":
                    payload = json.dumps(
                        obs.get_tracer().spans_as_dicts()).encode()
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                tracer = obs.get_tracer()
                parent = obs.extract_context(self.headers)
                with tracer.span("route.request",
                                 {"path": self.path, "method": method},
                                 parent=parent):
                    self._route(method, body)

            def _route(self, method: str, body) -> None:
                rm = _ROUTE_METRICS.get()
                model = _model_of_path(self.path)
                label = model or "unversioned"
                priority = _priority_of(self.headers)
                adm = front._admission
                if adm is not None:
                    decision = adm.admit(model or "default", priority)
                    if not decision.admitted:
                        # shed AT the front: a terminal 429 + Retry-After,
                        # before the request costs a worker queue slot
                        front._record_shed(label, priority)
                        payload = json.dumps(
                            {"error": "admission shed",
                             "reason": decision.reason}).encode()
                        self._reply(decision.status or 429, payload, {
                            "Content-Type": "application/json",
                            "Retry-After": str(max(
                                1, int(-(-decision.retry_after_s // 1))))})
                        return
                front._record_inflight(label, priority, +1)
                try:
                    self._route_admitted(method, body, rm, model, priority)
                finally:
                    front._record_inflight(label, priority, -1)

            def _route_admitted(self, method: str, body, rm,
                                model, priority) -> None:
                if front._journal is not None and method == "POST" \
                        and not self.path.startswith("/admin"):
                    # survivable-LLM plane: generation requests relay
                    # through the journal (chunk-level dedup + resubmit);
                    # everything else falls through to plain forwarding
                    if front._journal_route(self, body, rm, model):
                        return
                hdrs = {k: v for k, v in self.headers.items()
                        if k.lower() not in ("host", "connection",
                                             "traceparent")}
                # stitch the forwarded hop to the route.request span: the
                # worker's serving.request span becomes its child
                obs.get_tracer().inject(hdrs)
                if front._coalescer is not None and method == "POST":
                    group = front._coalescer.join(self.path)
                    # t0 starts AFTER the coalesce wait: pick_ms measures
                    # pure worker-pick overhead, not the batching window
                    t0 = time.perf_counter()
                    candidates, desperate = front._group_candidates(group)
                else:
                    t0 = time.perf_counter()
                    sig = (_prefix_sig(body) if front.route_by_prefix
                           and method == "POST" else None)
                    candidates, desperate = front._candidates(
                        model=model, prefix_sig=sig)
                picked = False
                pending_retry = False  # set by a REAL failure only: the
                # next attempt after one counts as a retry; a drain skip
                # does not arm it, so routine scale-down never shows up
                # in the retry counters
                for w in candidates:
                    key = (w.get("host"), w.get("port"))
                    breaker = front._breaker(key)
                    if not desperate and not breaker.allow():
                        continue  # raced shut since the candidate list
                    if pending_retry:
                        resilience_measures("distributed_serving").count("retry")
                        rm["retries"].inc()
                        pending_retry = False
                    if not picked:
                        # worker pick = table refresh + breaker filtering +
                        # rotation, before the first byte is forwarded
                        rm["pick_ms"].observe(
                            (time.perf_counter() - t0) * 1e3)
                        picked = True
                    endpoint = f"{key[0]}:{key[1]}"
                    version = _version_of(w)
                    fwd0 = time.perf_counter()
                    try:
                        got = _pooled_request(front._pool, key, method,
                                              self.path, body, hdrs)
                    except (http.client.HTTPException, OSError):
                        breaker.record_failure()
                        front._pool.clear(key)
                        rm["worker_failures"].inc(worker=endpoint)
                        front._record_version(version, ok=False)
                        rm["version_requests"].inc(version=version,
                                                   status="error")
                        pending_retry = True
                        continue
                    status, payload = got
                    breaker.record_success()  # proven alive
                    if status == 503 \
                            and payload == b'{"error": "worker draining"}':
                        # a DRAINING worker is healthy but leaving (fleet
                        # plane /admin/drain): reroute to the rest of the
                        # fleet instead of surfacing its refusal — scale-
                        # down stays invisible to clients. Not a breaker
                        # failure AND not a retry in the resilience
                        # counters (routine scale-down must not read as
                        # worker failures on a dashboard); the EXACT-body
                        # match cannot false-positive on an application
                        # 503 that merely mentions the phrase. The
                        # registry table drops the worker when its drain
                        # completes.
                        continue
                    elapsed_ms = (time.perf_counter() - fwd0) * 1e3
                    rm["request_ms"].observe(elapsed_ms, worker=endpoint)
                    front._record_version(version, ok=status < 500,
                                          latency_ms=elapsed_ms)
                    front._observe_admission(model, elapsed_ms,
                                             ok=status < 500)
                    rm["version_requests"].inc(
                        version=version,
                        status=f"{status // 100}xx")
                    rm["version_ms"].observe(elapsed_ms, version=version)
                    self._reply(status, payload,
                                {"X-Served-By": str(w.get("pid", "")),
                                 "X-Served-Version": version})
                    logger = front._request_logger
                    if logger is not None:
                        # after _reply: the client already has its bytes —
                        # a sampled log insert cannot delay the exchange
                        logger.log(method=method, path=self.path,
                                   body=body or b"", reply=payload,
                                   status=status, latency_ms=elapsed_ms,
                                   version=version)
                    front._maybe_shadow(method, self.path, body, hdrs,
                                        version, elapsed_ms)
                    return
                rm["unroutable"].inc()
                self._reply(503)

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

        self._server = NoDelayHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        # ONE instance id per front, shared by every collector it owns —
        # dashboards correlate its series by this label
        self._instance = str(next(_BREAKER_OWNER_IDS))
        _register_breaker_gauge(self, plane="front",
                                instance=self._instance)
        _register_split_gauge(self, self._instance)
        if self._journal is not None:
            _register_journal_gauge(self, self._instance)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _table(self) -> list[dict]:
        if self._registry is None:
            return self._static_workers
        reg = self._registry.workers()
        seen = {(w.get("host"), w.get("port")) for w in reg}
        return reg + [w for w in self._static_workers
                      if (w.get("host"), w.get("port")) not in seen]

    def _breaker(self, key: tuple) -> CircuitBreaker:
        """Per-worker breaker, created on first sight with the any-failure
        configuration (one connect failure opens; the half-open probe fires
        after ``resurrect_after_s`` — the old resurrection timer)."""
        with self._lock:
            breaker = self._breakers.get(key)
            if breaker is None:
                breaker = CircuitBreaker(
                    failure_rate_threshold=0.0, window=1, min_samples=1,
                    probe_interval_s=self._resurrect_after_s,
                    measures=resilience_measures("distributed_serving"),
                    name=f"{key[0]}:{key[1]}")
                self._breakers[key] = breaker
            return breaker

    def breaker_states(self) -> dict:
        """(host:port -> breaker state) snapshot, for ``/stats``."""
        with self._lock:
            return {f"{h}:{p}": br.state
                    for (h, p), br in self._breakers.items()}

    def _candidates(self, model: str | None = None,
                    prefix_sig: str | None = None) -> tuple[list[dict], bool]:
        """(routing order for one request, desperate): breaker-available
        (closed or probe-due) workers round-robin rotated; if none, the
        least-recently-failed worker as a desperation probe. With a traffic
        split active, a version is drawn by weight and its workers are
        ordered FIRST; every other live worker follows as fallback — a
        canary whose workers all failed degrades to the stable fleet
        instead of dropping the request.

        ``model`` (a ``/m/<model>`` path segment, fleet plane) adds model
        affinity ON TOP: workers advertising the model order first; when
        NONE advertise it and ``route_by_model`` is set, candidates order
        by a stable rendezvous hash of (model, endpoint) instead of the
        rotation — every request for one model lands on the same worker
        first, so multi-model residency workers pack a consistent subset
        instead of thrashing their LRU across the fleet.

        ``prefix_sig`` (``route_by_prefix`` fleets, the engine prefix-cache
        plane) rendezvous-orders workers by hash of (sig, endpoint) BELOW
        the model/version preferences: requests sharing a prompt head land
        on the same worker first, so its prefix cache accumulates hits
        instead of every worker cold-prefilling the same system prompt."""
        full_table = self._table()
        # breaker pruning keys off the FULL table — a model-filtered view
        # must not evict other models' workers' breakers
        live_keys = {(w.get("host"), w.get("port")) for w in full_table}
        with self._lock:
            # prune breakers for departed workers (respawns land on fresh
            # ephemeral ports; without this the map grows forever)
            if len(self._breakers) > len(live_keys):
                self._breakers = {k: b for k, b in self._breakers.items()
                                  if k in live_keys}
        table = full_table
        if model is not None:
            # a request that NAMES a model must never be answered by a
            # different model's pipeline: drop ineligible workers outright
            # (no eligible worker = honest 503, not a wrong 200)
            labeled = any(_model_aware(w) for w in full_table)
            table = [w for w in full_table
                     if _eligible_for_model(w, model, labeled)]
        if not table:
            return [], False
        alive = [w for w in table
                 if self._breaker((w.get("host"), w.get("port"))).available()]
        with self._lock:
            self._rr += 1
            rot = self._rr % max(len(alive), 1)
        if alive:
            ordered = alive[rot:] + alive[:rot]
            if prefix_sig is not None and self.route_by_prefix:
                # applied FIRST so the stable version/model partitions
                # below preserve the prefix order within each tier —
                # affinity composes as model > version > prefix
                def prank(w):
                    key = f"{prefix_sig}|{w.get('host')}:{w.get('port')}"
                    return hashlib.md5(key.encode()).hexdigest()

                ordered = sorted(ordered, key=prank)
            chosen = self._draw_version()
            if chosen is not None:
                preferred = [w for w in ordered
                             if _version_of(w) == chosen]
                ordered = preferred + [w for w in ordered
                                       if _version_of(w) != chosen]
            if model is not None:
                hosting = [w for w in ordered if _hosts_model(w, model)]
                if hosting:
                    ordered = hosting + [w for w in ordered
                                         if not _hosts_model(w, model)]
                elif self.route_by_model:
                    # rendezvous: stable per-model order (hash, not the
                    # rotation) so on-demand residency stays sticky
                    def rank(w):
                        key = f"{model}|{w.get('host')}:{w.get('port')}"
                        return hashlib.md5(key.encode()).hexdigest()

                    ordered = sorted(ordered, key=rank)
            return ordered, False
        # everything recently failed: probe the stalest failure anyway
        stalest = min(table, key=lambda w: self._breaker(
            (w.get("host"), w.get("port"))).last_failure_at or 0.0)
        return [stalest], True

    def _group_candidates(self, group: "_CoalesceGroup"):
        """One candidate ordering per coalesced group — every member
        forwards to the same worker first, so the worker's serve loop sees
        the whole group as one micro-batch. The first member to arrive here
        also accounts the group's occupancy/padding series."""
        with group.lock:
            if group.candidates is None:
                group.candidates, group.desperate = self._candidates(
                    model=_model_of_path(group.path))
                rm = _ROUTE_METRICS.get()
                version = (_version_of(group.candidates[0])
                           if group.candidates else "unversioned")
                n = group.count
                bucket = cb.default_bucketer().bucket_for(n)
                rm["bucket_occupancy"].observe(n, version=version)
                rm["real_rows"].inc(n, version=version)
                rm["padded_rows"].inc(bucket - n, version=version)
            return group.candidates, group.desperate

    # -- retrieval plane: shard fan-out + global top-k merge ---------------
    def _retrieval_fanout(self, path: str, body) -> tuple[int, dict, dict]:
        """``POST /retrieval/<index>`` with ``{"queries": [[...], ...],
        "k": 10}``: fan the query batch to the workers ADVERTISING each of
        the index's shards (registration ``shards`` lists), score
        per-shard top-k in parallel over the pooled keep-alive
        connections, and merge into global top-k at the front.

        Degradation contract: shards with no reachable holder are SKIPPED
        and named in the ``X-Retrieval-Partial`` response header — a
        partial result with explicit provenance, never a 500 (recall-proxy
        coverage lands in ``synapseml_retrieval_shard_coverage``). A
        worker failure mid-fan-out trips its breaker (same any-failure
        semantics as routed traffic) and retries its shards once on
        another advertising holder before degrading."""
        from ..retrieval.metrics import retrieval_metrics

        index = path.split("?", 1)[0].split("/")[2] if len(
            path.split("/")) >= 3 else ""
        if not index:
            return 404, {"error": "path must be /retrieval/<index>"}, {}
        try:
            req = json.loads(body) if body else {}
        except (ValueError, TypeError):
            return 400, {"error": "body must be JSON"}, {}
        queries = req.get("queries")
        if queries is None and "query" in req:
            queries = [req["query"]]
        if not queries:
            return 400, {"error": "body needs 'queries' or 'query'"}, {}
        k = int(req.get("k") or 10)
        holders = [w for w in self._table()
                   if _hosts_model(w, index) and w.get("shards")]
        if not holders:
            return 503, {"error": f"no workers advertise index "
                                  f"{index!r} shards"}, {}
        # the EXPECTED shard set is the union of advertisements (a downed
        # worker's registration persists until deregister/reap, so its
        # shards stay expected — that is what makes the result honestly
        # partial instead of silently narrower)
        expected = sorted({s for w in holders for s in w["shards"]})
        avail = [w for w in holders
                 if self._breaker((w.get("host"), w.get("port"))).available()]
        plan: dict[tuple, list[str]] = {}
        by_key = {}
        missing = []
        for shard in expected:
            cands = [w for w in avail if shard in w["shards"]]
            if not cands:
                missing.append(shard)
                continue
            w = min(cands, key=lambda c: len(
                plan.get((c.get("host"), c.get("port")), ())))
            key = (w.get("host"), w.get("port"))
            plan.setdefault(key, []).append(shard)
            by_key[key] = w
        t0 = time.perf_counter()
        merged: list[list] = [[] for _ in queries]
        scored: list[str] = []
        lock = threading.Lock()

        def _ask(key, shard_names) -> list[str]:
            """One worker's sub-query; returns the shards it FAILED."""
            breaker = self._breaker(key)
            payload = json.dumps({"queries": queries, "k": k,
                                  "shards": shard_names}).encode()
            try:
                status, raw = _pooled_request(
                    self._pool, key, "POST", f"/m/{index}", payload,
                    {"Content-Type": "application/json"})
                if status != 200:
                    raise ConnectionError(f"worker {key} -> {status}")
                reply = json.loads(raw)
                matches = reply["matches"]
            except Exception:  # noqa: BLE001 — any failure = these shards
                breaker.record_failure()
                return list(shard_names)
            breaker.record_success()
            with lock:
                scored.extend(shard_names)
                for i, row in enumerate(matches):
                    merged[i].extend(row)
            return []

        def _fan(assignments) -> list[str]:
            failed: list[list[str]] = [[] for _ in assignments]

            def run(i, key, names):
                failed[i] = _ask(key, names)

            threads = [threading.Thread(target=run, args=(i, key, names))
                       for i, (key, names) in enumerate(assignments)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return [s for f in failed for s in f]

        lost = _fan(list(plan.items()))
        if lost:
            # one failover round: reassign a failed worker's shards to any
            # OTHER still-available advertising holder
            retry: dict[tuple, list[str]] = {}
            still = []
            for shard in lost:
                cands = [w for w in holders
                         if shard in w["shards"]
                         and self._breaker((w.get("host"),
                                            w.get("port"))).available()]
                if not cands:
                    still.append(shard)
                    continue
                w = min(cands, key=lambda c: len(
                    retry.get((c.get("host"), c.get("port")), ())))
                retry.setdefault((w.get("host"), w.get("port")),
                                 []).append(shard)
            still += _fan(list(retry.items()))
            missing += still
        for i, row in enumerate(merged):
            row.sort(key=lambda m: (m.get("distance", 0.0), m.get("id", 0)))
            merged[i] = row[:k]
        missing = sorted(set(missing))
        m = retrieval_metrics()
        m["merge_ms"].observe((time.perf_counter() - t0) * 1000.0,
                              index=index)
        m["coverage"].observe(
            len(set(scored)) / max(len(expected), 1), index=index)
        hdrs = {}
        if missing:
            m["partial"].inc(index=index)
            hdrs["X-Retrieval-Partial"] = ",".join(missing)
        reply = {"matches": merged, "k": k, "shards": sorted(set(scored)),
                 "missing": missing}
        return 200, reply, hdrs

    # -- deployment plane: canary splits, shadow traffic, version stats ----
    def set_traffic_split(self, split: dict[str, float] | None) -> None:
        """Weighted canary split (version -> weight), e.g. ``{"v1": 0.95,
        "v2": 0.05}``. Weights are normalized; ``None`` restores plain
        round-robin."""
        if split:
            total = sum(float(v) for v in split.values())
            if total <= 0:
                raise ValueError(f"split weights must sum > 0: {split}")
            split = {str(k): float(v) / total for k, v in split.items()}
        else:
            split = None
        with self._deploy_lock:
            self._split = split

    def traffic_split(self) -> dict[str, float] | None:
        with self._deploy_lock:
            return dict(self._split) if self._split else None

    def set_shadow(self, version: str | None,
                   fraction: float = 1.0) -> None:
        """Duplicate ``fraction`` of successfully-served requests to a
        worker of ``version``, discarding the response and recording
        latency/error deltas (``synapseml_route_shadow_*``). ``None``
        disables shadowing."""
        with self._deploy_lock:
            self._shadow = (None if version is None
                            else (str(version), float(fraction)))

    def shadow(self) -> dict | None:
        with self._deploy_lock:
            if self._shadow is None:
                return None
            return {"version": self._shadow[0],
                    "fraction": self._shadow[1]}

    def clear_shadow(self) -> None:
        self.set_shadow(None)

    def version_stats(self) -> dict[str, dict]:
        """Monotonic per-version outcome counters + latency percentiles
        (the rollback controller's input; also exported on ``/stats``)."""
        with self._deploy_lock:
            return {v: s.snapshot()
                    for v, s in self._version_stats.items()}

    def _draw_version(self) -> str | None:
        with self._deploy_lock:
            if not self._split:
                return None
            split = dict(self._split)
            r = self._split_rng.random()
        acc = 0.0
        chosen = None
        for version, weight in split.items():
            acc += weight
            chosen = version
            if r < acc:
                break
        return chosen

    # -- fleet plane: admission control + per-priority accounting ----------
    def set_request_logger(self, logger) -> None:
        """Attach/detach (None) a ``continual.RequestLogger``: every
        forwarded request/response pair is offered to it post-reply."""
        self._request_logger = logger

    def request_logger(self):
        return self._request_logger

    def set_admission(self, controller) -> None:
        """Install/replace/clear (``None``) the admission controller
        (:class:`~synapseml_tpu.fleet.admission.AdmissionController`)
        consulted before every routed request."""
        self._admission = controller

    def admission(self):
        return self._admission

    # per-label stats entries are created on demand and never evicted, and
    # the /m/<model> label is CLIENT-controlled — without a cap, a scanner
    # spraying random model paths would grow _version_stats (and /stats
    # output) forever on a long-lived front
    _MAX_TRACKED_LABELS = 512

    def _stats_for(self, label: str, trusted: bool = False) -> _VersionStats:
        """Get-or-create a label's stats. ``trusted`` labels (worker
        registrations' VERSION labels — server-side data the canary
        rollback controller keys on) always get their own entry; untrusted
        labels (client-derived /m/<model> path segments) overflow into
        ``"other"`` past the cap, so a path scanner can fill the cap
        without ever blinding ``version_stats()[canary]``."""
        stats = self._version_stats.get(label)
        if stats is None:
            if not trusted \
                    and len(self._version_stats) >= self._MAX_TRACKED_LABELS \
                    and "other" != label:
                return self._stats_for("other")
            stats = self._version_stats[label] = _VersionStats()
        return stats

    def _record_shed(self, label: str, priority: str) -> None:
        with self._deploy_lock:
            stats = self._stats_for(label)
            stats.shed[priority] = stats.shed.get(priority, 0) + 1

    def _record_inflight(self, label: str, priority: str,
                         delta: int) -> None:
        with self._deploy_lock:
            stats = self._stats_for(label)
            stats.inflight[priority] = max(
                stats.inflight.get(priority, 0) + delta, 0)

    def _observe_admission(self, model: str | None, latency_ms: float,
                           ok: bool) -> None:
        if self._admission is not None:
            self._admission.observe(model or "default", latency_ms, ok=ok)

    def _record_version(self, version: str, ok: bool,
                        latency_ms: float | None = None,
                        shadow: bool = False) -> None:
        with self._deploy_lock:
            stats = self._stats_for(version, trusted=True)
            if shadow:
                if ok:
                    stats.shadow_ok += 1
                else:
                    stats.shadow_err += 1
            elif ok:
                stats.ok += 1
            else:
                stats.err += 1
            if latency_ms is not None and not shadow:
                stats.latencies_ms.append(latency_ms)

    def _maybe_shadow(self, method: str, path: str, body, headers: dict,
                      primary_version: str, primary_ms: float) -> None:
        """Fire-and-forget duplicate to the shadow version (post-reply, so
        the primary response is never delayed). Bounded by the in-flight
        semaphore — saturation drops the duplicate, never queues it."""
        with self._deploy_lock:
            shadow = self._shadow
        if shadow is None:
            return
        version, fraction = shadow
        if version == primary_version:
            return
        if fraction < 1.0 and self._split_rng.random() >= fraction:
            return
        targets = [w for w in self._table() if _version_of(w) == version]
        if not targets or not self._shadow_sem.acquire(blocking=False):
            return
        target = targets[self._rr % len(targets)]
        key = (target.get("host"), target.get("port"))
        rm = _ROUTE_METRICS.get()
        hdrs = {k: v for k, v in headers.items()
                if k.lower() != "traceparent"}

        def run():
            t0 = time.perf_counter()
            try:
                status, _payload = _pooled_request(self._pool, key, method,
                                                   path, body, hdrs)
            except (http.client.HTTPException, OSError):
                self._pool.clear(key)
                self._record_version(version, ok=False, shadow=True)
                rm["shadow_requests"].inc(version=version, status="error")
            else:
                ms = (time.perf_counter() - t0) * 1e3
                # a 5xx reply is a shadow FAILURE (the primary path counts
                # status>=500 as err too) — a canary that errors under
                # shadow must not look healthy to the rollout decision
                ok = status < 500
                self._record_version(version, ok=ok, shadow=True)
                rm["shadow_requests"].inc(
                    version=version,
                    status="ok" if ok else f"{status // 100}xx")
                rm["shadow_delta_ms"].observe(ms - primary_ms,
                                              version=version)
            finally:
                self._shadow_sem.release()

        threading.Thread(target=run, daemon=True).start()

    # -- survivable-LLM plane: journaled streams, migration, hedging -------
    def _admin_migrate(self, body: bytes) -> tuple[int, dict]:
        """Drain-handoff mailbox: a draining worker POSTs ``{"key":
        <journal key>, "snapshot": <exported sequence>}`` here; the relay
        loop for that key picks the snapshot up when the worker's
        ``__migrated__`` marker arrives and resubmits it to a healthy
        worker. A non-2xx tells the worker the handoff failed — it
        re-imports the snapshot locally instead of dropping the request."""
        if self._journal is None:
            return 404, {"error": "journal disabled on this front"}
        try:
            payload = json.loads(body or b"{}")
            key = payload["key"]
            snap = payload["snapshot"]
            if not isinstance(key, str) or not isinstance(snap, dict):
                raise ValueError("key must be a string, snapshot an object")
        except (ValueError, KeyError, TypeError) as e:
            return 400, {"error": str(e)}
        entry = self._journal.get(key)
        if entry is None or entry.done:
            return 404, {"error": f"no live journal entry for {key!r}"}
        with entry.lock:
            entry.mailbox = snap
        return 200, {"ok": True}

    def _journal_route(self, handler, body, rm, model) -> bool:
        """Journaled relay for generation requests; False = not a
        generation body, fall through to plain forwarding."""
        try:
            payload = json.loads(body or b"null")
        except ValueError:
            return False
        if not isinstance(payload, dict) or \
                ("prompt" not in payload and "input_ids" not in payload):
            return False
        jm = _JOURNAL_METRICS.get()
        # idempotency key: client-supplied (retry-safe) or generated
        key = handler.headers.get("X-Request-Key") or uuid.uuid4().hex
        digest = hashlib.sha256(body or b"").hexdigest()
        deadline = None
        dl = handler.headers.get("X-Deadline-Ms")
        if dl:
            try:
                deadline = time.monotonic() + float(dl) / 1e3
            except ValueError:
                pass
        entry, verdict = self._journal.admit(
            key, digest, payload, bool(payload.get("stream")), deadline)
        if verdict == "replay":
            jm["replays"].inc()
            res = entry.result if entry.result is not None \
                else {"error": "no terminal result recorded"}
            handler._reply(entry.status, json.dumps(res).encode(),
                           {"Content-Type": "application/json",
                            "X-Journal-Replay": "1"})
            return True
        if verdict == "conflict":
            handler._reply(409, json.dumps(
                {"error": "request key already in flight",
                 "key": key}).encode(),
                {"Content-Type": "application/json"})
            return True
        entry.inflight = True
        try:
            self._journal_run(handler, entry, rm, model)
        finally:
            entry.inflight = False
        return True

    def _journal_run(self, handler, entry, rm, model) -> None:
        """Attempt loop for one journaled generation: pick a worker,
        relay its stream, and on failure/migration resubmit until the
        terminal record lands or the attempt budget runs out."""
        writer = _StreamWriter(handler, entry)
        hdrs = {k: v for k, v in handler.headers.items()
                if k.lower() not in ("host", "connection", "traceparent",
                                     "content-length", "x-request-key",
                                     "x-deadline-ms")}
        obs.get_tracer().inject(hdrs)
        sig = _prefix_sig(entry.body) if self.route_by_prefix else None
        attempts = 0
        attempt_seq = 0
        tried: set[str] = set()
        attempt_log: list[str] = []
        while attempts < self._max_stream_attempts:
            if entry.deadline is not None \
                    and time.monotonic() >= entry.deadline:
                self._journal_terminal(handler, writer, entry, {
                    "error": "deadline exceeded", "done": True,
                    "finish_reason": "deadline"}, status=504)
                return
            candidates, _ = self._candidates(model=model, prefix_sig=sig)
            # don't hand the resubmit straight back to the endpoint that
            # just failed — unless it is the only one left
            fresh = [w for w in candidates
                     if f"{w.get('host')}:{w.get('port')}" not in tried]
            pick_from = fresh or candidates
            if not pick_from:
                break
            attempts += 1
            w = pick_from[0]
            tried.add(f"{w.get('host')}:{w.get('port')}")
            outcome = self._run_hedged(handler.path, w, pick_from[1:],
                                       entry, writer, hdrs, attempt_seq)
            attempt_seq += 2  # primary + potential hedge ids
            tag = outcome[0]
            attempt_log.append(
                f"{w.get('host')}:{w.get('port')}={':'.join(str(p) for p in outcome)}")
            if tag == "done":
                self._journal_finish(handler, writer, entry)
                return
            if tag == "migrated":
                # the worker posts the snapshot to /admin/migrate BEFORE
                # the marker, so the mailbox is nearly always filled
                # already; the grace wait covers reordering
                wait_until = time.monotonic() + 5.0
                while time.monotonic() < wait_until:
                    with entry.lock:
                        if entry.mailbox is not None:
                            break
                    time.sleep(0.01)
                # the drained worker's attempt has returned (it produced
                # the marker): release arbitration, or the import attempt's
                # chunks would all be rejected as hedge losers
                with entry.lock:
                    entry.winner = None
                # the drained worker stays in `tried`: it self-rejects new
                # work with its drain 503 anyway, so prefer the others
                continue
            if tag == "status":
                _, status, payload = outcome
                try:
                    rec = json.loads(payload or b"null")
                except ValueError:
                    rec = {"error": payload.decode("utf-8", "replace")}
                if not isinstance(rec, dict):
                    rec = {"result": rec}
                rec.setdefault("done", True)
                self._journal_terminal(handler, writer, entry, rec, status)
                return
            if tag == "client_gone":
                with entry.lock:
                    entry.done = True
                    entry.result = {"error": "client disconnected",
                                    "done": True}
                return
            # 'failed' / 'draining': release arbitration so the next
            # attempt may claim the stream, then rotate on
            with entry.lock:
                entry.winner = None
            if tag == "failed":
                resilience_measures("distributed_serving").count("retry")
                rm["retries"].inc()
        self._journal_terminal(handler, writer, entry, {
            "error": "no worker could complete the generation",
            "attempts": attempt_log, "done": True}, status=503)

    def _run_hedged(self, path, primary, alternates, entry, writer, hdrs,
                    base_id):
        """One attempt, hedged: the primary streams in a thread; if no
        first chunk lands within ``hedge_after_s`` (stuck prefill) and an
        alternate worker exists, a second attempt races it — the first to
        deliver a chunk wins the client stream, the loser is closed."""
        outq: "queue.Queue" = queue.Queue()
        first_evt = threading.Event()

        def run(w, aid, evt):
            out = self._stream_attempt(path, w, entry, writer, aid, hdrs,
                                       evt)
            if evt is not None:
                evt.set()  # a fast failure must not stall the hedge gate
            outq.put(out)

        threading.Thread(target=run, args=(primary, base_id, first_evt),
                         daemon=True).start()
        hedged = False
        if self._hedge_after_s is not None and alternates:
            first_evt.wait(self._hedge_after_s)
            if not first_evt.is_set():
                hedged = True
                threading.Thread(
                    target=run, args=(alternates[0], base_id + 1, None),
                    daemon=True).start()
        results = []
        terminal = None
        while len(results) < (2 if hedged else 1):
            out = outq.get()
            results.append(out)
            if out[0] in ("done", "migrated", "status", "client_gone"):
                terminal = out
                break
        if hedged:
            with entry.lock:
                win = entry.winner
            _JOURNAL_METRICS.get()["hedges"].inc(
                outcome="won" if win == base_id + 1 else "lost")
        if terminal is not None:
            return terminal
        for out in results:
            if out[0] == "failed":
                return out
        return results[0]

    def _stream_attempt(self, path, w, entry, writer, attempt_id, hdrs,
                        first_evt):
        """Stream one worker's attempt at a journaled generation, relaying
        chunks through ``writer``. Returns ('done',) | ('migrated',) |
        ('draining',) | ('status', code, payload) | ('failed', err) |
        ('lost',) | ('client_gone',)."""
        key = (w.get("host"), w.get("port"))
        endpoint = f"{key[0]}:{key[1]}"
        breaker = self._breaker(key)
        rm = _ROUTE_METRICS.get()
        jm = _JOURNAL_METRICS.get()
        with entry.lock:
            snap = entry.mailbox
            entry.mailbox = None
            emitted = list(entry.emitted_ids)
            uid = entry.uid
        if snap is not None:
            # migrated KV pages: splice the sequence in wholesale
            body_obj = {"__import__": snap}
            jm["resubmits"].inc(mode="import")
        elif emitted or uid is not None:
            # crash path: deterministic re-prefill over prompt + relayed
            # tokens, keeping the origin uid so sampling stays identical
            body_obj = {"__resume__": {"body": entry.body,
                                       "emitted_ids": emitted,
                                       "uid": uid}}
            jm["resubmits"].inc(mode="resume")
        else:
            body_obj = dict(entry.body)
            body_obj["stream"] = True  # the front owns client framing
        send_hdrs = dict(hdrs)
        send_hdrs["X-Request-Key"] = entry.key
        if entry.deadline is not None:
            left_ms = (entry.deadline - time.monotonic()) * 1e3
            if left_ms <= 0:
                return ("failed", "deadline expired")
            send_hdrs["X-Deadline-Ms"] = str(max(int(left_ms), 1))
        conn = None
        accepted = False  # worker took the body: the snapshot is spent
        try:
            try:
                conn, _fresh = self._pool.get(key)  # fault hook fires here
                conn.request("POST", path,
                             body=json.dumps(body_obj).encode(),
                             headers=send_hdrs)
                resp = conn.getresponse()
            except (http.client.HTTPException, OSError) as e:
                breaker.record_failure()
                self._pool.clear(key)
                rm["worker_failures"].inc(worker=endpoint)
                return ("failed", str(e))
            if resp.status != 200:
                payload = resp.read()
                breaker.record_success()  # it answered: alive
                if resp.status == 503 \
                        and payload == b'{"error": "worker draining"}':
                    return ("draining",)
                return ("status", resp.status, payload)
            accepted = True
            breaker.record_success()
            with entry.lock:
                entry.worker = endpoint
            while True:
                try:
                    line = resp.readline()
                except (http.client.HTTPException, OSError,
                        ValueError) as e:
                    breaker.record_failure()
                    self._pool.clear(key)
                    rm["worker_failures"].inc(worker=endpoint)
                    return ("failed", f"stream broke: {e}")
                if not line:
                    # ended without a terminal record: the worker died
                    # between chunks
                    breaker.record_failure()
                    rm["worker_failures"].inc(worker=endpoint)
                    return ("failed", "stream ended without terminal")
                line = line.strip()
                if not line:
                    continue
                try:
                    chunk = json.loads(line)
                except ValueError:
                    continue
                if first_evt is not None:
                    first_evt.set()
                if isinstance(chunk, dict) and chunk.get("__migrated__"):
                    return ("migrated",)
                if isinstance(chunk, dict) and "error" in chunk \
                        and "token" not in chunk:
                    # worker-side terminal error (hot swap, engine
                    # failure): resubmittable — the journal can rebuild
                    # the sequence elsewhere
                    return ("failed", str(chunk.get("error")))
                try:
                    verdict = writer.deliver(chunk, attempt_id)
                except _ClientGone:
                    return ("client_gone",)
                if verdict == "lost":
                    return ("lost",)
                if isinstance(chunk, dict) and chunk.get("done"):
                    return ("done",)
        finally:
            if snap is not None and not accepted:
                # the worker never took the migrated snapshot (refused /
                # unreachable): put it back so the NEXT attempt can still
                # splice the KV pages instead of re-prefilling
                with entry.lock:
                    if entry.mailbox is None:
                        entry.mailbox = snap
            if conn is not None:
                conn.close()

    def _journal_finish(self, handler, writer, entry) -> None:
        with entry.lock:
            res = entry.result if entry.result is not None else {}
            if isinstance(res, dict) \
                    and res.get("finish_reason") == "deadline":
                entry.status = 504
            status = entry.status
        if entry.client_stream:
            writer.finish_stream()  # terminal chunk already relayed
        else:
            handler._reply(status, json.dumps(res).encode(),
                           {"Content-Type": "application/json"})

    def _journal_terminal(self, handler, writer, entry, record: dict,
                          status: int) -> None:
        """Front-originated terminal (deadline, attempt exhaustion): the
        client ALWAYS gets a terminal reply — an error chunk + end on a
        begun stream, a plain status reply otherwise."""
        with entry.lock:
            entry.done = True
            entry.result = record
            entry.status = status
        if writer.began:
            try:
                writer._write(record)
            except OSError:
                pass
            writer.finish_stream()
        else:
            handler._reply(status, json.dumps(record).encode(),
                           {"Content-Type": "application/json"})

    def _admin_split(self, method: str, body: bytes) -> tuple[int, dict]:
        """``GET /admin/split`` reads, ``POST /admin/split`` applies
        ``{"split": {...}|null, "shadow": {"version": v, "fraction": f}
        |null}`` — the deployment plane's HTTP surface on the front."""
        if method == "GET":
            return 200, {"split": self.traffic_split(),
                         "shadow": self.shadow()}
        try:
            payload = json.loads(body or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            if "split" in payload:
                self.set_traffic_split(payload["split"])
            if "shadow" in payload:
                sh = payload["shadow"]
                if sh is None:
                    self.clear_shadow()
                else:
                    self.set_shadow(sh["version"],
                                    float(sh.get("fraction", 1.0)))
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            return 400, {"error": str(e)}
        return 200, {"ok": True, "split": self.traffic_split(),
                     "shadow": self.shadow()}

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._pool.close()


class RoutingClient:
    """Serve-where-it-lands client: fetches the routing table from a front's
    ``/routes`` (or takes a worker list), then talks to workers DIRECTLY over
    its own persistent connections, round-robin — zero proxy hops, the
    client-side analog of Spark clients hitting whichever executor serves
    them (``DistributedHTTPSource.scala:88-203``). Failing workers trip a
    per-worker circuit breaker (skipped until the ``resurrect_after_s``
    half-open probe) and the table is refreshed; when every breaker is open
    the least-recently-failed worker is tried anyway. Thread-safe.
    """

    def __init__(self, front_address: str | None = None,
                 workers: list[dict] | None = None, timeout_s: float = 10.0,
                 resurrect_after_s: float = 2.0):
        if front_address is None and workers is None:
            raise ValueError("RoutingClient needs front_address or workers")
        self._front = front_address
        self._workers = list(workers or [])
        self._pool = _ConnPool(timeout_s)
        self._rr = 0
        self._lock = threading.Lock()
        self._timeout_s = timeout_s
        self._resurrect_after_s = resurrect_after_s
        self._breakers: dict[tuple, CircuitBreaker] = {}
        _register_breaker_gauge(self, plane="client")
        if self._front is not None:
            self.refresh()

    def _breaker(self, key: tuple) -> CircuitBreaker:
        with self._lock:
            breaker = self._breakers.get(key)
            if breaker is None:
                breaker = CircuitBreaker(
                    failure_rate_threshold=0.0, window=1, min_samples=1,
                    probe_interval_s=self._resurrect_after_s,
                    measures=resilience_measures("distributed_serving"),
                    name=f"client {key[0]}:{key[1]}")
                self._breakers[key] = breaker
            return breaker

    def breaker_states(self) -> dict:
        """(host:port -> breaker state) snapshot, mirroring the front's."""
        with self._lock:
            return {f"{h}:{p}": br.state
                    for (h, p), br in self._breakers.items()}

    def refresh(self) -> list[dict]:
        if self._front is not None:
            with urllib.request.urlopen(self._front + "/routes",
                                        timeout=self._timeout_s) as r:
                table = json.loads(r.read())
            live_keys = {(w.get("host"), w.get("port")) for w in table}
            with self._lock:
                self._workers = table
                # drop breakers for workers no longer in the table (respawn
                # churn would otherwise grow the map forever)
                self._breakers = {k: b for k, b in self._breakers.items()
                                  if k in live_keys}
        return list(self._workers)

    def request(self, path: str, body: bytes | None = None,
                method: str | None = None, headers: dict | None = None):
        """(status, payload) from the next worker in rotation; a worker
        failure rotates on (with a table refresh) before giving up. Each
        request runs in one ``route.client`` span whose context is injected
        as ``traceparent`` so the worker's serving span joins the trace."""
        method = method or ("POST" if body is not None else "GET")
        tracer = obs.get_tracer()
        with tracer.span("route.client", {"path": path, "method": method}):
            headers = dict(headers or {})
            tracer.inject(headers)
            return self._request_routed(path, body, method, headers)

    def _request_routed(self, path: str, body, method: str, headers: dict):
        rm = _ROUTE_METRICS.get()
        with self._lock:
            table = list(self._workers)
            self._rr += 1
            rot = self._rr
        if not table:
            raise ConnectionError("no workers in the routing table")
        last_err, tried = None, 0
        for i in range(len(table)):
            w = table[(rot + i) % len(table)]
            key = (w.get("host"), w.get("port"))
            breaker = self._breaker(key)
            if not breaker.allow():
                continue  # breaker open: skip until its half-open probe
            if tried:
                resilience_measures("distributed_serving").count("retry")
            tried += 1
            t0 = time.perf_counter()
            try:
                result = _pooled_request(self._pool, key, method, path, body,
                                         headers)
                breaker.record_success()
                rm["request_ms"].observe((time.perf_counter() - t0) * 1e3,
                                         worker=f"{key[0]}:{key[1]}")
                return result
            except (http.client.HTTPException, OSError) as e:
                breaker.record_failure()
                self._pool.clear(key)
                last_err = e
            if self._front is not None:
                try:
                    table = self.refresh() or table
                except (urllib.error.URLError, OSError):
                    pass
        if tried == 0:
            # every breaker open: desperation-probe the stalest failure (the
            # client degrades to retrying, never to a permanent error)
            w = min(table, key=lambda w: self._breaker(
                (w.get("host"), w.get("port"))).last_failure_at or 0.0)
            key = (w.get("host"), w.get("port"))
            breaker = self._breaker(key)
            try:
                result = _pooled_request(self._pool, key, method, path, body,
                                         headers)
                breaker.record_success()
                return result
            except (http.client.HTTPException, OSError) as e:
                breaker.record_failure()
                self._pool.clear(key)
                last_err = e
        raise ConnectionError(f"all {len(table)} workers failed: {last_err}")

    def close(self) -> None:
        self._pool.close()


def worker_main(pipeline_path: str, registry_address: str,
                batch_interval_ms: int = 0,
                version: str | None = None) -> None:
    """Worker process entry: load the pickled pipeline, serve it, register,
    then park forever (the per-executor server loop). A hot swap
    (``POST /admin/load``) re-registers the worker with its NEW version so
    the front's canary routing and per-version metrics follow the swap."""
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    from .serving import serve_pipeline

    with open(pipeline_path, "rb") as f:
        pipeline = pickle.load(f)
    server = serve_pipeline(pipeline, batch_interval_ms=batch_interval_ms,
                            version=version)

    def register(*_swap_args) -> dict:
        info = {"host": server.host, "port": server.port,
                "pid": os.getpid(),
                "version": server.pipeline_holder.version}
        # fleet-swap observability: whether this worker's last hot swap
        # rode the AOT executable path (registry/aot.py) — the front's
        # worker listing shows at a glance if a rollout was compile-bound
        report = getattr(server, "last_swap_report", None)
        if report:
            info["aot"] = report.get("mode")
        urllib.request.urlopen(urllib.request.Request(
            registry_address, data=json.dumps(info).encode(), method="POST",
            headers={"Content-Type": "application/json"}), timeout=30).read()
        return info

    server.pipeline_holder.subscribe(register)
    info = register()

    def on_drained(_report) -> None:
        # graceful removal (fleet plane): deregister BEFORE exiting so the
        # front's routing table reflects the drain, then leave — the
        # supervisor (if any) sees a clean exit, not a crash to respawn
        deregister_worker(registry_address, info)
        os._exit(0)

    server.on_drained = on_drained
    print(f"worker ready {info}", flush=True)
    while True:  # killed by the parent, or exits via /admin/drain
        time.sleep(1.0)


def llm_worker_main(model_name: str, registry_address: str,
                    max_new_tokens: int = 64, engine: str = "paged",
                    warmup: bool = True) -> None:
    """LLM decode-worker process entry: build the named causal LM, serve
    it with the token scheduler (``serve_llm``), register with the driver
    registry, then park. The survivable-serving chaos tests SIGKILL these
    processes mid-decode; a drain (``/admin/drain`` with ``migrate_to``)
    deregisters and exits cleanly instead."""
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    from ..hf import HuggingFaceCausalLM
    from .serving import serve_llm

    lm = HuggingFaceCausalLM(model_name=model_name,
                             max_new_tokens=max_new_tokens, engine=engine)
    server = serve_llm(lm, warmup=warmup)
    info = {"host": server.host, "port": server.port, "pid": os.getpid()}
    urllib.request.urlopen(urllib.request.Request(
        registry_address, data=json.dumps(info).encode(), method="POST",
        headers={"Content-Type": "application/json"}), timeout=30).read()

    def on_drained(_report) -> None:
        deregister_worker(registry_address, info)
        os._exit(0)

    server.on_drained = on_drained
    print(f"llm worker ready {info}", flush=True)
    while True:  # killed by the parent/chaos, or exits via /admin/drain
        time.sleep(1.0)


class DistributedServing:
    """Handle owning the registry, worker processes, and routing front.

    A supervisor thread respawns any worker process that dies (the reference
    relies on Spark re-launching failed executors; here the driver handle does
    it): the replacement registers itself with the registry on startup and the
    registry-backed front routes to it immediately."""

    def __init__(self, front: RoutingFront, registry: WorkerRegistry,
                 procs: list, tmp_file: str, spawn=None,
                 supervise_interval_s: float = 0.25):
        self.front = front
        self.registry = registry
        self.procs = procs
        self._tmp_file = tmp_file
        self._spawn = spawn
        self._stopping = threading.Event()
        self._supervisor = None
        if spawn is not None:
            self._supervisor = threading.Thread(
                target=self._supervise, args=(supervise_interval_s,),
                daemon=True)
            self._supervisor.start()

    def _supervise(self, interval_s: float) -> None:
        # per-slot respawn backoff: a worker that keeps dying young (crash on
        # startup: bad pickle, OOM on load) is respawned at a decaying rate
        # (doubling delay, capped) instead of ~4 forks/sec forever; a spawn
        # failure itself never kills the supervisor thread.
        n = len(self.procs)
        next_try, delay, spawned = [0.0] * n, [interval_s] * n, [0.0] * n
        while not self._stopping.wait(interval_s):
            now = time.monotonic()
            for i, p in enumerate(self.procs):
                if p.poll() is None:
                    if now - spawned[i] > 10.0:
                        delay[i] = interval_s  # survived long enough: reset
                    continue
                if self._stopping.is_set() or now < next_try[i]:
                    continue
                self.registry.remove_pid(p.pid)
                try:
                    self.procs[i] = self._spawn()
                    spawned[i] = now
                except OSError as e:
                    print(f"# worker respawn failed (slot {i}): {e}",
                          file=sys.stderr, flush=True)
                delay[i] = min(delay[i] * 2, 10.0)
                next_try[i] = now + delay[i]

    @property
    def address(self) -> str:
        return self.front.address

    def stop(self) -> None:
        self._stopping.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        self.front.close()
        self.registry.close()
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        try:
            os.unlink(self._tmp_file)
        except OSError:
            pass


def serve_pipeline_distributed(pipeline, num_workers: int = 2,
                               batch_interval_ms: int = 0,
                               startup_timeout_s: float = 90.0,
                               version: str | None = None,
                               coalesce_window_ms: float = 0.0) -> DistributedServing:
    """Serve a (picklable) Transformer across ``num_workers`` OS processes
    behind one routed public port — the DistributedHTTPSource analog.
    ``version`` labels the initial pipeline for the deployment plane
    (canary splits + per-version metrics; see ``registry/deploy.py``).
    ``coalesce_window_ms`` > 0 groups same-path requests at the front so
    they reach one worker as a bucket-sized batch (continuous batching
    across the fleet) — padding-waste and occupancy land in the metrics
    registry per version. Coalescing requires micro-batch workers
    (``batch_interval_ms`` > 0): funneling a group at a continuous worker
    that drains one row per loop would add the window's latency and
    serialize the group on one process for zero batching gain."""
    if coalesce_window_ms > 0 and batch_interval_ms == 0:
        raise ValueError(
            "coalesce_window_ms requires micro-batch workers: set "
            "batch_interval_ms > 0 so the chosen worker drains the "
            "coalesced group as one batch (continuous workers drain one "
            "row per loop — the group would serialize for no gain)")
    import tempfile

    fd, path = tempfile.mkstemp(suffix=".pipeline.pkl")
    with os.fdopen(fd, "wb") as f:
        pickle.dump(pipeline, f)

    registry = WorkerRegistry()
    code = ("from synapseml_tpu.io.distributed_serving import worker_main; "
            f"worker_main({path!r}, {registry.address + '/register'!r}, "
            f"{batch_interval_ms}, version={version!r})")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = [repo_root]
    # unpickling user-defined Transformer classes in the worker needs their
    # defining module importable
    cls_mod = sys.modules.get(type(pipeline).__module__)
    mod_file = getattr(cls_mod, "__file__", None)
    if mod_file:
        paths.append(os.path.dirname(os.path.abspath(mod_file)))
    env["PYTHONPATH"] = os.pathsep.join(paths + [env.get("PYTHONPATH", "")])

    def spawn():
        return subprocess.Popen([sys.executable, "-c", code], env=env)

    procs = [spawn() for _ in range(num_workers)]
    try:
        registry.wait_for(num_workers, timeout_s=startup_timeout_s)
    except TimeoutError:
        for p in procs:
            p.terminate()
        registry.close()
        raise
    front = RoutingFront(registry=registry,
                         coalesce_window_ms=coalesce_window_ms)
    return DistributedServing(front, registry, procs, path, spawn=spawn)


def collect_distributed_trace(front_address: str,
                              timeout_s: float = 10.0) -> list[dict]:
    """Stitch one multi-process trace: the front process's spans
    (``GET /trace`` served by the front itself) + every live worker's spans
    (``GET /trace`` on each endpoint from ``/routes``). Returns a flat list
    of span dicts — feed it to
    :func:`~synapseml_tpu.core.observability.chrome_trace_events` /
    ``export_chrome_trace`` for one Perfetto-loadable timeline."""
    spans: list[dict] = []
    with urllib.request.urlopen(front_address + "/trace",
                                timeout=timeout_s) as r:
        spans.extend(json.loads(r.read()))
    with urllib.request.urlopen(front_address + "/routes",
                                timeout=timeout_s) as r:
        table = json.loads(r.read())
    for w in table:
        url = f"http://{w.get('host')}:{w.get('port')}/trace"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                spans.extend(json.loads(r.read()))
        except (urllib.error.URLError, OSError):
            continue  # a dead worker's spans are simply missing
    return spans


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
