"""Distributed serving: per-worker HTTP servers + driver routing front.

Reference: ``streaming/DistributedHTTPSource.scala:88-203`` — every executor
runs a ``JVMSharedServer`` and requests are served wherever they land, with
the driver service collecting worker endpoints
(``DriverServiceUtils``, ``continuous/HTTPSourceV2.scala:132-202``). Here:

  * ``worker_main`` — one OS process per partition-worker, running
    ``serve_pipeline`` on its own port and registering (host, port) with the
    driver registry;
  * ``WorkerRegistry`` — the driver-side registration endpoint (worker list =
    the routing table);
  * ``RoutingFront`` — the one public port: forwards each request round-robin
    to a live worker, skipping dead ones (the shared-server role).

``serve_pipeline_distributed`` wires all three and returns the front.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["WorkerRegistry", "RoutingFront", "serve_pipeline_distributed",
           "worker_main"]


class WorkerRegistry:
    """Driver-side worker registration (DriverServiceUtils analog): workers
    POST {host, port, pid}; the routing table is the registered list. A
    re-registration from the same (host, port) replaces the old entry, so a
    restarted worker rejoins cleanly."""

    def __init__(self):
        self._workers: list[dict] = []
        self._lock = threading.Lock()
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                info = json.loads(self.rfile.read(n))
                key = (info.get("host"), info.get("port"))
                with registry._lock:
                    registry._workers = [
                        w for w in registry._workers
                        if (w.get("host"), w.get("port")) != key]
                    registry._workers.append(info)
                body = b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def workers(self) -> list[dict]:
        with self._lock:
            return list(self._workers)

    def remove_pid(self, pid: int) -> None:
        """Drop a worker whose process is known dead (supervisor callback)."""
        with self._lock:
            self._workers = [w for w in self._workers if w.get("pid") != pid]

    def wait_for(self, n: int, timeout_s: float = 60.0) -> list[dict]:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            w = self.workers()
            if len(w) >= n:
                return w
            time.sleep(0.05)
        raise TimeoutError(f"only {len(self.workers())}/{n} workers registered")

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RoutingFront:
    """One public port; round-robin forwarding to live workers.

    Reliability semantics (the reference's serve-where-it-lands plane never
    loses workers permanently, ``DistributedHTTPSource.scala:88-203``):

    * connect failures AND timeouts mark a worker dead for
      ``resurrect_after_s`` seconds, after which it is probed again
      (time-based resurrection — a slow-but-alive worker is excluded only
      briefly, while a blackholed one stops stalling every rotation by
      ``timeout_s``); any successful reply clears the mark immediately;
    * when every worker is marked dead the least-recently-failed one is
      probed anyway (the front degrades to retrying, never to a permanent
      503);
    * with a ``registry``, the routing table refreshes from it on every
      request, so workers registered AFTER startup (restarts, scale-up) are
      routed to immediately; a static ``workers`` list is merged in (the
      registry entry wins on a (host, port) collision).
    """

    def __init__(self, workers: list[dict] | None = None, port: int = 0,
                 timeout_s: float = 60.0, registry: "WorkerRegistry" = None,
                 resurrect_after_s: float = 2.0):
        if workers is None and registry is None:
            raise ValueError("RoutingFront needs workers and/or a registry")
        self._static_workers = list(workers or [])
        self._registry = registry
        self._dead: dict[tuple, float] = {}  # (host, port) -> time marked
        self._rr = 0
        self._lock = threading.Lock()
        front = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _forward(self, method: str):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else None
                for w in front._candidates():
                    key = (w.get("host"), w.get("port"))
                    url = f"http://{w['host']}:{w['port']}{self.path}"
                    req = urllib.request.Request(url, data=body, method=method,
                                                 headers={k: v for k, v in
                                                          self.headers.items()
                                                          if k.lower() != "host"})
                    try:
                        with urllib.request.urlopen(req, timeout=timeout_s) as r:
                            payload = r.read()
                            with front._lock:
                                front._dead.pop(key, None)  # proven alive
                            self.send_response(r.status)
                            self.send_header("Content-Length", str(len(payload)))
                            self.send_header("X-Served-By", str(w.get("pid", "")))
                            self.end_headers()
                            self.wfile.write(payload)
                            return
                    except urllib.error.HTTPError as e:
                        payload = e.read()
                        self.send_response(e.code)
                        self.send_header("Content-Length", str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                        return
                    except (urllib.error.URLError, OSError):
                        with front._lock:
                            front._dead[key] = time.monotonic()
                self.send_response(503)
                self.end_headers()

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

        self._resurrect_after_s = resurrect_after_s
        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _table(self) -> list[dict]:
        if self._registry is None:
            return self._static_workers
        reg = self._registry.workers()
        seen = {(w.get("host"), w.get("port")) for w in reg}
        return reg + [w for w in self._static_workers
                      if (w.get("host"), w.get("port")) not in seen]

    def _candidates(self) -> list[dict]:
        """Routing order for one request: alive + resurrection-due workers
        round-robin rotated; if none, the least-recently-failed worker."""
        table = self._table()
        if not table:
            return []
        now = time.monotonic()
        with self._lock:
            alive = [w for w in table
                     if (now - self._dead.get((w.get("host"), w.get("port")),
                                              -1e18)) >= self._resurrect_after_s]
            self._rr += 1
            rot = self._rr % max(len(alive), 1)
        if alive:
            return alive[rot:] + alive[:rot]
        # everything recently failed: probe the stalest failure anyway
        with self._lock:
            stalest = min(table, key=lambda w: self._dead.get(
                (w.get("host"), w.get("port")), 0.0))
        return [stalest]

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def worker_main(pipeline_path: str, registry_address: str,
                batch_interval_ms: int = 0) -> None:
    """Worker process entry: load the pickled pipeline, serve it, register,
    then park forever (the per-executor server loop)."""
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    from .serving import serve_pipeline

    with open(pipeline_path, "rb") as f:
        pipeline = pickle.load(f)
    server = serve_pipeline(pipeline, batch_interval_ms=batch_interval_ms)
    info = {"host": server.host, "port": server.port, "pid": os.getpid()}
    urllib.request.urlopen(urllib.request.Request(
        registry_address, data=json.dumps(info).encode(), method="POST",
        headers={"Content-Type": "application/json"}), timeout=30).read()
    print(f"worker ready {info}", flush=True)
    while True:  # killed by the parent
        time.sleep(1.0)


class DistributedServing:
    """Handle owning the registry, worker processes, and routing front.

    A supervisor thread respawns any worker process that dies (the reference
    relies on Spark re-launching failed executors; here the driver handle does
    it): the replacement registers itself with the registry on startup and the
    registry-backed front routes to it immediately."""

    def __init__(self, front: RoutingFront, registry: WorkerRegistry,
                 procs: list, tmp_file: str, spawn=None,
                 supervise_interval_s: float = 0.25):
        self.front = front
        self.registry = registry
        self.procs = procs
        self._tmp_file = tmp_file
        self._spawn = spawn
        self._stopping = threading.Event()
        self._supervisor = None
        if spawn is not None:
            self._supervisor = threading.Thread(
                target=self._supervise, args=(supervise_interval_s,),
                daemon=True)
            self._supervisor.start()

    def _supervise(self, interval_s: float) -> None:
        # per-slot respawn backoff: a worker that keeps dying young (crash on
        # startup: bad pickle, OOM on load) is respawned at a decaying rate
        # (doubling delay, capped) instead of ~4 forks/sec forever; a spawn
        # failure itself never kills the supervisor thread.
        n = len(self.procs)
        next_try, delay, spawned = [0.0] * n, [interval_s] * n, [0.0] * n
        while not self._stopping.wait(interval_s):
            now = time.monotonic()
            for i, p in enumerate(self.procs):
                if p.poll() is None:
                    if now - spawned[i] > 10.0:
                        delay[i] = interval_s  # survived long enough: reset
                    continue
                if self._stopping.is_set() or now < next_try[i]:
                    continue
                self.registry.remove_pid(p.pid)
                try:
                    self.procs[i] = self._spawn()
                    spawned[i] = now
                except OSError as e:
                    print(f"# worker respawn failed (slot {i}): {e}",
                          file=sys.stderr, flush=True)
                delay[i] = min(delay[i] * 2, 10.0)
                next_try[i] = now + delay[i]

    @property
    def address(self) -> str:
        return self.front.address

    def stop(self) -> None:
        self._stopping.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        self.front.close()
        self.registry.close()
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        try:
            os.unlink(self._tmp_file)
        except OSError:
            pass


def serve_pipeline_distributed(pipeline, num_workers: int = 2,
                               batch_interval_ms: int = 0,
                               startup_timeout_s: float = 90.0) -> DistributedServing:
    """Serve a (picklable) Transformer across ``num_workers`` OS processes
    behind one routed public port — the DistributedHTTPSource analog."""
    import tempfile

    fd, path = tempfile.mkstemp(suffix=".pipeline.pkl")
    with os.fdopen(fd, "wb") as f:
        pickle.dump(pipeline, f)

    registry = WorkerRegistry()
    code = ("from synapseml_tpu.io.distributed_serving import worker_main; "
            f"worker_main({path!r}, {registry.address + '/register'!r}, "
            f"{batch_interval_ms})")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = [repo_root]
    # unpickling user-defined Transformer classes in the worker needs their
    # defining module importable
    cls_mod = sys.modules.get(type(pipeline).__module__)
    mod_file = getattr(cls_mod, "__file__", None)
    if mod_file:
        paths.append(os.path.dirname(os.path.abspath(mod_file)))
    env["PYTHONPATH"] = os.pathsep.join(paths + [env.get("PYTHONPATH", "")])

    def spawn():
        return subprocess.Popen([sys.executable, "-c", code], env=env)

    procs = [spawn() for _ in range(num_workers)]
    try:
        registry.wait_for(num_workers, timeout_s=startup_timeout_s)
    except TimeoutError:
        for p in procs:
            p.terminate()
        registry.close()
        raise
    front = RoutingFront(registry=registry)
    return DistributedServing(front, registry, procs, path, spawn=spawn)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
