"""Model serving: HTTP requests in, pipeline transform, replies out.

Reference (SURVEY.md §3.4): Spark Serving's ``HTTPSourceV2``/``HTTPSinkV2`` —
an HTTP source enqueues requests as rows tagged with a request id, the user
pipeline transforms request rows into reply rows, and the sink routes each
reply back to the originating open connection by request id
(``continuous/HTTPSinkV2.scala:74-154``, ``HTTPServerUtils.respond``).

Here: a threaded stdlib HTTP server parks each connection on an Event;
``ServingServer.read_batch`` drains the queue into a DataFrame (micro-batch
mode, ``HTTPMicroBatchReader`` analog); ``reply_batch`` completes the parked
exchanges. ``serve_pipeline`` wires a Transformer into the loop — micro-batch
with ``batch_interval_ms`` or per-request continuous mode (``interval=0``,
the reference's sub-millisecond continuous path). ``serve_llm`` runs the
TOKEN-granular scheduler instead: prefill between decode steps over the
paged-KV engine, chunked streaming replies, immediate slot refill on EOS
(docs/SERVING.md, "Token-level LLM serving").
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
import socket
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..core import batching as cb
from ..core import observability as obs
from ..core.dataframe import DataFrame

__all__ = ["ServingServer", "serve_pipeline", "serve_llm",
           "NoDelayHTTPServer", "PipelineHolder", "run_warmup"]

# batch-size histogram rungs: one bucket per pow-2 occupancy up to the
# serve-loop max (NOT latency buckets — these count rows per micro-batch)
_BATCH_ROW_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

# default /admin/load warmup precompiles ladder rungs up to this many rows;
# an explicit serve_pipeline(bucket_ladder=...) warms its full ladder
_DEFAULT_WARMUP_CAP = 64

# hot-path metric handles, re-resolved only when the registry is replaced
_SERVING_METRICS = obs.HandleCache(lambda reg: {
    "request_ms": reg.histogram(
        "synapseml_serving_request_duration_ms",
        "worker HTTP request latency", ("method",)),
    "requests": reg.counter(
        "synapseml_serving_requests_total",
        "worker HTTP requests by status class", ("method", "status")),
    "queue_wait": reg.histogram(
        "synapseml_serving_queue_wait_ms",
        "request time spent queued before batch pickup").labels(),
    "swaps": reg.counter(
        "synapseml_serving_pipeline_swaps_total",
        "hot pipeline swaps on this worker, by outcome", ("outcome",)),
    "batch_rows": reg.histogram(
        "synapseml_serving_batch_rows",
        "rows per drained serve-loop micro-batch (continuous batching "
        "occupancy)", buckets=_BATCH_ROW_BUCKETS).labels(),
    "expired": reg.counter(
        "synapseml_serving_expired_requests_total",
        "queued requests dropped because their reply deadline passed "
        "before batch pickup").labels(),
    "drains": reg.counter(
        "synapseml_serving_drains_total",
        "graceful worker drains, by outcome", ("outcome",)),
    "migrations": reg.counter(
        "synapseml_llm_migrations_total",
        "live LLM sequence migrations off this worker, by reason "
        "(drain/...) and outcome (ok = peer accepted the KV snapshot, "
        "error = handoff failed and the sequence resumed locally)",
        ("reason", "outcome")),
    "migration_ms": reg.histogram(
        "synapseml_llm_migration_ms",
        "per-sequence live-migration latency: KV export -> peer "
        "acceptance via the front").labels(),
})


class PipelineHolder:
    """The mutable slot the serving loop reads its pipeline from.

    Hot-swap (``POST /admin/load``) loads the replacement side-by-side,
    warms it, then calls :meth:`swap` — one attribute assignment under a
    lock, so in-flight batches finish on the old pipeline and the next
    batch picks up the new one with zero dropped requests. ``subscribe``
    registers post-swap callbacks (the distributed worker re-registers its
    new version with the driver registry through one)."""

    def __init__(self, pipeline, version: str | None = None):
        self._lock = threading.Lock()
        self._pipeline = pipeline
        self._version = version
        self._callbacks: list = []

    @property
    def version(self) -> str | None:
        with self._lock:
            return self._version

    @property
    def pipeline(self):
        with self._lock:
            return self._pipeline

    def get(self):
        """(pipeline, version) — one consistent snapshot."""
        with self._lock:
            return self._pipeline, self._version

    def subscribe(self, fn) -> None:
        """``fn(new_version, old_version)`` after every successful swap."""
        self._callbacks.append(fn)

    def swap(self, pipeline, version: str | None = None) -> str | None:
        with self._lock:
            old = self._version
            self._pipeline = pipeline
            self._version = version
        for fn in list(self._callbacks):
            try:
                fn(version, old)
            except Exception:  # noqa: BLE001 - a callback must not undo a swap
                pass
        return old


class NoDelayHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that sets TCP_NODELAY on every accepted socket.
    With HTTP/1.1 keep-alive, Nagle + the peer's delayed ACK turns each
    small-write response into a ~40 ms stall; sub-millisecond serving (the
    reference's claim) requires segments to go out immediately. Enforced
    here at accept time so no Handler class can forget it."""

    def get_request(self):
        sock, addr = super().get_request()
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock, addr


_STREAM_END = object()  # chunk-queue sentinel: close the chunked response


class _Exchange:
    def __init__(self, request_id: str, method: str, path: str, headers: dict,
                 body: bytes):
        self.request_id = request_id
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body
        self.enqueued_at = time.perf_counter()  # queue-wait measurement
        self.reply_event = threading.Event()
        self.reply_body: bytes = b""
        self.reply_status: int = 200
        self.reply_headers: dict = {}
        # token-streaming mode: the scheduler pushes chunks, the parked
        # handler thread writes them out as HTTP/1.1 chunked encoding.
        # The chunk queue is created lazily in stream_begin — the dominant
        # non-streaming path must not pay a Queue (lock + 3 condvars) per
        # request
        self.streaming = False
        self.chunks: "queue.Queue | None" = None
        self._replied = False
        # set by the handler when a stream write hits a dead socket: the
        # token scheduler checks it and aborts the sequence (reason
        # 'client_gone') instead of decoding to max_new into nothing
        self.client_gone = False

    def respond(self, body, status: int = 200, headers: dict | None = None):
        if self._replied:
            return  # first terminal reply wins (drop-path vs handler races)
        self._replied = True
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
            headers = {"Content-Type": "application/json", **(headers or {})}
        elif isinstance(body, str):
            body = body.encode()
        self.reply_body = body or b""
        self.reply_status = status
        self.reply_headers = headers or {}
        self.reply_event.set()

    def stream_begin(self, status: int = 200,
                     headers: dict | None = None) -> None:
        """Switch the parked handler into chunked-streaming mode; chunks
        pushed via :meth:`stream_chunk` flush per token."""
        if self._replied:
            return
        self._replied = True
        self.chunks = queue.Queue()
        self.streaming = True
        self.reply_status = status
        self.reply_headers = headers or {"Content-Type":
                                         "application/x-ndjson"}
        self.reply_event.set()

    def stream_chunk(self, data) -> None:
        if self.chunks is None or self.client_gone:
            return  # stream never began (or the peer socket is dead)
        if isinstance(data, (dict, list)):
            data = (json.dumps(data) + "\n").encode()
        elif isinstance(data, str):
            data = data.encode()
        self.chunks.put(data)

    def stream_end(self) -> None:
        if self.chunks is not None:
            self.chunks.put(_STREAM_END)


def _header(headers: dict, name: str) -> str | None:
    """Case-insensitive header lookup on a plain-dict header map."""
    want = name.lower()
    for k, v in headers.items():
        if str(k).lower() == want:
            return v
    return None


def _post_json(url: str, obj, timeout: float = 10.0) -> bool:
    """Best-effort JSON POST; True iff the peer replied 2xx."""
    import http.client
    from urllib.parse import urlsplit
    parts = urlsplit(url)
    conn = http.client.HTTPConnection(parts.hostname,
                                      parts.port or 80, timeout=timeout)
    try:
        body = json.dumps(obj).encode()
        conn.request("POST", parts.path or "/", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        return 200 <= resp.status < 300
    except OSError:
        return False
    finally:
        conn.close()


class ServingServer:
    """(ref ``HTTPSourceV2``/``DistributedHTTPSource``)"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 reply_timeout_s: float = 30.0, max_queue: int = 4096):
        self.reply_timeout_s = reply_timeout_s
        # set by serve_pipeline: the hot-swap slot + the loop's parsing
        # config (the /admin/load warmup must prepare batches EXACTLY like
        # the serve loop does, or warmup success proves nothing)
        self.pipeline_holder: PipelineHolder | None = None
        self._loop_cfg = {"parse_json": True, "input_col": "body"}
        # serve-loop bucket ladder (set by serve_pipeline): the adaptive
        # scheduler's flush rungs. _warmup_buckets is the /admin/load
        # precompile set — the full ladder when explicitly configured, the
        # latency-sensitive small rungs otherwise (a default full-ladder
        # warmup of a heavy model can outlast the deploy plane's load
        # timeout; big batches amortize a compile stall anyway)
        self._bucket_ladder: tuple | None = None
        self._warmup_buckets: tuple = ()
        # the live pipeline's AOT blob tier (registry/aot.py) + the last
        # swap's warmup breakdown (operators + fleet registration read it)
        self._aot_provider = None
        self.last_swap_report: dict | None = None
        # graceful-drain state (fleet plane, POST /admin/drain): a draining
        # worker refuses NEW requests with terminal 503s, finishes the
        # queued backlog, then fires on_drained (worker entrypoints
        # deregister + exit there; in-process launchers stop the server) —
        # a scale-down is now distinguishable from a crash
        self.draining = False
        self.on_drained = None  # fn(report: dict), called once, off-thread
        self._drain_thread: threading.Thread | None = None
        # live-drain handoff (serve_llm): /admin/drain may name a front to
        # migrate active sequences to; drain_barrier (set by the token
        # scheduler) holds the drain waiter open until every live sequence
        # has migrated or finished — streaming exchanges are NOT in
        # _pending, so the settle loop alone would conclude too early
        self.migrate_to: str | None = None
        self.drain_barrier = None  # fn(budget_s), blocks until quiesced
        # handlers between their draining check and their queue insert: the
        # drain waiter must not conclude "empty" while an admission is in
        # flight (guarded by _lock)
        self._admitting = 0
        self.started_at = time.monotonic()
        # set by serve_multi_model: the residency manager /admin/stats reads
        self.residency = None
        # set by serve_llm: engine-level stats (prefix-cache occupancy /
        # hit-rate, speculation acceptance) surface on /admin/stats so the
        # routing front and autoscaler read them without scraping /metrics
        self.llm_stats_fn = None
        # continual plane (continual/logger.py): a RequestLogger attached
        # here records every batched exchange at reply time — sampled,
        # bounded, shed-before-delay, so serving latency never pays for it
        self.request_logger = None
        # bounded: a stalled pipeline sheds load with 503s instead of parking
        # unbounded connections (backpressure the round-1 loop lacked)
        self._queue: "queue.Queue[_Exchange]" = queue.Queue(maxsize=max_queue)
        self._pending: dict[str, _Exchange] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: connections persist across requests — the per-request
            # TCP handshake is most of the loopback round-trip (the
            # reference's JVMSharedServer keeps executor sockets open too);
            # the server sets TCP_NODELAY at accept (NoDelayHTTPServer)
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply_bytes(self, status: int, payload: bytes,
                             content_type: str | None = None) -> None:
                self.send_response(status)
                if content_type:
                    self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                if payload:
                    self.wfile.write(payload)

            def _handle(self, method: str):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if method == "GET" and self.path == "/metrics":
                    payload, ctype = obs.prometheus_exposition()
                    self._reply_bytes(200, payload, ctype)
                    return
                if method == "GET" and self.path == "/trace":
                    payload = json.dumps(
                        obs.get_tracer().spans_as_dicts()).encode()
                    self._reply_bytes(200, payload, "application/json")
                    return
                # deployment-plane admin endpoints (registry/deploy.py):
                # handled here, never queued behind the pipeline
                if method == "GET" and self.path == "/admin/version":
                    self._reply_bytes(
                        200, json.dumps(outer._admin_version()).encode(),
                        "application/json")
                    return
                if method == "GET" and self.path == "/admin/stats":
                    self._reply_bytes(
                        200, json.dumps(outer._admin_stats()).encode(),
                        "application/json")
                    return
                if method == "POST" and self.path == "/admin/load":
                    status, reply = outer._admin_load(body)
                    self._reply_bytes(status, json.dumps(reply).encode(),
                                      "application/json")
                    return
                if method == "POST" and self.path == "/admin/drain":
                    status, reply = outer._admin_drain(body)
                    self._reply_bytes(status, json.dumps(reply).encode(),
                                      "application/json")
                    return
                # one span per served request, stitched to the caller's trace
                # via the W3C traceparent header the RoutingFront injects
                tracer = obs.get_tracer()
                parent = obs.extract_context(self.headers)
                t0 = time.perf_counter()
                status = None  # stays None when _exchange raises -> "error"
                try:
                    with tracer.span("serving.request",
                                     {"path": self.path, "method": method},
                                     parent=parent):
                        status = self._exchange(method, body)
                finally:
                    dur_ms = (time.perf_counter() - t0) * 1e3
                    m = _SERVING_METRICS.get()
                    m["request_ms"].observe(dur_ms, method=method)
                    m["requests"].inc(
                        method=method,
                        status=(f"{status // 100}xx" if status is not None
                                else "error"))

            def _exchange(self, method: str, body: bytes) -> int:
                # the admitting count brackets the draining check and the
                # queue insert, so the drain waiter can never observe an
                # empty queue while this handler is between the two (the
                # accepted-then-abandoned race)
                with outer._lock:
                    outer._admitting += 1
                    draining = outer.draining
                if draining:
                    with outer._lock:
                        outer._admitting -= 1
                    # a draining worker refuses NEW work with a terminal
                    # reply (never a queued request it would then abandon);
                    # Retry-After points clients at the rest of the fleet.
                    # NOTE: the RoutingFront reroutes on an EXACT match of
                    # this payload — change both together.
                    payload = json.dumps(
                        {"error": "worker draining"}).encode()
                    self.send_response(503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Retry-After", "1")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return 503
                ex = _Exchange(uuid.uuid4().hex, method, self.path,
                               dict(self.headers), body)
                with outer._lock:
                    outer._pending[ex.request_id] = ex
                try:
                    outer._queue.put_nowait(ex)
                except queue.Full:
                    with outer._lock:
                        outer._pending.pop(ex.request_id, None)
                        outer._admitting -= 1
                    self.send_response(503)  # shed load under backpressure
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return 503
                with outer._lock:
                    outer._admitting -= 1
                ok = ex.reply_event.wait(outer.reply_timeout_s)
                with outer._lock:
                    outer._pending.pop(ex.request_id, None)
                if not ok:
                    self.send_response(504)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return 504
                if ex.streaming:
                    return self._stream_reply(ex)
                self.send_response(ex.reply_status)
                for k, v in ex.reply_headers.items():
                    if k.lower() != "content-length":  # we set the real one
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(ex.reply_body)))
                self.end_headers()
                self.wfile.write(ex.reply_body)
                return ex.reply_status

            def _stream_reply(self, ex) -> int:
                """Incremental (token-streaming) reply: HTTP/1.1 chunked
                encoding, one flush per pushed chunk. The handler thread
                stays parked on the chunk queue; a scheduler that stops
                feeding it past ``reply_timeout_s`` truncates the stream
                cleanly rather than parking the connection forever."""
                self.send_response(ex.reply_status)
                for k, v in ex.reply_headers.items():
                    if k.lower() not in ("content-length",
                                         "transfer-encoding"):
                        self.send_header(k, v)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        try:
                            chunk = ex.chunks.get(
                                timeout=outer.reply_timeout_s)
                        except queue.Empty:
                            break  # stalled producer: close the stream
                        if chunk is _STREAM_END:
                            break
                        if chunk:
                            self.wfile.write(b"%x\r\n" % len(chunk) + chunk
                                             + b"\r\n")
                            self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    # the client hung up mid-stream: flag the exchange so
                    # the scheduler reaps the sequence (pages freed NOW)
                    # rather than decoding the rest into a dead socket
                    ex.client_gone = True
                    self.close_connection = True
                return ex.reply_status

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

        self._server = NoDelayHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._running = False

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingServer":
        self._thread.start()
        self._running = True
        return self

    def stop(self) -> None:
        if self._running:
            self._server.shutdown()
            self._server.server_close()
            self._running = False

    # ---- deployment-plane admin (hot swap; registry/deploy.py) ----
    def _admin_version(self) -> dict:
        holder = self.pipeline_holder
        if holder is None:
            return {"version": None, "pipeline": None}
        pipeline, version = holder.get()
        return {"version": version, "pipeline": type(pipeline).__name__}

    def _admin_stats(self) -> dict:
        """Worker-local load snapshot (``GET /admin/stats``) — the fleet
        autoscaler's queue-depth signal, plus the last swap's warmup
        breakdown (the zero-cold-start evidence a scale-up must show) and
        the resident model set on multi-model workers."""
        out = {
            **self._admin_version(),
            "queue_depth": self._queue.qsize(),
            "pending": len(self._pending),
            "draining": self.draining,
            "uptime_s": round(time.monotonic() - self.started_at, 3),
            "swap": self.last_swap_report,
        }
        if self.residency is not None:
            out["resident"] = self.residency.resident()
            out["resident_bytes"] = self.residency.resident_bytes()
        if self.llm_stats_fn is not None:
            try:
                out["llm"] = self.llm_stats_fn()
            except Exception:  # noqa: BLE001 — stats must not fail /admin
                out["llm"] = None
        return out

    def _admin_drain(self, body: bytes) -> tuple[int, dict]:
        """``POST /admin/drain``: stop accepting new requests (terminal
        503s, never queued-then-abandoned), let the serve loop finish the
        queued backlog so every already-accepted exchange gets its real
        reply (zero dropped exchanges — the PR-6 terminal-reply
        discipline), then fire ``on_drained`` (worker entrypoints
        deregister from the WorkerRegistry and exit there). The reply
        returns immediately with the backlog size; drain completes
        asynchronously — poll ``/admin/stats`` or the registry table for
        completion. Body: ``{"timeout_s": <backlog deadline, default 30>}``
        — exchanges still unfinished at the deadline receive terminal 503s
        rather than holding the drain open forever."""
        try:
            payload = json.loads(body.decode() or "{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            timeout_s = float(payload.get("timeout_s", 30.0))
            migrate_to = payload.get("migrate_to")
            if migrate_to is not None and not isinstance(migrate_to, str):
                raise ValueError("migrate_to must be a URL string")
        except (json.JSONDecodeError, UnicodeDecodeError, TypeError,
                ValueError) as e:
            return 400, {"error": f"bad drain body: {e}"}
        with self._lock:  # two racing drains must start ONE waiter (and
            already = self.draining  # fire on_drained once)
            self.draining = True
            if migrate_to:
                # live drain: the token scheduler exports active sequences
                # and hands them to peers through this front instead of
                # running them to completion
                self.migrate_to = migrate_to
        backlog = self._queue.qsize()
        pending = len(self._pending)
        if not already:
            self._drain_thread = threading.Thread(
                target=self._drain_and_finish, args=(timeout_s,),
                daemon=True)
            self._drain_thread.start()
        return 200, {"ok": True, "draining": True, "backlog": backlog,
                     "pending": pending, "already_draining": already}

    def _drain_and_finish(self, timeout_s: float) -> None:
        # the /admin/drain handler writes its 200 AFTER starting this
        # thread — on an empty backlog the waiter would otherwise complete
        # instantly and on_drained (server stop / process exit) could cut
        # the drain reply itself off mid-write
        time.sleep(0.1)
        deadline = time.monotonic() + max(timeout_s, 0.0)
        barrier = self.drain_barrier
        if barrier is not None:
            # the token scheduler's sequences live OUTSIDE _pending (their
            # streaming handlers already dequeued) — wait for it to migrate
            # or finish every live sequence before declaring settled
            try:
                barrier(max(deadline - time.monotonic(), 0.0))
            except Exception:  # noqa: BLE001 — a barrier bug must not
                pass           # wedge the drain
        while time.monotonic() < deadline:
            with self._lock:
                settled = not self._pending and not self._admitting
            if settled and self._queue.qsize() == 0:
                break
            time.sleep(0.02)
        # anything STILL parked past the deadline gets a terminal reply —
        # a drain may time a slow pipeline out, but it never silently
        # abandons an accepted exchange
        with self._lock:
            stuck = list(self._pending.values())
        for ex in stuck:
            ex.respond({"error": "worker drained before this request "
                                 "finished"}, status=503)
        if stuck:
            # the responds above only WAKE the parked handler threads; give
            # them a bounded window to actually write the 503s before
            # on_drained (which may os._exit) can cut the sockets off
            flush_deadline = time.monotonic() + 5.0
            while time.monotonic() < flush_deadline:
                with self._lock:
                    if not self._pending:
                        break
                time.sleep(0.02)
            # handlers pop _pending BEFORE writing the response bytes; a
            # short grace covers the final socket writes
            time.sleep(0.25)
        outcome = "ok" if not stuck else "timeout"
        _SERVING_METRICS.get()["drains"].inc(outcome=outcome)
        report = {"outcome": outcome, "stuck": len(stuck)}
        callback = self.on_drained
        if callback is not None:
            try:
                callback(report)
            except Exception:  # noqa: BLE001 — a callback bug must not
                pass           # leave the worker half-drained

    def _warmup(self, stage, rows: list,
                buckets: "list[int] | None" = None) -> int:
        """Run ``rows`` (JSON-able request bodies) through ``stage`` with
        the SAME batch preparation the serve loop uses. When ``buckets`` is
        set (or the server has a configured ladder), the rows are cycled up
        to EACH bucket size and transformed once per bucket — every serve
        rung's executable compiles through the CompiledCache before the
        swap, so a hot-swap never pays first-request compile latency
        (zero-compile-stall, extending PR-3's zero-drop guarantee). Raises
        on any transform failure — a pipeline that cannot serve its warmup
        batch must never be swapped in."""
        if buckets is None:
            buckets = list(self._warmup_buckets)
        return run_warmup(stage, rows, buckets, self._loop_cfg)

    def _admin_load(self, body: bytes) -> tuple[int, dict]:
        """Load a new pipeline version side-by-side, warm it, atomically
        swap. Body: ``{"path": <stage dir>}`` or ``{"registry": <root or
        url>, "model": <name>, "ref": <version or alias>}``, plus optional
        ``"version"`` label and ``"warmup"`` (list of request bodies). The
        old pipeline keeps serving until the instant of the swap; a load or
        warmup failure leaves it untouched (409). ``"warmup_buckets"``
        overrides the precompile sizes (default: the server's configured
        bucket ladder).

        Registry artifacts published with AOT executable ladders load
        through the zero-cold-start path: the manifest's blob set installs
        as a CompiledCache second tier, the manifest-recorded warmup
        replays at the FULL ladder (the PR-4 "rungs <= 64" default cap is
        lifted — loading an executable is I/O, not compile), and the reply
        carries a ``warmup`` breakdown (io_ms / compile_ms / aot_hits /
        aot_misses / executables loaded vs traced). A runtime-fingerprint
        mismatch or missing mechanism logs one structured warning and
        falls back to JIT warmup — it never fails the swap. ``"aot":
        false`` / ``"autotune": false`` opt out per load (the coldstart
        bench's A/B switch)."""
        holder = self.pipeline_holder
        if holder is None:
            return 409, {"error": "this server has no swappable pipeline "
                                  "(started without serve_pipeline?)"}
        try:
            payload = json.loads(body.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return 400, {"error": f"bad JSON body: {e}"}
        if not isinstance(payload, dict):
            return 400, {"error": "body must be a JSON object"}
        t0 = time.perf_counter()
        manifest = None
        provider = None
        stage = None
        fallback_reason = None
        autotune_applied = None
        cache = cb.get_compiled_cache()
        try:
            if "path" in payload:
                from ..core.serialization import load_stage

                stage = load_stage(payload["path"])
                version = (payload.get("version")
                           or os.path.basename(
                               str(payload["path"]).rstrip("/")))
                aot_dir = None
            elif "registry" in payload and "model" in payload:
                from ..registry.registry import ModelRegistry

                resolved = ModelRegistry(payload["registry"]).resolve(
                    payload["model"], payload.get("ref", "latest"))
                stage, version = resolved.stage, resolved.version
                manifest = resolved.manifest
                aot_dir = os.path.join(os.path.dirname(resolved.path), "aot")
            else:
                return 400, {"error":
                             "body needs 'path' or 'registry'+'model'"}
            resolve_ms = (time.perf_counter() - t0) * 1e3
            from ..registry import aot as raot

            # pin the artifact's autotuned backends before any warmup or
            # ordinal binding (publish captured with the winners applied)
            tune = (manifest or {}).get("autotune")
            if tune and payload.get("autotune", True):
                from ..registry.autotune import apply_autotune

                autotune_applied = apply_autotune(stage, tune)
            # re-apply the artifact's declarative sharding BEFORE warmup
            # (warmup must compile the programs the serve loop will run —
            # sharded placement changes them). A mesh this host cannot
            # build demotes to a replicated load with one structured
            # warning; the swap itself never fails on topology.
            sharding_note = None
            shard_sec = (manifest or {}).get("sharding")
            if shard_sec:
                from ..parallel import partition as pshard

                applied, reason = pshard.apply_manifest_sharding(
                    stage, shard_sec,
                    enabled=payload.get("sharding", True),
                    model=payload.get("model"), version=version)
                sharding_note = "applied" if applied \
                    else f"replicated ({reason})"
            aot_cfg = (manifest or {}).get("aot") or {}
            warmup_rows = payload.get("warmup") or []
            warmup_buckets = payload.get("warmup_buckets")
            if aot_cfg.get("entries"):
                if not payload.get("aot", True):
                    fallback_reason = "aot disabled by request"
                elif tune and not payload.get("autotune", True):
                    # the shipped executables were compiled with the tuned
                    # backends baked in — serving them under saved-default
                    # configs would silently run the tuned kernels anyway
                    fallback_reason = ("autotune disabled by request but "
                                       "the aot executables were compiled "
                                       "with the tuned backends")
                else:
                    fallback_reason = raot.load_blocker(aot_cfg)
                recorded = aot_cfg.get("warmup", {})
                # the manifest-recorded rows drive warmup either way; a
                # JIT fallback keeps the default small-rung cap — its
                # compiles are real again
                warmup_rows = warmup_rows or recorded.get("rows") or []
                if fallback_reason is None:
                    provider = raot.AOTExecutableSet(aot_cfg, aot_dir)
                    # the rung cap lifts ONLY for true zero-compile loads:
                    # 'export' blobs skip tracing but still XLA-compile at
                    # load, so replaying the full ladder could outlast the
                    # deploy-plane timeout exactly like JIT warmup would
                    if warmup_buckets is None \
                            and provider.mechanism == "xla":
                        warmup_buckets = recorded.get("buckets")
                else:
                    raot.log_fallback(fallback_reason,
                                      model=payload.get("model"),
                                      version=version)
            stats0 = cache.stats()
            if provider is not None:
                cache.install_aot_provider(provider)
                provider.begin_binding()
            try:
                warmed = self._warmup(stage, warmup_rows, warmup_buckets)
            finally:
                if provider is not None:
                    provider.freeze()
            stats1 = cache.stats()
        except Exception as e:  # noqa: BLE001 - any failure must 409, not swap
            if provider is not None:
                cache.remove_aot_provider(provider)
            if stage is not None:
                # the discarded candidate's warmed entries would otherwise
                # pin its weights in the cache with no owner to evict them
                cb.release_executables(stage)
            _SERVING_METRICS.get()["swaps"].inc(outcome="failed")
            return 409, {"error": f"{type(e).__name__}: {e}"}
        breakdown = {
            "mode": "aot" if provider is not None else "jit",
            "fallback_reason": fallback_reason,
            "io_ms": round(resolve_ms
                           + (provider.io_ms if provider else 0.0), 2),
            "compile_ms": round(stats1["trace_ms_total"]
                                - stats0["trace_ms_total"], 2),
            "aot_hits": provider.hits if provider else 0,
            "aot_misses": provider.misses if provider else 0,
            "aot_errors": provider.errors if provider else 0,
            "executables_loaded": provider.loaded if provider else 0,
            "executables_traced": stats1["misses"] - stats0["misses"],
            "rows": warmed,
        }
        if autotune_applied:
            breakdown["autotune"] = autotune_applied
        if sharding_note is not None:
            breakdown["sharding"] = sharding_note
        raot.emit_load_metrics(breakdown)
        replaced = holder.pipeline
        previous = holder.swap(stage, version)
        # evict the replaced pipeline's executables: every swap would
        # otherwise pin one more dead model's weights in the CompiledCache
        # until LRU churn (in-flight batches on the old pipeline keep their
        # callables; they just can't be re-acquired) — and detach its AOT
        # blob tier
        if replaced is not stage:
            cb.release_executables(replaced)
        old_provider = self._aot_provider
        if old_provider is not None and old_provider is not provider:
            cache.remove_aot_provider(old_provider)
        self._aot_provider = provider
        self.last_swap_report = breakdown
        _SERVING_METRICS.get()["swaps"].inc(outcome="ok")
        return 200, {"ok": True, "version": version, "previous": previous,
                     "warmup_rows": warmed, "warmup": breakdown,
                     "load_ms": round((time.perf_counter() - t0) * 1e3, 2)}

    # ---- micro-batch source/sink API (HTTPMicroBatchReader / HTTPWriter) ----
    def _empty_batch(self) -> DataFrame:
        """The schema'd empty batch (not an empty-dict partition, which
        breaks downstream schema checks). Built ONCE and reused — the serve
        loop polls this on every idle tick, and four fresh numpy arrays per
        poll was measurable allocator churn. Callers only read it."""
        cached = self.__dict__.get("_empty_batch_cache")
        if cached is None:
            empty = np.empty(0, dtype=object)
            cached = DataFrame([{"id": empty, "method": empty.copy(),
                                 "path": empty.copy(), "body": empty.copy()}])
            self.__dict__["_empty_batch_cache"] = cached
        return cached

    def _finish_batch(self, exchanges: list) -> DataFrame:
        """Exchanges -> DataFrame, dropping requests whose reply deadline
        already passed (their handler thread has 504'd and gone — feeding
        them to the pipeline would burn compute a slow batch can't spare)
        and recording queue-wait + occupancy."""
        now = time.perf_counter()
        live, dropped = [], []
        for e in exchanges:
            (live if now - e.enqueued_at < self.reply_timeout_s
             else dropped).append(e)
        m = _SERVING_METRICS.get()
        if dropped:
            m["expired"].inc(len(dropped))
            # terminal reply for every dropped exchange: a handler racing
            # the deadline (clock skew, a just-under-the-wire dequeue) must
            # wake NOW with an error, never park out its full timeout on a
            # request the scheduler has already abandoned
            for e in dropped:
                e.respond({"error": "request expired in queue before "
                                    "batch pickup"}, status=504)
        if not live:
            return self._empty_batch()
        # queue wait = enqueue -> drained into a batch (the micro-batch
        # scheduling delay, distinct from transform time)
        qw = m["queue_wait"]
        for e in live:
            qw.observe((now - e.enqueued_at) * 1e3)
        m["batch_rows"].observe(len(live))
        return DataFrame([{
            "id": np.asarray([e.request_id for e in live], dtype=object),
            "method": np.asarray([e.method for e in live], dtype=object),
            "path": np.asarray([e.path for e in live], dtype=object),
            "body": np.asarray([e.body for e in live], dtype=object),
        }])

    def read_batch(self, max_rows: int = 1024, timeout_s: float = 0.1) -> DataFrame:
        """Drain queued requests into a DataFrame (id, method, path, body) —
        the fixed-timeout scheduler: returns as soon as anything is queued."""
        exchanges: list[_Exchange] = []
        try:
            exchanges.append(self._queue.get(timeout=timeout_s))
            while len(exchanges) < max_rows:
                exchanges.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        if not exchanges:
            return self._empty_batch()
        return self._finish_batch(exchanges)

    def read_batch_adaptive(self, max_rows: int = 1024,
                            latency_budget_s: float = 0.01,
                            poll_timeout_s: float = 0.05,
                            ladder: "tuple[int, ...] | None" = None,
                            min_fill: int = 2) -> DataFrame:
        """Continuous-batching scheduler: drain what's queued, then

        * flush IMMEDIATELY when the batch exactly fills a ladder rung (a
          full bucket's worth is queued — zero padding, no reason to wait),
        * flush immediately when fewer than ``min_fill`` requests showed up
          (an idle queue: waiting would only add latency at low load),
        * otherwise wait for more — but never past the OLDEST queued
          request's latency budget, so the per-request deadline bounds batch
          assembly and a slow batch cannot starve the queue.

        Expired requests (handler already 504'd) are dropped, not served."""
        rungs = frozenset(ladder if ladder is not None
                          else cb.default_bucketer().ladder)
        try:
            first = self._queue.get(timeout=poll_timeout_s)
        except queue.Empty:
            return self._empty_batch()
        exchanges = [first]
        deadline = first.enqueued_at + latency_budget_s
        while len(exchanges) < max_rows:
            try:
                # drain the backlog greedily — a deep queue fills toward
                # max_rows before any rung/budget decision
                exchanges.append(self._queue.get_nowait())
                continue
            except queue.Empty:
                pass
            if len(exchanges) in rungs:
                break  # a full bucket's worth is queued: flush early
            if len(exchanges) < min_fill:
                break  # idle queue: flush now, don't tax low-load latency
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break  # the oldest request's budget is spent
            try:
                exchanges.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        return self._finish_batch(exchanges)

    def exchange_for(self, request_id: str) -> "_Exchange | None":
        """The still-parked exchange for ``request_id`` (None once its
        handler gave up) — the token scheduler uses it to stream chunks
        back through the originating connection."""
        with self._lock:
            return self._pending.get(str(request_id))

    def reply_batch(self, df: DataFrame, id_col: str = "id",
                    reply_col: str = "reply", status: int = 200) -> int:
        """Route replies back by request id (``HTTPSinkV2`` / ``ServingUDFs``)."""
        if df.is_empty():
            return 0
        ids = df.collect_column(id_col)
        replies = df.collect_column(reply_col)
        # one lock acquisition for the whole batch (was once per row);
        # respond() happens outside the lock — it only sets the handler's
        # Event, and holding _lock across N wakeups would serialize them
        with self._lock:
            found = [(self._pending.get(str(rid)), reply)
                     for rid, reply in zip(ids, replies)]
        n = 0
        logger = self.request_logger
        now = time.perf_counter()
        holder = self.pipeline_holder
        version = holder.version if holder is not None else None
        for ex, reply in found:
            if ex is not None:
                ex.respond(reply, status=status)
                n += 1
                if logger is not None:
                    # after respond(): the handler thread is already awake,
                    # the log call cannot add to its latency
                    logger.log(method=ex.method, path=ex.path, body=ex.body,
                               reply=reply, status=status,
                               latency_ms=(now - ex.enqueued_at) * 1e3,
                               version=version)
        return n


def run_warmup(stage, rows: list, buckets: list, loop_cfg: dict) -> int:
    """The ONE warmup drive shared by ``/admin/load`` (precompile before a
    hot swap) and publish-time AOT capture (``registry/aot.py``): cycle
    ``rows`` (JSON-able request bodies) up to each bucket size and
    transform once per bucket through the EXACT serve-loop batch
    preparation. The two callers sharing this path is what makes AOT
    ordinal binding sound — publish capture and load warmup replay the
    same stage-execution order. Returns total rows driven."""
    if not rows:
        return 0
    bodies = [r if isinstance(r, bytes)
              else (r.encode() if isinstance(r, str)
                    else json.dumps(r).encode()) for r in rows]
    sizes = sorted({int(b) for b in buckets} | {len(bodies)})
    total = 0
    for size in sizes:
        batch_bodies = [bodies[i % len(bodies)] for i in range(size)]
        batch = DataFrame([{
            "id": np.asarray([f"warmup-{i}" for i in range(size)],
                             dtype=object),
            "method": np.asarray(["POST"] * size, dtype=object),
            "path": np.asarray(["/"] * size, dtype=object),
            "body": np.asarray(batch_bodies, dtype=object),
        }])
        batch = _prepare_batch(batch, **loop_cfg)
        stage.transform(batch)
        total += size
    return total


def _prepare_batch(batch: DataFrame, parse_json: bool = True,
                   input_col: str = "body") -> DataFrame:
    """Request-batch input preparation, shared verbatim between the serve
    loop and the /admin/load warmup path."""
    if parse_json:
        def parse(p):
            out = np.empty(len(p["body"]), dtype=object)
            for i, b in enumerate(p["body"]):
                try:
                    out[i] = json.loads(b.decode() or "null")
                except (json.JSONDecodeError, UnicodeDecodeError):
                    out[i] = None
            return out

        return batch.with_column(input_col, parse)
    if input_col != "body":
        return batch.with_column(input_col, lambda p: p["body"])
    return batch


def serve_pipeline(pipeline, port: int = 0, batch_interval_ms: int = 10,
                   input_col: str = "body", reply_col: str = "reply",
                   parse_json: bool = True, num_threads: int = 1,
                   version: str | None = None,
                   scheduler: str = "adaptive",
                   latency_budget_ms: float | None = None,
                   bucket_ladder=None,
                   max_batch_rows: int = 1024) -> ServingServer:
    """Run a Transformer as an HTTP service: request body -> ``input_col`` ->
    pipeline.transform -> ``reply_col`` -> response body. ``batch_interval_ms=0``
    replies per-request (continuous mode); ``num_threads`` transform loops
    drain the queue concurrently (for pipelines that release the GIL or do
    IO — the reference's concurrent continuous path).

    Micro-batch mode runs the CONTINUOUS-BATCHING scheduler by default
    (``scheduler="adaptive"``): flush as soon as a full bucket ladder rung
    is queued, wait up to ``latency_budget_ms`` (default: the batch
    interval) otherwise, and never past the oldest request's budget.
    ``scheduler="fixed"`` keeps the old fixed-timeout poll (the A/B
    baseline the serving-microbatch bench compares against).
    ``bucket_ladder`` pins the flush rungs AND the ``/admin/load`` warmup
    precompile set; by default both resolve to the process-wide pow-2
    ladder capped at ``max_batch_rows``, so a warmed hot swap never
    compile-stalls at any rung the scheduler can flush.

    The pipeline lives in a :class:`PipelineHolder` (``version`` labels the
    initial one; pass a holder directly to share it), so ``POST /admin/load``
    can hot-swap a new version mid-serve: in-flight batches finish on the
    old pipeline, the next batch reads the new one — zero dropped requests."""
    if scheduler not in ("adaptive", "fixed"):
        raise ValueError(f"scheduler must be 'adaptive' or 'fixed', "
                         f"got {scheduler!r}")
    server = ServingServer(port=port)
    holder = (pipeline if isinstance(pipeline, PipelineHolder)
              else PipelineHolder(pipeline, version))
    server.pipeline_holder = holder
    server._loop_cfg = {"parse_json": parse_json, "input_col": input_col}
    if bucket_ladder is not None:
        # explicit config: flush AND warm the full ladder (the caller opted
        # into its warmup cost for the zero-compile-stall guarantee)
        server._bucket_ladder = tuple(sorted({int(b) for b in bucket_ladder}))
        server._warmup_buckets = server._bucket_ladder
    elif batch_interval_ms != 0:
        # default micro-batch mode: flush at the process-wide ladder, but
        # precompile only the latency-sensitive small rungs — warming a
        # heavy model at every rung up to 1024 rows can outlast the deploy
        # plane's /admin/load timeout, and large batches amortize a compile
        # stall across their rows anyway
        server._bucket_ladder = tuple(
            b for b in cb.default_bucketer().ladder if b <= max_batch_rows)
        server._warmup_buckets = tuple(
            b for b in server._bucket_ladder if b <= _DEFAULT_WARMUP_CAP)
    budget_s = (batch_interval_ms if latency_budget_ms is None
                else latency_budget_ms) / 1000.0
    server.start()

    def read_next() -> DataFrame:
        if batch_interval_ms == 0:  # continuous: one row, reply per request
            return server.read_batch(max_rows=1, timeout_s=0.01)
        if scheduler == "fixed":
            return server.read_batch(
                max_rows=max_batch_rows,
                timeout_s=max(batch_interval_ms, 10) / 1000.0)
        return server.read_batch_adaptive(
            max_rows=max_batch_rows, latency_budget_s=budget_s,
            poll_timeout_s=max(batch_interval_ms, 10) / 1000.0,
            ladder=server._bucket_ladder)

    def loop():
        while server._running:
            batch = read_next()
            if batch.is_empty():
                continue
            batch = _prepare_batch(batch, parse_json=parse_json,
                                   input_col=input_col)
            stage, _version = holder.get()
            try:
                replied = stage.transform(batch)
                server.reply_batch(replied, reply_col=reply_col)
            except Exception as e:  # noqa: BLE001 - serve loop must survive
                err = {"error": str(e)}
                fallback = batch.with_column(reply_col,
                                             lambda p: np.asarray([err] * len(p["id"]),
                                                                  dtype=object))
                server.reply_batch(fallback, reply_col=reply_col, status=500)

    for _ in range(max(num_threads, 1)):
        threading.Thread(target=loop, daemon=True).start()
    return server


def serve_llm(stage, port: int = 0, poll_ms: float = 20.0,
              latency_budget_ms: float = 5.0, max_new_tokens_cap: int = 1024,
              max_waiting: int = 256, version: str | None = None,
              warmup: bool = True) -> ServingServer:
    """Token-granular LLM serving: the continuous-batching TOKEN scheduler
    over a paged-KV decode engine (``models/paged_engine.py``).

    ``stage`` is a causal-LM transformer exposing ``serving_engine()``
    (:class:`~synapseml_tpu.hf.HuggingFaceCausalLM`) or a
    :class:`PipelineHolder` of one. Request body::

        {"prompt": "...", "max_new_tokens": 32, "stream": false}

    Unlike ``serve_pipeline`` (whole-request micro-batches), the loop
    interleaves at token granularity: queued requests drain through
    ``read_batch_adaptive`` and PREFILL between decode steps, every decode
    step advances all active sequences one token, and a sequence that emits
    EOS or exhausts its budget frees its KV pages and decode slot
    immediately — no run-to-completion barrier, so short generations never
    wait out a long neighbor's tail. ``stream: true`` replies are chunked
    NDJSON (one ``{"token", "text"}`` object per token, then a terminal
    ``{"done": true, ...}`` record); non-streaming requests get one final
    JSON reply. Any request the scheduler dequeues but cannot serve (bad
    payload, overload, engine swap) receives a TERMINAL error reply — a
    client never blocks to its full timeout on a silently-dropped request.

    ``POST /admin/load`` hot-swaps stay zero-compile-stall: the loop
    rebuilds the engine from the swapped-in stage and ``warmup()``
    precompiles every prefill rung (seq ladder) and decode rung (slot
    ladder) BEFORE the new engine takes a request; the old engine's
    executables are evicted."""
    holder = (stage if isinstance(stage, PipelineHolder)
              else PipelineHolder(stage, version))
    if not hasattr(holder.pipeline, "serving_engine"):
        raise TypeError(
            f"serve_llm needs a stage exposing serving_engine() (e.g. "
            f"HuggingFaceCausalLM); got {type(holder.pipeline).__name__} — "
            f"use serve_pipeline for whole-request stages")
    server = ServingServer(port=port)
    server.pipeline_holder = holder
    server._loop_cfg = {"parse_json": True, "input_col": "prompt"}
    server.start()

    def build_engine(st):
        eng = st.serving_engine()
        if warmup:
            eng.warmup()
        return eng

    open_streams: dict[str, object] = {}  # request_id -> exchange
    state = {"engine": None}  # the drain barrier reads the live engine

    def dispatch(engine, events):
        for ev in events:
            seq = ev["seq"]
            rid = seq.request_id
            if rid is None:
                continue
            ex = open_streams.get(rid) or server.exchange_for(rid)
            if ex is None or getattr(ex, "client_gone", False):
                # handler timed out or the socket died mid-stream: stop
                # decoding into a dead connection — free pages + slot NOW
                if not ev["done"]:
                    engine.abort(seq, reason="client_gone")
                open_streams.pop(rid, None)
                continue
            if seq.stream:
                if rid not in open_streams:
                    ex.stream_begin()
                    open_streams[rid] = ex
                if ev["token"] is not None:
                    ch = engine.chunk_for(ev)
                    if isinstance(ch, dict):
                        # monotonic per-request chunk number = the GLOBAL
                        # token index, so a migrated/resumed continuation
                        # keeps counting where the origin stopped and the
                        # front's journal dedups across handoffs exactly;
                        # uid lets a crash resubmit keep the origin's
                        # sampling stream
                        ch.setdefault("seq", len(seq.generated) - 1)
                        ch.setdefault("uid", seq.uid)
                    ex.stream_chunk(ch)
                if ev["done"]:
                    res = engine.result_for(seq)
                    if isinstance(res, dict):
                        res.setdefault("seq", len(seq.generated))
                    ex.stream_chunk(res)
                    ex.stream_end()
                    open_streams.pop(rid, None)
            elif ev["done"]:
                # a deadline expiry is the worker's fault-containment 504,
                # not a successful generation
                status = (504 if ev.get("finish_reason") == "deadline"
                          else 200)
                ex.respond(engine.result_for(seq), status=status)

    def fail_inflight(engine, err, status=503):
        """TERMINAL replies for EVERY in-flight request when the engine
        goes away (hot swap, device failure): live streaming exchanges get
        an error chunk + stream end — never a silent hang to client
        timeout — and parked buffered exchanges get an error reply."""
        for rid, ex in list(open_streams.items()):
            ex.stream_chunk({"error": err, "done": True})
            ex.stream_end()
            open_streams.pop(rid, None)
        try:
            doomed = engine.abort_all()
        except Exception:  # noqa: BLE001 — a dead engine must not block
            doomed = []    # the terminal replies
        for seq in doomed:
            if not seq.request_id:
                continue
            ex = server.exchange_for(seq.request_id)
            if ex is None:
                continue
            if ex.streaming:
                # stream_begin happened but the handler hasn't dequeued
                # yet — terminal error rides the chunk channel
                ex.stream_chunk({"error": err, "done": True})
                ex.stream_end()
            else:
                ex.respond({"error": err}, status=status)

    def migrate_out(engine):
        """Live drain: export every front-relayed sequence and hand it to
        a peer via the front's /admin/migrate mailbox. On handoff failure
        the snapshot re-imports locally (the sequence finishes here under
        the drain barrier) — a failed migration degrades to the old
        run-to-completion drain, never to a lost request."""
        target = server.migrate_to
        if target is None or not hasattr(engine, "export"):
            return
        m = _SERVING_METRICS.get()
        for seq in list(engine.live_requests()):
            rid = seq.request_id
            key = getattr(seq, "journal_key", None)
            if rid is None or key is None or not seq.stream:
                continue  # not front-relayed: no peer can splice its
                #           stream — it finishes locally instead
            t0 = time.perf_counter()
            n_emitted = len(seq.generated)
            snap = engine.export(seq.uid)
            if snap is None:
                continue
            ok = _post_json(target.rstrip("/") + "/admin/migrate",
                            {"key": key, "snapshot": snap})
            if ok:
                m["migrations"].inc(reason="drain", outcome="ok")
                m["migration_ms"].observe((time.perf_counter() - t0) * 1e3)
                ex = open_streams.pop(rid, None) \
                    or server.exchange_for(rid)
                if ex is not None:
                    # in-band handoff marker: the front stops reading this
                    # stream and splices the peer's continuation; seq-
                    # numbered chunks make the cutover dup/loss-free
                    ex.stream_chunk({"__migrated__": True,
                                     "seq": n_emitted})
                    ex.stream_end()
            else:
                m["migrations"].inc(reason="drain", outcome="error")
                try:
                    engine.import_snapshot(snap, rid, journal_key=key)
                except Exception:  # noqa: BLE001 — local re-import of a
                    pass           # just-exported snapshot
        # non-migratable work keeps decoding while draining; the drain
        # barrier holds on_drained until it finishes or times out

    def drain_barrier(budget_s: float) -> None:
        deadline = time.monotonic() + max(float(budget_s), 0.0)
        while time.monotonic() < deadline:
            eng = state["engine"]
            if eng is None or (not eng.has_work() and not open_streams):
                return
            time.sleep(0.02)

    server.drain_barrier = drain_barrier

    def llm_stats():
        # reads the LIVE engine (hot-swaps rebuild it), so /admin/stats
        # always reflects the serving engine, not the one at boot
        eng = state["engine"]
        if eng is None or not hasattr(eng, "stats"):
            return None
        return eng.stats()

    server.llm_stats_fn = llm_stats

    def loop():
        # ONE consistent snapshot: a hot-swap landing during this (long,
        # warmup-heavy) build must still trip the v != current check below
        stage0, current = holder.get()
        engine = build_engine(stage0)
        state["engine"] = engine
        while server._running:
            try:
                engine, current = _iterate(engine, current)
                state["engine"] = engine
            except Exception as e:  # noqa: BLE001 — scheduler must survive
                # an engine failure fails every in-flight request with a
                # TERMINAL reply (never a silent stall to client timeout)
                fail_inflight(engine, f"engine failure: {e}")
                # the failed call may have consumed the DONATED page-pool
                # buffers mid-step, leaving the engine unusable — rebuild
                # it rather than retrying into deleted buffers
                try:
                    engine.release()
                    st, v = holder.get()
                    engine = build_engine(st)
                    state["engine"] = engine
                    current = v
                except Exception:  # noqa: BLE001 — retry next iteration
                    time.sleep(0.5)

    def _iterate(engine, current):
            stage_now, v = holder.get()
            if v != current:
                # hot swap: precompile the replacement's rungs, then cut
                # over between steps; in-flight sequences finish... they
                # cannot — the pages live in the old engine — so every one
                # of them (streaming AND buffered) gets a terminal error
                # instead of a silent stall
                old, engine = engine, build_engine(stage_now)
                state["engine"] = engine
                current = v
                fail_inflight(old, "pipeline hot-swapped mid-generation")
                old.release()
            if server.draining:
                migrate_out(engine)
            busy = engine.has_work()
            # busy: drain without blocking — a 1 ms queue wait would tax
            # EVERY decode step of every active sequence; idle: block on
            # the poll interval
            batch = server.read_batch_adaptive(
                max_rows=64, latency_budget_s=latency_budget_ms / 1e3,
                poll_timeout_s=(0.0 if busy else max(poll_ms, 1.0) / 1e3))
            if not batch.is_empty():
                ids = batch.collect_column("id")
                bodies = batch.collect_column("body")
                for rid, body in zip(ids, bodies):
                    rid = str(rid)
                    ex = server.exchange_for(rid)
                    if ex is None:
                        continue
                    if engine.waiting_count >= max_waiting:
                        ex.respond({"error": "LLM queue full"}, status=503)
                        continue
                    try:
                        payload = json.loads(body.decode() or "null")
                        deadline = None
                        dl = _header(ex.headers, "X-Deadline-Ms")
                        if dl is not None:
                            # client deadline propagates front -> worker as
                            # a remaining-budget header; the engine expires
                            # the sequence past it (pages freed, 504)
                            deadline = (time.perf_counter()
                                        + float(dl) / 1e3)
                        jkey = _header(ex.headers, "X-Request-Key")
                        if isinstance(payload, dict) \
                                and "__import__" in payload:
                            # live-migration continuation: adopt the peer's
                            # exported KV pages (or re-prefill on mismatch)
                            engine.import_snapshot(
                                payload["__import__"], rid,
                                deadline=deadline, journal_key=jkey)
                        elif isinstance(payload, dict) \
                                and "__resume__" in payload:
                            # crash-path resubmit from the front's journal:
                            # re-prefill over prompt + already-relayed ids
                            engine.resume(payload["__resume__"], rid,
                                          max_new_cap=max_new_tokens_cap,
                                          deadline=deadline,
                                          journal_key=jkey)
                        else:
                            engine.submit(payload, rid,
                                          max_new_cap=max_new_tokens_cap,
                                          deadline=deadline,
                                          journal_key=jkey)
                    except (ValueError, TypeError, KeyError, IndexError,
                            UnicodeDecodeError) as e:
                        # one malformed body is THAT client's 400, never an
                        # engine failure that aborts everyone else
                        ex.respond({"error": f"bad request: {e}"}, status=400)
            dispatch(engine, engine.admit())
            dispatch(engine, engine.step())
            return engine, current

    threading.Thread(target=loop, daemon=True).start()
    return server
