"""Model serving: HTTP requests in, pipeline transform, replies out.

Reference (SURVEY.md §3.4): Spark Serving's ``HTTPSourceV2``/``HTTPSinkV2`` —
an HTTP source enqueues requests as rows tagged with a request id, the user
pipeline transforms request rows into reply rows, and the sink routes each
reply back to the originating open connection by request id
(``continuous/HTTPSinkV2.scala:74-154``, ``HTTPServerUtils.respond``).

Here: a threaded stdlib HTTP server parks each connection on an Event;
``ServingServer.read_batch`` drains the queue into a DataFrame (micro-batch
mode, ``HTTPMicroBatchReader`` analog); ``reply_batch`` completes the parked
exchanges. ``serve_pipeline`` wires a Transformer into the loop — micro-batch
with ``batch_interval_ms`` or per-request continuous mode (``interval=0``,
the reference's sub-millisecond continuous path).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
import socket
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..core import observability as obs
from ..core.dataframe import DataFrame

__all__ = ["ServingServer", "serve_pipeline", "NoDelayHTTPServer",
           "PipelineHolder"]

# hot-path metric handles, re-resolved only when the registry is replaced
_SERVING_METRICS = obs.HandleCache(lambda reg: {
    "request_ms": reg.histogram(
        "synapseml_serving_request_duration_ms",
        "worker HTTP request latency", ("method",)),
    "requests": reg.counter(
        "synapseml_serving_requests_total",
        "worker HTTP requests by status class", ("method", "status")),
    "queue_wait": reg.histogram(
        "synapseml_serving_queue_wait_ms",
        "request time spent queued before batch pickup").labels(),
    "swaps": reg.counter(
        "synapseml_serving_pipeline_swaps_total",
        "hot pipeline swaps on this worker, by outcome", ("outcome",)),
})


class PipelineHolder:
    """The mutable slot the serving loop reads its pipeline from.

    Hot-swap (``POST /admin/load``) loads the replacement side-by-side,
    warms it, then calls :meth:`swap` — one attribute assignment under a
    lock, so in-flight batches finish on the old pipeline and the next
    batch picks up the new one with zero dropped requests. ``subscribe``
    registers post-swap callbacks (the distributed worker re-registers its
    new version with the driver registry through one)."""

    def __init__(self, pipeline, version: str | None = None):
        self._lock = threading.Lock()
        self._pipeline = pipeline
        self._version = version
        self._callbacks: list = []

    @property
    def version(self) -> str | None:
        with self._lock:
            return self._version

    @property
    def pipeline(self):
        with self._lock:
            return self._pipeline

    def get(self):
        """(pipeline, version) — one consistent snapshot."""
        with self._lock:
            return self._pipeline, self._version

    def subscribe(self, fn) -> None:
        """``fn(new_version, old_version)`` after every successful swap."""
        self._callbacks.append(fn)

    def swap(self, pipeline, version: str | None = None) -> str | None:
        with self._lock:
            old = self._version
            self._pipeline = pipeline
            self._version = version
        for fn in list(self._callbacks):
            try:
                fn(version, old)
            except Exception:  # noqa: BLE001 - a callback must not undo a swap
                pass
        return old


class NoDelayHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that sets TCP_NODELAY on every accepted socket.
    With HTTP/1.1 keep-alive, Nagle + the peer's delayed ACK turns each
    small-write response into a ~40 ms stall; sub-millisecond serving (the
    reference's claim) requires segments to go out immediately. Enforced
    here at accept time so no Handler class can forget it."""

    def get_request(self):
        sock, addr = super().get_request()
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock, addr


class _Exchange:
    def __init__(self, request_id: str, method: str, path: str, headers: dict,
                 body: bytes):
        self.request_id = request_id
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body
        self.enqueued_at = time.perf_counter()  # queue-wait measurement
        self.reply_event = threading.Event()
        self.reply_body: bytes = b""
        self.reply_status: int = 200
        self.reply_headers: dict = {}

    def respond(self, body, status: int = 200, headers: dict | None = None):
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
            headers = {"Content-Type": "application/json", **(headers or {})}
        elif isinstance(body, str):
            body = body.encode()
        self.reply_body = body or b""
        self.reply_status = status
        self.reply_headers = headers or {}
        self.reply_event.set()


class ServingServer:
    """(ref ``HTTPSourceV2``/``DistributedHTTPSource``)"""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 reply_timeout_s: float = 30.0, max_queue: int = 4096):
        self.reply_timeout_s = reply_timeout_s
        # set by serve_pipeline: the hot-swap slot + the loop's parsing
        # config (the /admin/load warmup must prepare batches EXACTLY like
        # the serve loop does, or warmup success proves nothing)
        self.pipeline_holder: PipelineHolder | None = None
        self._loop_cfg = {"parse_json": True, "input_col": "body"}
        # bounded: a stalled pipeline sheds load with 503s instead of parking
        # unbounded connections (backpressure the round-1 loop lacked)
        self._queue: "queue.Queue[_Exchange]" = queue.Queue(maxsize=max_queue)
        self._pending: dict[str, _Exchange] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: connections persist across requests — the per-request
            # TCP handshake is most of the loopback round-trip (the
            # reference's JVMSharedServer keeps executor sockets open too);
            # the server sets TCP_NODELAY at accept (NoDelayHTTPServer)
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply_bytes(self, status: int, payload: bytes,
                             content_type: str | None = None) -> None:
                self.send_response(status)
                if content_type:
                    self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                if payload:
                    self.wfile.write(payload)

            def _handle(self, method: str):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if method == "GET" and self.path == "/metrics":
                    payload, ctype = obs.prometheus_exposition()
                    self._reply_bytes(200, payload, ctype)
                    return
                if method == "GET" and self.path == "/trace":
                    payload = json.dumps(
                        obs.get_tracer().spans_as_dicts()).encode()
                    self._reply_bytes(200, payload, "application/json")
                    return
                # deployment-plane admin endpoints (registry/deploy.py):
                # handled here, never queued behind the pipeline
                if method == "GET" and self.path == "/admin/version":
                    self._reply_bytes(
                        200, json.dumps(outer._admin_version()).encode(),
                        "application/json")
                    return
                if method == "POST" and self.path == "/admin/load":
                    status, reply = outer._admin_load(body)
                    self._reply_bytes(status, json.dumps(reply).encode(),
                                      "application/json")
                    return
                # one span per served request, stitched to the caller's trace
                # via the W3C traceparent header the RoutingFront injects
                tracer = obs.get_tracer()
                parent = obs.extract_context(self.headers)
                t0 = time.perf_counter()
                status = None  # stays None when _exchange raises -> "error"
                try:
                    with tracer.span("serving.request",
                                     {"path": self.path, "method": method},
                                     parent=parent):
                        status = self._exchange(method, body)
                finally:
                    dur_ms = (time.perf_counter() - t0) * 1e3
                    m = _SERVING_METRICS.get()
                    m["request_ms"].observe(dur_ms, method=method)
                    m["requests"].inc(
                        method=method,
                        status=(f"{status // 100}xx" if status is not None
                                else "error"))

            def _exchange(self, method: str, body: bytes) -> int:
                ex = _Exchange(uuid.uuid4().hex, method, self.path,
                               dict(self.headers), body)
                with outer._lock:
                    outer._pending[ex.request_id] = ex
                try:
                    outer._queue.put_nowait(ex)
                except queue.Full:
                    with outer._lock:
                        outer._pending.pop(ex.request_id, None)
                    self.send_response(503)  # shed load under backpressure
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return 503
                ok = ex.reply_event.wait(outer.reply_timeout_s)
                with outer._lock:
                    outer._pending.pop(ex.request_id, None)
                if not ok:
                    self.send_response(504)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return 504
                self.send_response(ex.reply_status)
                for k, v in ex.reply_headers.items():
                    if k.lower() != "content-length":  # we set the real one
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(ex.reply_body)))
                self.end_headers()
                self.wfile.write(ex.reply_body)
                return ex.reply_status

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

        self._server = NoDelayHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._running = False

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingServer":
        self._thread.start()
        self._running = True
        return self

    def stop(self) -> None:
        if self._running:
            self._server.shutdown()
            self._server.server_close()
            self._running = False

    # ---- deployment-plane admin (hot swap; registry/deploy.py) ----
    def _admin_version(self) -> dict:
        holder = self.pipeline_holder
        if holder is None:
            return {"version": None, "pipeline": None}
        pipeline, version = holder.get()
        return {"version": version, "pipeline": type(pipeline).__name__}

    def _warmup(self, stage, rows: list) -> int:
        """Run ``rows`` (JSON-able request bodies) through ``stage`` with
        the SAME batch preparation the serve loop uses. Raises on any
        transform failure — a pipeline that cannot serve its warmup batch
        must never be swapped in."""
        if not rows:
            return 0
        bodies = [r if isinstance(r, bytes)
                  else (r.encode() if isinstance(r, str)
                        else json.dumps(r).encode()) for r in rows]
        batch = DataFrame([{
            "id": np.asarray([f"warmup-{i}" for i in range(len(bodies))],
                             dtype=object),
            "method": np.asarray(["POST"] * len(bodies), dtype=object),
            "path": np.asarray(["/"] * len(bodies), dtype=object),
            "body": np.asarray(bodies, dtype=object),
        }])
        batch = _prepare_batch(batch, **self._loop_cfg)
        stage.transform(batch)
        return len(bodies)

    def _admin_load(self, body: bytes) -> tuple[int, dict]:
        """Load a new pipeline version side-by-side, warm it, atomically
        swap. Body: ``{"path": <stage dir>}`` or ``{"registry": <root or
        url>, "model": <name>, "ref": <version or alias>}``, plus optional
        ``"version"`` label and ``"warmup"`` (list of request bodies). The
        old pipeline keeps serving until the instant of the swap; a load or
        warmup failure leaves it untouched (409)."""
        holder = self.pipeline_holder
        if holder is None:
            return 409, {"error": "this server has no swappable pipeline "
                                  "(started without serve_pipeline?)"}
        try:
            payload = json.loads(body.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return 400, {"error": f"bad JSON body: {e}"}
        if not isinstance(payload, dict):
            return 400, {"error": "body must be a JSON object"}
        t0 = time.perf_counter()
        try:
            if "path" in payload:
                from ..core.serialization import load_stage

                stage = load_stage(payload["path"])
                version = (payload.get("version")
                           or os.path.basename(
                               str(payload["path"]).rstrip("/")))
            elif "registry" in payload and "model" in payload:
                from ..registry.registry import ModelRegistry

                resolved = ModelRegistry(payload["registry"]).resolve(
                    payload["model"], payload.get("ref", "latest"))
                stage, version = resolved.stage, resolved.version
            else:
                return 400, {"error":
                             "body needs 'path' or 'registry'+'model'"}
            warmed = self._warmup(stage, payload.get("warmup") or [])
        except Exception as e:  # noqa: BLE001 - any failure must 409, not swap
            _SERVING_METRICS.get()["swaps"].inc(outcome="failed")
            return 409, {"error": f"{type(e).__name__}: {e}"}
        previous = holder.swap(stage, version)
        _SERVING_METRICS.get()["swaps"].inc(outcome="ok")
        return 200, {"ok": True, "version": version, "previous": previous,
                     "warmup_rows": warmed,
                     "load_ms": round((time.perf_counter() - t0) * 1e3, 2)}

    # ---- micro-batch source/sink API (HTTPMicroBatchReader / HTTPWriter) ----
    def read_batch(self, max_rows: int = 1024, timeout_s: float = 0.1) -> DataFrame:
        """Drain queued requests into a DataFrame (id, method, path, body)."""
        exchanges: list[_Exchange] = []
        try:
            exchanges.append(self._queue.get(timeout=timeout_s))
            while len(exchanges) < max_rows:
                exchanges.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        if exchanges:
            # queue wait = enqueue -> drained into a batch (the micro-batch
            # scheduling delay, distinct from transform time)
            qw = _SERVING_METRICS.get()["queue_wait"]
            now = time.perf_counter()
            for e in exchanges:
                qw.observe((now - e.enqueued_at) * 1e3)
        if not exchanges:
            # schema'd empty batch (not an empty-dict partition, which breaks
            # downstream schema checks)
            empty = np.empty(0, dtype=object)
            return DataFrame([{"id": empty, "method": empty.copy(),
                               "path": empty.copy(), "body": empty.copy()}])
        ids = np.asarray([e.request_id for e in exchanges], dtype=object)
        return DataFrame([{
            "id": ids,
            "method": np.asarray([e.method for e in exchanges], dtype=object),
            "path": np.asarray([e.path for e in exchanges], dtype=object),
            "body": np.asarray([e.body for e in exchanges], dtype=object),
        }])

    def reply_batch(self, df: DataFrame, id_col: str = "id",
                    reply_col: str = "reply", status: int = 200) -> int:
        """Route replies back by request id (``HTTPSinkV2`` / ``ServingUDFs``)."""
        if df.is_empty():
            return 0
        n = 0
        ids = df.collect_column(id_col)
        replies = df.collect_column(reply_col)
        for rid, reply in zip(ids, replies):
            with self._lock:
                ex = self._pending.get(str(rid))
            if ex is not None:
                ex.respond(reply, status=status)
                n += 1
        return n


def _prepare_batch(batch: DataFrame, parse_json: bool = True,
                   input_col: str = "body") -> DataFrame:
    """Request-batch input preparation, shared verbatim between the serve
    loop and the /admin/load warmup path."""
    if parse_json:
        def parse(p):
            out = np.empty(len(p["body"]), dtype=object)
            for i, b in enumerate(p["body"]):
                try:
                    out[i] = json.loads(b.decode() or "null")
                except (json.JSONDecodeError, UnicodeDecodeError):
                    out[i] = None
            return out

        return batch.with_column(input_col, parse)
    if input_col != "body":
        return batch.with_column(input_col, lambda p: p["body"])
    return batch


def serve_pipeline(pipeline, port: int = 0, batch_interval_ms: int = 10,
                   input_col: str = "body", reply_col: str = "reply",
                   parse_json: bool = True, num_threads: int = 1,
                   version: str | None = None) -> ServingServer:
    """Run a Transformer as an HTTP service: request body -> ``input_col`` ->
    pipeline.transform -> ``reply_col`` -> response body. ``batch_interval_ms=0``
    replies per-request (continuous mode); ``num_threads`` transform loops
    drain the queue concurrently (for pipelines that release the GIL or do
    IO — the reference's concurrent continuous path).

    The pipeline lives in a :class:`PipelineHolder` (``version`` labels the
    initial one; pass a holder directly to share it), so ``POST /admin/load``
    can hot-swap a new version mid-serve: in-flight batches finish on the
    old pipeline, the next batch reads the new one — zero dropped requests."""
    server = ServingServer(port=port)
    holder = (pipeline if isinstance(pipeline, PipelineHolder)
              else PipelineHolder(pipeline, version))
    server.pipeline_holder = holder
    server._loop_cfg = {"parse_json": parse_json, "input_col": input_col}
    server.start()

    def loop():
        while server._running:
            batch = server.read_batch(
                max_rows=1 if batch_interval_ms == 0 else 1024,
                timeout_s=max(batch_interval_ms, 10) / 1000.0)
            if batch.is_empty():
                continue
            batch = _prepare_batch(batch, parse_json=parse_json,
                                   input_col=input_col)
            stage, _version = holder.get()
            try:
                replied = stage.transform(batch)
                server.reply_batch(replied, reply_col=reply_col)
            except Exception as e:  # noqa: BLE001 - serve loop must survive
                err = {"error": str(e)}
                fallback = batch.with_column(reply_col,
                                             lambda p: np.asarray([err] * len(p["id"]),
                                                                  dtype=object))
                server.reply_batch(fallback, reply_col=reply_col, status=500)

    for _ in range(max(num_threads, 1)):
        threading.Thread(target=loop, daemon=True).start()
    return server
