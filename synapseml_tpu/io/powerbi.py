"""PowerBI streaming-dataset writer.

Reference: ``core/.../io/powerbi/PowerBIWriter.scala`` — POST DataFrame rows to
a PowerBI push-dataset REST URL, per partition, in JSON batches with
retry/backoff (the streaming ``foreachBatch`` sink). PowerBI push datasets
accept ``[{col: value, ...}, ...]`` arrays, max ~10k rows per request.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from ..core.dataframe import DataFrame
from .http import HTTPRequest, send_with_retries

__all__ = ["PowerBIWriter"]


def _jsonable(v: Any):
    if isinstance(v, (np.floating, np.integer, np.bool_)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


class PowerBIWriter:
    """``PowerBIWriter.write(df, url)`` — batched row POSTs with per-batch
    retry; raises on the first failed batch (matching the reference's
    fail-the-stream semantics) unless ``error_col`` collection is requested."""

    def __init__(self, url: str, batch_size: int = 1000, timeout_s: float = 60.0,
                 concurrency: int = 1):
        if batch_size > 10_000:
            raise ValueError("PowerBI push datasets cap at 10000 rows/request")
        self.url = url
        self.batch_size = batch_size
        self.timeout_s = timeout_s
        self.concurrency = concurrency

    def _rows_of(self, part: dict) -> list[dict]:
        cols = list(part)
        n = len(part[cols[0]]) if cols else 0
        return [{c: _jsonable(part[c][i]) for c in cols} for i in range(n)]

    def write(self, df: DataFrame) -> int:
        """POST every row; returns the number of rows written."""
        written = 0
        for part in df.partitions:
            rows = self._rows_of(part)
            for s in range(0, len(rows), self.batch_size):
                chunk = rows[s: s + self.batch_size]
                resp = send_with_retries(
                    HTTPRequest(url=self.url, method="POST",
                                headers={"Content-Type": "application/json"},
                                entity=json.dumps(chunk)),
                    timeout_s=self.timeout_s)
                if resp is None or resp.error or resp.status_code // 100 != 2:
                    raise RuntimeError(
                        f"PowerBI write failed after retries at row {written}: "
                        f"{getattr(resp, 'error', None) or getattr(resp, 'status_code', '?')}")
                written += len(chunk)
        return written

    def write_stream(self, batches, stop_on_error: bool = True) -> int:
        """Consume an iterator of DataFrames (micro-batch sink role)."""
        total = 0
        for df in batches:
            try:
                total += self.write(df)
            except RuntimeError:
                if stop_on_error:
                    raise
        return total
