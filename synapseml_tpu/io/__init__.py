"""IO: HTTP-on-DataFrame client stack + model serving.

Reference: ``core/.../io/http/`` and the Spark Serving sources/sinks under
``org/apache/spark/sql/execution/streaming/`` (SURVEY.md §2.5, §3.4).
"""

from .http import (
    AsyncHTTPClient,
    CustomInputParser,
    HTTPRequest,
    HTTPResponse,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    SimpleHTTPTransformer,
    StringOutputParser,
    send_with_retries,
)
from .serving import ServingServer, serve_pipeline

__all__ = [
    "HTTPRequest", "HTTPResponse", "HTTPTransformer", "SimpleHTTPTransformer",
    "JSONInputParser", "JSONOutputParser", "CustomInputParser",
    "StringOutputParser", "AsyncHTTPClient", "send_with_retries",
    "ServingServer", "serve_pipeline",
]
