"""IO: HTTP-on-DataFrame client stack + model serving.

Reference: ``core/.../io/http/`` and the Spark Serving sources/sinks under
``org/apache/spark/sql/execution/streaming/`` (SURVEY.md §2.5, §3.4).
"""

from .http import (
    AsyncHTTPClient,
    CustomInputParser,
    HTTPRequest,
    HTTPResponse,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    SimpleHTTPTransformer,
    StringOutputParser,
    send_with_retries,
)
from .serving import ServingServer, serve_pipeline
from .files import (read_binary_files, read_csv, read_image_files,
                    read_jsonl, write_csv, write_jsonl)
from .powerbi import PowerBIWriter
from .distributed_serving import serve_pipeline_distributed

__all__ = [
    "HTTPRequest", "HTTPResponse", "HTTPTransformer", "SimpleHTTPTransformer",
    "JSONInputParser", "JSONOutputParser", "CustomInputParser",
    "StringOutputParser", "AsyncHTTPClient", "send_with_retries",
    "ServingServer", "serve_pipeline", "read_binary_files", "read_image_files",
    "read_csv", "write_csv", "read_jsonl", "write_jsonl",
    "PowerBIWriter", "serve_pipeline_distributed",
]
