"""Minibatching transformers — the dynamic-rows ↔ static-shapes seam.

Reference: ``core/.../stages/MiniBatchTransformer.scala`` —
``DynamicMiniBatchTransformer:55``, ``TimeIntervalMiniBatchTransformer:79``,
``FixedMiniBatchTransformer:153``, ``FlattenBatch:189``. Each batched output row
holds one column-array per input column; ``FlattenBatch`` is the inverse.

TPU-native notes: a batched row's arrays are exactly what
:func:`synapseml_tpu.parallel.batching.pad_batch` pads to a compile bucket, so
``FixedMiniBatchTransformer(batch_size=B)`` in front of an inference model
yields one XLA program compiled once for bucket B (reference uses a default
batch of 10 in front of ONNXModel, ``onnx/ONNXModel.scala:102-105``).
"""

from __future__ import annotations

import time
from typing import Iterable

import numpy as np

from ..core.dataframe import DataFrame, Partition
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = [
    "FixedMiniBatchTransformer",
    "DynamicMiniBatchTransformer",
    "TimeIntervalMiniBatchTransformer",
    "FlattenBatch",
]


def _n_rows(p: Partition) -> int:
    return len(next(iter(p.values()))) if p else 0


def _batch_rows(p: Partition, bounds: Iterable[tuple[int, int]]) -> Partition:
    """Slice a partition into batch rows: each output cell is the ndarray of the
    batch's values for that column (object columns stay lists-of-objects)."""
    out: dict[str, np.ndarray] = {}
    spans = list(bounds)
    for name, col in p.items():
        cells = np.empty(len(spans), dtype=object)
        for i, (lo, hi) in enumerate(spans):
            chunk = col[lo:hi]
            cells[i] = list(chunk) if col.dtype == object else np.asarray(chunk)
        out[name] = cells
    return out


class _MiniBatchBase(Transformer):
    def _spans(self, n: int) -> list[tuple[int, int]]:  # pragma: no cover - abstract
        raise NotImplementedError

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.map_partitions(lambda p: _batch_rows(p, self._spans(_n_rows(p))))


class FixedMiniBatchTransformer(_MiniBatchBase):
    """Group rows into fixed-size batches (ref ``MiniBatchTransformer.scala:153``)."""

    batch_size = Param("batch_size", "rows per batch", default=10,
                       converter=TypeConverters.to_int, validator=lambda v: v > 0)
    max_buffer_size = Param("max_buffer_size", "buffering cap (accepted for parity; "
                            "eager plane needs no buffer)", default=2147483647,
                            converter=TypeConverters.to_int)
    buffered = Param("buffered", "buffer batches on a background thread (parity flag)",
                     default=False, converter=TypeConverters.to_bool)

    def _spans(self, n: int) -> list[tuple[int, int]]:
        b = self.get("batch_size")
        return [(lo, min(lo + b, n)) for lo in range(0, n, b)]


class DynamicMiniBatchTransformer(_MiniBatchBase):
    """Batch whatever is available, capped (ref ``MiniBatchTransformer.scala:55``).
    In the eager data plane the whole partition is 'available'."""

    max_batch_size = Param("max_batch_size", "cap on rows per batch", default=2147483647,
                           converter=TypeConverters.to_int, validator=lambda v: v > 0)

    def _spans(self, n: int) -> list[tuple[int, int]]:
        b = min(self.get("max_batch_size"), max(n, 1))
        return [(lo, min(lo + b, n)) for lo in range(0, n, b)]


class TimeIntervalMiniBatchTransformer(DynamicMiniBatchTransformer):
    """Batch by wall-clock interval (ref ``MiniBatchTransformer.scala:79``).
    Against a materialized partition all rows are already present, so the
    eager path degenerates to :class:`DynamicMiniBatchTransformer`'s capped
    batching — matching the reference when the upstream iterator never blocks.
    ``batch_stream`` is the true streaming path used by serving."""

    millis_to_wait = Param("millis_to_wait", "interval to collect a batch", default=1000,
                           converter=TypeConverters.to_int)

    def batch_stream(self, rows: Iterable[dict]) -> Iterable[dict]:
        """Streaming path (serving): drain `rows` into interval batches."""
        interval = self.get("millis_to_wait") / 1000.0
        cap = self.get("max_batch_size")
        buf: list[dict] = []
        deadline = time.monotonic() + interval
        for row in rows:
            buf.append(row)
            if len(buf) >= cap or time.monotonic() >= deadline:
                yield _rows_to_batch(buf)
                buf, deadline = [], time.monotonic() + interval
        if buf:
            yield _rows_to_batch(buf)


def _rows_to_batch(rows: list[dict]) -> dict:
    return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}


class FlattenBatch(Transformer):
    """Explode batched array-columns back into per-element rows
    (ref ``MiniBatchTransformer.scala:189``)."""

    def _transform(self, df: DataFrame) -> DataFrame:
        def per_part(p: Partition) -> Partition:
            if not p:
                return p
            out: dict[str, list] = {k: [] for k in p}
            n = _n_rows(p)
            for i in range(n):
                lens = {len(p[k][i]) for k in p
                        if p[k][i] is not None and hasattr(p[k][i], "__len__")
                        and not isinstance(p[k][i], (str, bytes))}
                if len(lens) > 1:
                    raise ValueError(f"FlattenBatch: unequal batch lengths {lens} in row {i}")
                m = lens.pop() if lens else 1
                for k in p:
                    cell = p[k][i]
                    if cell is not None and hasattr(cell, "__len__") and not isinstance(cell, (str, bytes)):
                        out[k].extend(list(cell))
                    else:
                        out[k].extend([cell] * m)
            from ..core.dataframe import _as_column

            return {k: _as_column(v) for k, v in out.items()}

        return df.map_partitions(per_part)
