"""Generic reusable transformers (reference ``core/.../stages/``, SURVEY.md §2.5).

Minibatching lives in :mod:`.minibatch` — on TPU it is the seam between dynamic
row streams and static-shape XLA executables, so the batched representation is
columnar (object arrays of per-batch ndarrays) and feeds straight into the
padding buckets of :mod:`synapseml_tpu.parallel.batching`.
"""

from .minibatch import (  # noqa: F401
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    FlattenBatch,
    TimeIntervalMiniBatchTransformer,
)
from .basic import (  # noqa: F401
    Cacher,
    ClassBalancer,
    ClassBalancerModel,
    DropColumns,
    EnsembleByKey,
    Explode,
    Lambda,
    MultiColumnAdapter,
    PartitionConsolidator,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    Timer,
    TimerModel,
    UDFTransformer,
)
from .text import TextPreprocessor, UnicodeNormalize  # noqa: F401
from .summarize import SummarizeData  # noqa: F401
