"""SummarizeData — per-column summary statistics as a DataFrame
(reference ``core/.../stages/SummarizeData.scala:101``)."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, _as_column
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["SummarizeData"]


class SummarizeData(Transformer):
    counts = Param("counts", "include count/unique/missing", default=True,
                   converter=TypeConverters.to_bool)
    basic = Param("basic", "include mean/std/min/max", default=True,
                  converter=TypeConverters.to_bool)
    sample = Param("sample", "include skew/kurtosis/variance", default=True,
                   converter=TypeConverters.to_bool)
    percentiles = Param("percentiles", "include p0.5/p1/p5/p25/p50/p75/p95/p99/p99.5",
                        default=True, converter=TypeConverters.to_bool)
    error_threshold = Param("error_threshold", "approx-quantile tolerance (parity; exact here)",
                            default=0.0, converter=TypeConverters.to_float)

    _PCTS = [0.5, 1, 5, 25, 50, 75, 95, 99, 99.5]

    def _transform(self, df: DataFrame) -> DataFrame:
        whole = df.collect()
        rows: dict[str, list] = {"feature": []}
        stats_order: list[str] = []

        def put(name: str, value):
            if name not in rows:
                rows[name] = []
                stats_order.append(name)
            rows[name].append(value)

        for name, col in whole.items():
            numeric = col.dtype != object and np.issubdtype(col.dtype, np.number) and col.ndim == 1
            rows["feature"].append(name)
            if self.get("counts"):
                put("count", int(len(col)))
                try:
                    put("unique_value_count", int(len(np.unique(col[~_isnan(col)])
                                                      if numeric else set(map(str, col)))))
                except TypeError:
                    put("unique_value_count", float("nan"))
                put("missing_value_count",
                    int(np.count_nonzero(_isnan(col))) if numeric
                    else sum(1 for v in col if v is None))
            vals = col[~_isnan(col)].astype(np.float64) if numeric else None
            if self.get("basic"):
                put("mean", float(np.mean(vals)) if numeric and len(vals) else float("nan"))
                put("stddev", float(np.std(vals, ddof=1)) if numeric and len(vals) > 1 else float("nan"))
                put("min", float(np.min(vals)) if numeric and len(vals) else float("nan"))
                put("max", float(np.max(vals)) if numeric and len(vals) else float("nan"))
            if self.get("sample"):
                put("variance", float(np.var(vals, ddof=1)) if numeric and len(vals) > 1 else float("nan"))
                put("skewness", _skew(vals) if numeric and len(vals) > 2 else float("nan"))
                put("kurtosis", _kurt(vals) if numeric and len(vals) > 3 else float("nan"))
            if self.get("percentiles"):
                for q in self._PCTS:
                    put(f"p{q:g}", float(np.percentile(vals, q)) if numeric and len(vals)
                        else float("nan"))
        out = {"feature": _as_column(rows["feature"])}
        for s in stats_order:
            out[s] = _as_column(rows[s])
        return DataFrame([out])


def _isnan(col: np.ndarray) -> np.ndarray:
    if col.dtype != object and np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    return np.zeros(len(col), dtype=bool)


def _skew(v: np.ndarray) -> float:
    m, s = np.mean(v), np.std(v)
    return float(np.mean(((v - m) / s) ** 3)) if s > 0 else 0.0


def _kurt(v: np.ndarray) -> float:
    m, s = np.mean(v), np.std(v)
    return float(np.mean(((v - m) / s) ** 4) - 3.0) if s > 0 else 0.0
