"""Text normalization stages (reference ``core/.../stages/TextPreprocessor.scala``
and ``UnicodeNormalize.scala``)."""

from __future__ import annotations

import re
import unicodedata

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["TextPreprocessor", "UnicodeNormalize"]


class TextPreprocessor(Transformer):
    """Longest-match substring replacement over a map (the reference builds a
    char trie for the same longest-match semantics), then optional lowercase."""

    input_col = Param("input_col", "text column", default="text")
    output_col = Param("output_col", "output column", default="processed")
    map = Param("map", "substring -> replacement mapping", default={})
    normalize_case = Param("normalize_case", "lowercase after replacement", default=True,
                           converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        mapping = dict(self.get("map") or {})
        # longest-first alternation == trie longest-match
        pattern = (re.compile("|".join(re.escape(k) for k in
                                       sorted(mapping, key=len, reverse=True)))
                   if mapping else None)
        lower = self.get("normalize_case")

        def clean(text: str) -> str:
            s = str(text)
            if pattern is not None:
                s = pattern.sub(lambda m: mapping[m.group(0)], s)
            return s.lower() if lower else s

        def per_part(p):
            col = p[self.get("input_col")]
            out = np.empty(len(col), dtype=object)
            out[:] = [clean(t) for t in col]
            return out

        return df.with_column(self.get("output_col"), per_part)


class UnicodeNormalize(Transformer):
    form = Param("form", "unicode normal form NFC|NFD|NFKC|NFKD", default="NFKD",
                 validator=lambda v: v in ("NFC", "NFD", "NFKC", "NFKD"))
    input_col = Param("input_col", "text column", default="text")
    output_col = Param("output_col", "output column", default="normalized")
    lower = Param("lower", "lowercase output", default=True, converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        form, lower = self.get("form"), self.get("lower")

        def per_part(p):
            col = p[self.get("input_col")]
            out = np.empty(len(col), dtype=object)
            out[:] = [unicodedata.normalize(form, str(t)).lower() if lower
                      else unicodedata.normalize(form, str(t)) for t in col]
            return out

        return df.with_column(self.get("output_col"), per_part)
