"""Basic reusable transformers (reference ``core/.../stages/*.scala``)."""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from ..core.dataframe import DataFrame, Partition, _as_column, scalar_of as _key
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer

__all__ = [
    "Lambda", "UDFTransformer", "DropColumns", "SelectColumns", "RenameColumn",
    "Repartition", "Cacher", "Explode", "EnsembleByKey", "StratifiedRepartition",
    "PartitionConsolidator", "Timer", "TimerModel", "ClassBalancer",
    "ClassBalancerModel", "MultiColumnAdapter",
]


class Lambda(Transformer):
    """Arbitrary DataFrame->DataFrame function as a stage
    (ref ``stages/Lambda.scala:24``)."""

    transform_fn = ComplexParam("transform_fn", "DataFrame -> DataFrame callable")
    transform_schema_fn = ComplexParam("transform_schema_fn", "schema -> schema callable")

    def __init__(self, transform_fn: Callable[[DataFrame], DataFrame] | None = None, **kw):
        super().__init__(**kw)
        if transform_fn is not None:
            self.set(transform_fn=transform_fn)

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get("transform_fn")(df)

    def transform_schema(self, schema: dict) -> dict:
        fn = self.get("transform_schema_fn")
        return fn(schema) if fn else schema


class UDFTransformer(Transformer):
    """Apply a user function to input column(s) producing an output column
    (ref ``stages/UDFTransformer.scala:27``). The udf receives per-partition
    column arrays (vectorized — the TPU-friendly contract) unless
    ``vectorized=False``, in which case it is applied per element."""

    input_col = Param("input_col", "single input column")
    input_cols = Param("input_cols", "multiple input columns", converter=TypeConverters.to_list)
    output_col = Param("output_col", "output column", default="output")
    udf = ComplexParam("udf", "the function")
    vectorized = Param("vectorized", "call once per partition with arrays", default=True,
                       converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("input_cols") or ([self.get("input_col")] if self.get("input_col") else [])
        if not cols:
            raise ValueError("UDFTransformer: set input_col or input_cols")
        self.require_columns(df, *cols)
        fn = self.get("udf")

        def per_part(p: Partition) -> np.ndarray:
            args = [p[c] for c in cols]
            if self.get("vectorized"):
                return _as_column(fn(*args), len(args[0]))
            return _as_column([fn(*vals) for vals in zip(*args)], len(args[0]))

        return df.with_column(self.get("output_col"), per_part)


class DropColumns(Transformer):
    cols = Param("cols", "columns to drop", converter=TypeConverters.to_list, default=[])

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.drop([c for c in self.get("cols") if c in df.columns])


class SelectColumns(Transformer):
    cols = Param("cols", "columns to keep", converter=TypeConverters.to_list, default=[])

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.select(self.get("cols"))


class RenameColumn(Transformer):
    input_col = Param("input_col", "existing name")
    output_col = Param("output_col", "new name")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        return df.with_column_renamed(self.get("input_col"), self.get("output_col"))


class Repartition(Transformer):
    """(ref ``stages/Repartition.scala``) — partitions map 1:1 to host feeding
    units on the mesh, so this is also the executor-count control."""

    n = Param("n", "target partition count", converter=TypeConverters.to_int,
              validator=lambda v: v > 0)
    disable = Param("disable", "pass through unchanged", default=False,
                    converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        return df if self.get("disable") else df.repartition(self.get("n"))


class Cacher(Transformer):
    """(ref ``stages/Cacher.scala``) — the eager data plane is always
    materialized; kept for pipeline parity."""

    disable = Param("disable", "skip caching", default=False, converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        return df if self.get("disable") else df.cache()


class Explode(Transformer):
    """Explode an array column into rows (ref ``stages/Explode.scala``)."""

    input_col = Param("input_col", "array column to explode")
    output_col = Param("output_col", "exploded column name")

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get("input_col")
        out_col = self.get("output_col") or in_col
        self.require_columns(df, in_col)

        def per_part(p: Partition) -> Partition:
            n = len(p[in_col])
            reps = np.asarray([len(p[in_col][i]) for i in range(n)], dtype=np.int64)
            out: dict[str, np.ndarray] = {}
            for k, col in p.items():
                if k == in_col and out_col == in_col:
                    continue  # replaced by the exploded values below
                out[k] = np.repeat(col, reps, axis=0)
            flat: list = []
            for i in range(n):
                flat.extend(list(p[in_col][i]))
            out[out_col] = _as_column(flat)
            return out

        return df.map_partitions(per_part)


class EnsembleByKey(Transformer):
    """Group rows by key column(s) and aggregate value column(s)
    (ref ``stages/EnsembleByKey.scala:22``). Strategy: mean (vectors average
    elementwise, the reference's behavior for DenseVector cols)."""

    keys = Param("keys", "grouping key columns", converter=TypeConverters.to_list)
    cols = Param("cols", "value columns to aggregate", converter=TypeConverters.to_list)
    col_names = Param("col_names", "output names (default '<strategy>(<col>)')",
                      converter=TypeConverters.to_list)
    strategy = Param("strategy", "aggregation strategy", default="mean",
                     validator=lambda v: v in ("mean",))
    collapse_group = Param("collapse_group", "one row per key (else broadcast back)",
                           default=True, converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        keys, cols = self.get("keys"), self.get("cols")
        self.require_columns(df, *keys, *cols)
        names = self.get("col_names") or [f"{self.get('strategy')}({c})" for c in cols]
        whole = df.collect()
        n = len(next(iter(whole.values())))
        key_rows = list(zip(*[whole[k] for k in keys]))
        index: dict[tuple, list[int]] = {}
        for i, kr in enumerate(key_rows):
            index.setdefault(kr, []).append(i)
        group_keys = list(index.keys())
        agg = {name: [np.mean(np.stack([np.asarray(whole[c][i], dtype=np.float64)
                                        for i in idx]), axis=0)
                      for idx in index.values()]
               for name, c in zip(names, cols)}
        if self.get("collapse_group"):
            out: Partition = {k: _as_column([gk[j] for gk in group_keys])
                              for j, k in enumerate(keys)}
            for name in names:
                out[name] = _as_column(agg[name])
            return DataFrame([out])
        pos = {kr: gi for gi, kr in enumerate(group_keys)}
        out = dict(whole)
        for name in names:
            out[name] = _as_column([agg[name][pos[key_rows[i]]] for i in range(n)])
        return DataFrame([out])


class StratifiedRepartition(Transformer):
    """Repartition so every partition sees every label value
    (ref ``stages/StratifiedRepartition.scala:31``): round-robin within each
    stratum across partitions. Modes: 'equal' (equalize class counts by
    resampling), 'original' (keep counts), 'mixed' (cap imbalance at 3x min)."""

    label_col = Param("label_col", "stratification column", default="label")
    mode = Param("mode", "equal | original | mixed", default="original",
                 validator=lambda v: v in ("equal", "original", "mixed"))
    seed = Param("seed", "resampling seed", default=0, converter=TypeConverters.to_int)

    def _transform(self, df: DataFrame) -> DataFrame:
        lab = self.get("label_col")
        self.require_columns(df, lab)
        nparts = df.num_partitions
        whole = df.collect()
        labels = whole[lab]
        values, counts = np.unique(labels, return_counts=True)
        rng = np.random.default_rng(self.get("seed"))
        mode = self.get("mode")
        if mode == "equal":
            target = {v: int(counts.max()) for v in values}
        elif mode == "mixed":
            cap = int(min(counts) * 3)
            target = {v: min(int(c), cap) for v, c in zip(values, counts)}
        else:
            target = {v: int(c) for v, c in zip(values, counts)}
        chosen: list[np.ndarray] = []
        for v in values:
            idx = np.nonzero(labels == v)[0]
            t = target[v]
            if t <= len(idx):
                chosen.append(rng.choice(idx, size=t, replace=False) if t < len(idx) else idx)
            else:  # upsample with replacement to equalize
                extra = rng.choice(idx, size=t - len(idx), replace=True)
                chosen.append(np.concatenate([idx, extra]))
        parts: list[list[int]] = [[] for _ in range(nparts)]
        for idx in chosen:  # round-robin each stratum across partitions
            for j, i in enumerate(idx):
                parts[j % nparts].append(int(i))
        return DataFrame([{k: v[np.asarray(p_idx, dtype=np.int64)] for k, v in whole.items()}
                          for p_idx in parts if p_idx])


class PartitionConsolidator(Transformer):
    """Funnel data to one partition per host (ref
    ``stages/PartitionConsolidator.scala:22`` — one-per-executor for
    rate-limited resources like HTTP clients; here: one per mesh host)."""

    num_hosts = Param("num_hosts", "target host count (default: jax process count)",
                      converter=TypeConverters.to_int)

    def _transform(self, df: DataFrame) -> DataFrame:
        n = self.get("num_hosts")
        if n is None:
            import jax

            n = max(jax.process_count(), 1)
        return df.coalesce(min(n, df.num_partitions))


class TimerModel(Model):
    stage = ComplexParam("stage", "wrapped fitted stage")
    log_to_scala = Param("log_to_scala", "print timing lines", default=True,
                         converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        inner = self.get("stage")
        t0 = time.perf_counter()
        out = inner.transform(df)
        self.last_elapsed = time.perf_counter() - t0
        if self.get("log_to_scala"):
            print(f"[Timer] {type(inner).__name__}.transform took {self.last_elapsed:.4f}s")
        return out


class Timer(Estimator):
    """Time a wrapped stage's fit/transform (ref ``stages/Timer.scala:56``)."""

    stage = ComplexParam("stage", "stage to time")
    log_to_scala = Param("log_to_scala", "print timing lines", default=True,
                         converter=TypeConverters.to_bool)

    def _fit(self, df: DataFrame) -> TimerModel:
        inner = self.get("stage")
        t0 = time.perf_counter()
        fitted = inner.fit(df) if isinstance(inner, Estimator) else inner
        self.last_elapsed = time.perf_counter() - t0
        if self.get("log_to_scala") and isinstance(inner, Estimator):
            print(f"[Timer] {type(inner).__name__}.fit took {self.last_elapsed:.4f}s")
        return TimerModel(stage=fitted, log_to_scala=self.get("log_to_scala"))


class ClassBalancerModel(Model):
    input_col = Param("input_col", "label column")
    output_col = Param("output_col", "weight column", default="weight")
    weights = ComplexParam("weights", "label value -> weight mapping (dict)")

    def _transform(self, df: DataFrame) -> DataFrame:
        w = self.get("weights")
        col = self.get("input_col")
        self.require_columns(df, col)
        return df.with_column(
            self.get("output_col"),
            lambda p: np.asarray([w.get(_key(v), 1.0) for v in p[col]], dtype=np.float64))



class ClassBalancer(Estimator):
    """Weight column = max_class_count / class_count
    (ref ``stages/ClassBalancer.scala``)."""

    input_col = Param("input_col", "label column", default="label")
    output_col = Param("output_col", "weight column", default="weight")

    def _fit(self, df: DataFrame) -> ClassBalancerModel:
        col = self.get("input_col")
        self.require_columns(df, col)
        labels = df.collect_column(col)
        values, counts = np.unique(labels, return_counts=True)
        mx = counts.max()
        weights = {_key(v): float(mx) / float(c) for v, c in zip(values, counts)}
        return ClassBalancerModel(input_col=col, output_col=self.get("output_col"),
                                  weights=weights)


class MultiColumnAdapter(Estimator):
    """Apply a 1-col stage independently to many columns
    (ref ``stages/MultiColumnAdapter.scala``)."""

    base_stage = ComplexParam("base_stage", "stage with input_col/output_col params")
    input_cols = Param("input_cols", "input columns", converter=TypeConverters.to_list)
    output_cols = Param("output_cols", "output columns", converter=TypeConverters.to_list)

    def _make_stages(self):
        base = self.get("base_stage")
        ins, outs = self.get("input_cols"), self.get("output_cols")
        if len(ins) != len(outs):
            raise ValueError("input_cols and output_cols must align")
        return [base.copy({"input_col": i, "output_col": o}) for i, o in zip(ins, outs)]

    def _fit(self, df: DataFrame):
        from ..core.pipeline import Pipeline

        return Pipeline(stages=self._make_stages()).fit(df)
