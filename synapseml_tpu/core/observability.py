"""Unified observability plane: metrics registry + distributed trace spans.

Reference (SURVEY §2.5/§5): the reference treats observability as a layer —
``SynapseMLLogging`` JSON stage events, LightGBM ``TaskInstrumentationMeasures``
phase windows, VW per-partition ``TrainingStats``. Our reproduction had three
disconnected fragments (``core/instrumentation.py`` windows, ``core/logging.py``
stage events, per-plane ``resilience_measures`` dicts behind an ad-hoc
``GET /stats``). This module is the one plane they all feed:

* :class:`MetricsRegistry` — process-wide Counter/Gauge/Histogram families
  (labeled series, fixed histogram buckets, thread-safe) with Prometheus
  text-format exposition (served as ``GET /metrics`` by every serving HTTP
  server) and a ``snapshot()`` carrying bucket-estimated p50/p95/p99 for the
  bench trajectory;
* :class:`Tracer` — nested spans (trace_id/span_id/parent, monotonic
  duration, attributes, per-thread context stack) with W3C ``traceparent``
  propagation, so one serving request through the RoutingFront fan-out
  stitches into a single multi-process trace;
* exporters — Chrome/Perfetto trace-event JSON (loads in ``chrome://tracing``
  / ui.perfetto.dev, alongside the XLA traces from ``profile_trace``) and the
  Prometheus endpoint.

Adapters register the pre-existing fragments as first-class series:
``register_resilience_collector`` (per-plane retry/breaker/deadline counters),
``register_instrumentation`` (any ``InstrumentationMeasures``), and
``observe_stage`` (every ``StageTelemetry`` fit/transform lands in the
``synapseml_stage_duration_ms`` histogram automatically).
"""

from __future__ import annotations

import bisect
import contextlib
import json
import os
import threading
import time
import uuid
import weakref
from typing import Any, Callable, Iterator

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "Sample",
    "HandleCache",
    "get_registry", "reset_registry", "prometheus_exposition",
    "register_resilience_collector", "register_instrumentation",
    "observe_stage",
    "Span", "SpanContext", "Tracer", "get_tracer", "reset_tracer",
    "format_traceparent", "parse_traceparent",
    "chrome_trace_events", "export_chrome_trace",
]


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

# Default latency buckets in MILLISECONDS (the repo's native unit — phase
# windows, stage durations and serving latencies all export ``*_ms``).
# Spans sub-ms loopback serving up to multi-minute training phases.
DEFAULT_BUCKETS_MS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                      1000, 2500, 5000, 10_000, 30_000, 60_000)


class Sample:
    """One exposition-ready sample a collector can yield: a named value with
    labels. ``kind`` is the Prometheus family type."""

    __slots__ = ("name", "labels", "value", "kind", "help")

    def __init__(self, name: str, labels: dict | None, value: float,
                 kind: str = "gauge", help: str = ""):
        self.name = name
        self.labels = dict(labels or {})
        self.value = float(value)
        self.kind = kind
        self.help = help


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace('"', r"\"")


def _format_labels(labels: dict, extra: dict | None = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _normalize_buckets(buckets) -> tuple:
    bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS_MS)))
    if not bounds:
        raise ValueError("histogram needs at least one bucket")
    return bounds


class _Metric:
    """One metric family: a name plus labeled child series. Children are
    created on first ``labels(...)`` call; the bare family (no labels) is
    itself a series so unlabeled ``inc``/``set``/``observe`` work directly."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", label_names: tuple = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._series: dict[tuple, Any] = {}

    def labels(self, **labels) -> "Any":
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}")
        key = _label_key(labels)
        with self._lock:
            child = self._series.get(key)
            if child is None:
                child = self._new_child()
                self._series[key] = child
            return child

    def _child_items(self) -> list[tuple[dict, Any]]:
        with self._lock:
            return [(dict(k), c) for k, c in self._series.items()]

    def _default_child(self):
        return self.labels()


class _CounterSeries:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Metric):
    kind = "counter"

    def _new_child(self) -> _CounterSeries:
        return _CounterSeries()

    def inc(self, n: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(n)


class _GaugeSeries:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self) -> _GaugeSeries:
        return _GaugeSeries()

    def set(self, v: float, **labels) -> None:
        self.labels(**labels).set(v)

    def inc(self, n: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(n)


class _HistogramSeries:
    __slots__ = ("_buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets: tuple):
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self._buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        out = {"count": total, "sum": round(s, 3),
               "buckets": {str(b): c for b, c in zip(self._buckets, counts)}}
        out["buckets"]["+Inf"] = counts[-1]
        for q in (0.5, 0.95, 0.99):
            out[f"p{int(q * 100)}"] = self._quantile(q, counts, total)
        return out

    def _quantile(self, q: float, counts: list, total: int) -> float | None:
        """Bucket-interpolated quantile estimate (Prometheus
        ``histogram_quantile`` semantics; None when empty)."""
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            lo = self._buckets[i - 1] if i > 0 else 0.0
            hi = self._buckets[i] if i < len(self._buckets) else None
            if cum + c >= rank:
                if c == 0 or hi is None:
                    return round(lo, 3)  # +Inf bucket: clamp to last bound
                return round(lo + (hi - lo) * (rank - cum) / c, 3)
            cum += c
        return round(float(self._buckets[-1]), 3)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "", label_names: tuple = (),
                 buckets: tuple | None = None):
        super().__init__(name, help, label_names)
        self.buckets = _normalize_buckets(buckets)

    def _new_child(self) -> _HistogramSeries:
        return _HistogramSeries(self.buckets)

    def observe(self, v: float, **labels) -> None:
        self.labels(**labels).observe(v)

    @contextlib.contextmanager
    def time_ms(self, **labels) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe((time.perf_counter() - t0) * 1e3, **labels)


class MetricsRegistry:
    """Process-wide registry of metric families + pull-time collectors.

    ``counter``/``gauge``/``histogram`` are get-or-create (idempotent per
    name; a kind mismatch raises — two subsystems cannot silently fight over
    one name). Collectors are callables invoked at exposition/snapshot time
    yielding :class:`Sample` rows — used for state owned elsewhere (breaker
    states, resilience-plane counters) so the registry never caches stale
    copies. Thread-safe throughout."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], Iterator[Sample]]] = []
        self._lock = threading.Lock()

    # -- registration -----------------------------------------------------
    def _get_or_create(self, cls, name: str, help: str, label_names: tuple,
                       **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}{m.label_names}, requested "
                        f"{cls.__name__}{tuple(label_names)}")
                if kw.get("buckets") is not None and \
                        m.buckets != _normalize_buckets(kw["buckets"]):
                    # silently sharing a family with different boundaries
                    # would dump one caller's observations into +Inf
                    raise ValueError(
                        f"metric {name!r} already registered with buckets "
                        f"{m.buckets}, requested "
                        f"{_normalize_buckets(kw['buckets'])}")
                return m
            m = cls(name, help, tuple(label_names), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                label_names: tuple = ()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "",
              label_names: tuple = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "", label_names: tuple = (),
                  buckets: tuple | None = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets)

    def register_collector(self, fn: Callable[[], Iterator[Sample]]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    # -- exposition -------------------------------------------------------
    def _collected(self) -> list[Sample]:
        with self._lock:
            collectors = list(self._collectors)
        out: list[Sample] = []
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:  # noqa: BLE001 — one bad collector must not
                continue       # take down the whole /metrics endpoint
        return out

    def exposition(self) -> str:
        """Prometheus text format 0.0.4 (``# HELP``/``# TYPE`` + samples;
        histograms expand to ``_bucket``/``_sum``/``_count``)."""
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            lines.append(f"# HELP {name} {m.help or name}")
            lines.append(f"# TYPE {name} {m.kind}")
            for labels, series in m._child_items():
                label_str = _format_labels(labels)
                if m.kind == "histogram":
                    snap = series.snapshot()
                    cum = 0
                    for b in m.buckets:
                        cum += snap["buckets"][str(b)]
                        le = _format_labels(labels, {"le": _fmt_float(b)})
                        lines.append(f"{name}_bucket{le} {cum}")
                    le = _format_labels(labels, {"le": "+Inf"})
                    lines.append(f"{name}_bucket{le} {snap['count']}")
                    lines.append(f"{name}_sum{label_str} {_fmt_float(snap['sum'])}")
                    lines.append(f"{name}_count{label_str} {snap['count']}")
                else:
                    lines.append(f"{name}{label_str} {_fmt_float(series.value)}")
        by_name: dict[str, list[Sample]] = {}
        for s in self._collected():
            by_name.setdefault(s.name, []).append(s)
        for name in sorted(by_name):
            samples = by_name[name]
            lines.append(f"# HELP {name} {samples[0].help or name}")
            lines.append(f"# TYPE {name} {samples[0].kind}")
            for s in samples:
                lines.append(
                    f"{name}{_format_labels(s.labels)} {_fmt_float(s.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Flat JSON-able view for bench records: counters/gauges as numbers,
        histograms as {count, sum, p50, p95, p99, buckets}. Series keys are
        ``name{k=v,...}``."""
        out: dict[str, Any] = {}
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            for labels, series in m._child_items():
                key = name + _format_labels(labels)
                out[key] = (series.snapshot() if m.kind == "histogram"
                            else series.value)
        for s in self._collected():
            out[s.name + _format_labels(s.labels)] = s.value
        return out


def _fmt_float(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class HandleCache:
    """Per-registry memo of metric handles for hot paths.

    ``build(registry)`` returns whatever handle structure the call site wants
    (a dict of ``.labels()`` children, say); ``get()`` rebuilds only when the
    global registry was replaced (``reset_registry`` in tests) — so a request
    path pays one identity check instead of get-or-create lock traffic per
    event."""

    def __init__(self, build: Callable[["MetricsRegistry"], Any]):
        self._build = build
        self._reg: MetricsRegistry | None = None
        self._handles: Any = None
        self._lock = threading.Lock()

    def get(self) -> Any:
        reg = get_registry()
        if reg is not self._reg:
            with self._lock:
                if reg is not self._reg:
                    self._handles = self._build(reg)
                    self._reg = reg
        return self._handles


_REGISTRY = MetricsRegistry()
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (what ``GET /metrics`` serves)."""
    return _REGISTRY


def reset_registry() -> MetricsRegistry:
    """Replace the global registry with a fresh one (tests). Pre-wired
    collectors (resilience planes) are re-registered on the new registry."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = MetricsRegistry()
        register_resilience_collector(_REGISTRY)
        return _REGISTRY


def prometheus_exposition() -> tuple[bytes, str]:
    """(payload, content-type) for an HTTP /metrics handler."""
    return (get_registry().exposition().encode("utf-8"),
            "text/plain; version=0.0.4; charset=utf-8")


# ---------------------------------------------------------------------------
# adapters: the pre-existing fragments become first-class series
# ---------------------------------------------------------------------------

def _resilience_samples() -> Iterator[Sample]:
    from .resilience import all_resilience_measures

    for plane, d in sorted(all_resilience_measures().items()):
        for k, v in sorted(d.items()):
            if k.endswith("_count"):
                yield Sample(f"synapseml_resilience_{k[:-6]}_total",
                             {"plane": plane}, v, kind="counter",
                             help="resilience plane counter "
                                  "(core/resilience.py)")


def register_resilience_collector(registry: MetricsRegistry | None = None) -> None:
    """Export every ``resilience_measures(plane)`` counter as
    ``synapseml_resilience_<name>_total{plane=...}`` — pull-time, so the
    planes stay the single source of truth."""
    (registry or get_registry()).register_collector(_resilience_samples)


def register_instrumentation(prefix: str, measures,
                             labels: dict | None = None,
                             registry: MetricsRegistry | None = None) -> None:
    """Expose an :class:`~synapseml_tpu.core.instrumentation.
    InstrumentationMeasures` as pull-time series: phase windows become
    ``<prefix>_<phase>_ms`` gauges, counts become ``<prefix>_<name>_total``
    counters. Holds the collector via weakref — a dropped collector silently
    stops exporting instead of pinning train state alive."""
    ref = weakref.ref(measures)
    labels = dict(labels or {})

    def collect() -> Iterator[Sample]:
        m = ref()
        if m is None:
            return
        for k, v in m.to_dict().items():
            if k.endswith("_count"):
                yield Sample(f"{prefix}_{k[:-6]}_total", labels, v,
                             kind="counter", help=f"{prefix} counter")
            elif k.endswith("_ms"):
                yield Sample(f"{prefix}_{k}", labels, v, kind="gauge",
                             help=f"{prefix} phase window (ms)")

    (registry or get_registry()).register_collector(collect)


def observe_stage(class_name: str, method: str, duration_ms: float,
                  error: bool = False) -> None:
    """Record one StageTelemetry fit/transform event (called by
    ``core/logging.py`` on every ``log_verb``): duration histogram + event
    counter, labeled by stage class and verb."""
    reg = get_registry()
    reg.histogram(
        "synapseml_stage_duration_ms",
        "StageTelemetry fit/transform duration (SynapseMLLogging analog)",
        ("stage", "method"),
    ).observe(duration_ms, stage=class_name, method=method)
    reg.counter(
        "synapseml_stage_events_total", "StageTelemetry events by outcome",
        ("stage", "method", "status"),
    ).inc(stage=class_name, method=method,
          status="error" if error else "ok")


register_resilience_collector(_REGISTRY)


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

_TRACEPARENT_HEADER = "traceparent"


class SpanContext:
    """What crosses a process/thread boundary: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"


def format_traceparent(ctx: SpanContext) -> str:
    """W3C Trace Context: ``00-<32hex trace>-<16hex span>-01``."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value: str | None) -> SpanContext | None:
    """Parse a ``traceparent`` header; None on absence or malformed input
    (a bad upstream header must start a fresh trace, never raise)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    if parts[1] == "0" * 32 or parts[2] == "0" * 16:
        return None
    return SpanContext(parts[1].lower(), parts[2].lower())


def extract_context(headers) -> SpanContext | None:
    """Pull a SpanContext out of an HTTP header mapping (case-insensitive)."""
    if headers is None:
        return None
    for k in (_TRACEPARENT_HEADER, "Traceparent", "TRACEPARENT"):
        v = headers.get(k) if hasattr(headers, "get") else None
        if v:
            return parse_traceparent(v)
    # BaseHTTPRequestHandler headers are email.message.Message — already
    # case-insensitive via get; plain dicts with odd casing land here
    try:
        for k, v in headers.items():
            if k.lower() == _TRACEPARENT_HEADER:
                return parse_traceparent(v)
    except AttributeError:
        pass
    return None


class Span:
    """One timed operation. ``end()`` freezes duration; finished spans land
    in the tracer's ring buffer for export."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attributes",
                 "start_wall", "_start_mono", "duration_ms", "status",
                 "pid", "tid")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str | None, attributes: dict | None = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attributes = dict(attributes or {})
        self.start_wall = time.time()
        self._start_mono = time.perf_counter()
        self.duration_ms: float | None = None
        self.status = "ok"
        self.pid = os.getpid()
        self.tid = threading.get_ident()

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def end(self, error: BaseException | None = None) -> None:
        if self.duration_ms is None:
            self.duration_ms = (time.perf_counter() - self._start_mono) * 1e3
        if error is not None:
            self.status = "error"
            self.attributes.setdefault(
                "error", f"{type(error).__name__}: {error}")

    def to_dict(self) -> dict:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "start_wall": self.start_wall,
            "duration_ms": round(self.duration_ms or 0.0, 3),
            "status": self.status, "pid": self.pid, "tid": self.tid,
            "attributes": self.attributes,
        }


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    """Nested spans with a PER-THREAD context stack. ``span(...)`` nests
    under the thread's current span unless ``parent`` (a
    :class:`SpanContext`, e.g. extracted from ``traceparent``) pins it to a
    remote trace. Finished spans go to a bounded ring buffer
    (``max_spans``) — long-lived servers never grow without bound."""

    def __init__(self, max_spans: int = 10_000):
        self._local = threading.local()
        self._finished: list[Span] = []
        self._max_spans = int(max_spans)
        self._lock = threading.Lock()

    # -- context stack ----------------------------------------------------
    def _stack(self) -> list[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_span(self) -> Span | None:
        stack = self._stack()
        return stack[-1] if stack else None

    def current_context(self) -> SpanContext | None:
        span = self.current_span()
        return span.context if span is not None else None

    # -- span lifecycle ---------------------------------------------------
    def start_span(self, name: str, attributes: dict | None = None,
                   parent: SpanContext | None = None) -> Span:
        if parent is None:
            cur = self.current_span()
            parent = cur.context if cur is not None else None
        if parent is None:
            span = Span(name, _new_trace_id(), _new_span_id(), None,
                        attributes)
        else:
            span = Span(name, parent.trace_id, _new_span_id(),
                        parent.span_id, attributes)
        self._stack().append(span)
        return span

    def end_span(self, span: Span, error: BaseException | None = None) -> None:
        span.end(error)
        stack = self._stack()
        if span in stack:
            # pop through (tolerates a leaked deeper span)
            del stack[stack.index(span):]
        with self._lock:
            self._finished.append(span)
            if len(self._finished) > self._max_spans:
                del self._finished[:len(self._finished) - self._max_spans]

    @contextlib.contextmanager
    def span(self, name: str, attributes: dict | None = None,
             parent: SpanContext | None = None) -> Iterator[Span]:
        s = self.start_span(name, attributes, parent)
        try:
            yield s
        except BaseException as e:
            self.end_span(s, error=e)
            raise
        else:
            self.end_span(s)

    # -- headers ----------------------------------------------------------
    def inject(self, headers: dict) -> dict:
        """Stamp the current context's ``traceparent`` into ``headers``
        (mutates and returns it; no-op without an active span)."""
        ctx = self.current_context()
        if ctx is not None:
            headers[_TRACEPARENT_HEADER] = format_traceparent(ctx)
        return headers

    # -- export -----------------------------------------------------------
    def finished_spans(self) -> list[Span]:
        with self._lock:
            return list(self._finished)

    def spans_as_dicts(self) -> list[dict]:
        return [s.to_dict() for s in self.finished_spans()]

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer (what ``GET /trace`` serves)."""
    return _TRACER


def reset_tracer(max_spans: int = 10_000) -> Tracer:
    global _TRACER
    _TRACER = Tracer(max_spans)
    return _TRACER


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace-event export
# ---------------------------------------------------------------------------

def chrome_trace_events(span_dicts: list[dict] | None = None) -> dict:
    """Spans -> Chrome trace-event JSON (the ``chrome://tracing`` /
    Perfetto format): one complete ("X") event per span, microsecond
    timestamps, pid/tid preserved so a STITCHED multi-process trace (front +
    workers' ``/trace`` outputs concatenated) renders as one timeline.
    Accepts plain span dicts so cross-process JSON needs no deserialization
    into Span objects."""
    if span_dicts is None:
        span_dicts = get_tracer().spans_as_dicts()
    events = []
    procs = {}
    for d in span_dicts:
        pid = d.get("pid", 0)
        if pid not in procs:
            procs[pid] = True
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0,
                           "args": {"name": f"synapseml pid {pid}"}})
        args = dict(d.get("attributes") or {})
        args.update({"trace_id": d.get("trace_id"),
                     "span_id": d.get("span_id"),
                     "parent_id": d.get("parent_id"),
                     "status": d.get("status", "ok")})
        events.append({
            "ph": "X", "name": d.get("name", "?"), "cat": "synapseml",
            "ts": round(float(d.get("start_wall", 0.0)) * 1e6, 3),
            "dur": round(float(d.get("duration_ms", 0.0)) * 1e3, 3),
            "pid": pid, "tid": d.get("tid", 0), "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str,
                        span_dicts: list[dict] | None = None) -> str:
    """Write the Chrome trace-event JSON to ``path``; returns the path."""
    with open(path, "w") as f:
        json.dump(chrome_trace_events(span_dicts), f)
    return path
