"""Shared ``synapseml_hpo_*`` metric families for fused training arrays.

Both fused sweep engines (``models.fused_trainer`` for NN trials,
``gbdt.fused`` for boosters) emit the same series distinguished by an
``engine`` label. The families live here — next to the trial-count ladder
in :mod:`core.batching` — so the two emitters cannot drift into
conflicting registrations (the registry raises on a spec mismatch for an
existing family).
"""

from __future__ import annotations

from . import observability as obs

__all__ = ["HPO_ARRAY_METRICS"]

HPO_ARRAY_METRICS = obs.HandleCache(lambda reg: {
    "active": reg.gauge(
        "synapseml_hpo_active_trials",
        "live (not early-stopped) trials in the fused training array",
        ("engine",)),
    "step_ms": reg.histogram(
        "synapseml_hpo_fused_step_ms",
        "wall time of one fused train step (all live trials together)",
        ("engine",)),
    "trials_per_sec": reg.gauge(
        "synapseml_hpo_trials_per_sec",
        "trial-steps per second through the fused array "
        "(live trials x steps / wall)", ("engine",)),
    "steps": reg.counter(
        "synapseml_hpo_fused_steps_total",
        "fused optimizer steps executed", ("engine",)),
    "compactions": reg.counter(
        "synapseml_hpo_compactions_total",
        "rung-boundary compactions of the trial axis", ("engine",)),
})
