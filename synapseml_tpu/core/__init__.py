from .dataframe import DataFrame, Partition, concat_partitions, schema_of
from .params import ComplexParam, GlobalParams, Param, Params, ServiceParam, TypeConverters
from .pipeline import Estimator, Model, Pipeline, PipelineModel, PipelineStage, Transformer, load_stage
from .utils import ClusterInfo, StopWatch, cluster_info, retry_with_timeout, using

__all__ = [
    "DataFrame", "Partition", "concat_partitions", "schema_of",
    "Param", "ComplexParam", "ServiceParam", "Params", "GlobalParams", "TypeConverters",
    "PipelineStage", "Transformer", "Estimator", "Model", "Pipeline", "PipelineModel", "load_stage",
    "StopWatch", "retry_with_timeout", "using", "ClusterInfo", "cluster_info",
]
