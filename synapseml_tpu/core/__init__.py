from .batching import (
    CompiledCache,
    ShapeBucketer,
    default_bucketer,
    get_compiled_cache,
    instance_token,
    invalidate_token,
    reset_compiled_cache,
    set_default_bucketer,
)
from .dataframe import DataFrame, Partition, concat_partitions, schema_of
from .faults import FaultPlan, FaultSpec, active_fault_plan, inject_faults
from .observability import (
    MetricsRegistry,
    Span,
    SpanContext,
    Tracer,
    chrome_trace_events,
    export_chrome_trace,
    get_registry,
    get_tracer,
    register_instrumentation,
    reset_registry,
    reset_tracer,
)
from .params import ComplexParam, GlobalParams, Param, Params, ServiceParam, TypeConverters
from .pipeline import Estimator, Model, Pipeline, PipelineModel, PipelineStage, Transformer, load_stage
from .resilience import (
    CircuitBreaker,
    Deadline,
    DeadlineExpired,
    RetryBudget,
    RetryPolicy,
    all_resilience_measures,
    reset_resilience_measures,
    resilience_measures,
)
from .utils import ClusterInfo, StopWatch, cluster_info, retry_with_timeout, using

__all__ = [
    "DataFrame", "Partition", "concat_partitions", "schema_of",
    "Param", "ComplexParam", "ServiceParam", "Params", "GlobalParams", "TypeConverters",
    "PipelineStage", "Transformer", "Estimator", "Model", "Pipeline", "PipelineModel", "load_stage",
    "StopWatch", "retry_with_timeout", "using", "ClusterInfo", "cluster_info",
    "RetryPolicy", "RetryBudget", "CircuitBreaker", "Deadline", "DeadlineExpired",
    "resilience_measures", "reset_resilience_measures", "all_resilience_measures",
    "FaultPlan", "FaultSpec", "inject_faults", "active_fault_plan",
    "ShapeBucketer", "CompiledCache", "get_compiled_cache",
    "reset_compiled_cache", "default_bucketer", "set_default_bucketer",
    "instance_token", "invalidate_token",
    "MetricsRegistry", "get_registry", "reset_registry",
    "register_instrumentation",
    "Tracer", "Span", "SpanContext", "get_tracer", "reset_tracer",
    "chrome_trace_events", "export_chrome_trace",
]
