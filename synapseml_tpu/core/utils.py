"""Core utilities: timing, fault tolerance, cluster/device introspection.

Reference analogs: ``core/utils/ClusterUtil.scala:14-191`` (executor/task-slot
discovery), ``core/utils/FaultToleranceUtils`` (retryWithTimeout),
``StopWatch``, ``StreamUtilities.using``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

__all__ = ["StopWatch", "retry_with_timeout", "using", "ClusterInfo", "cluster_info",
           "ParamsStringBuilder"]


class StopWatch:
    def __init__(self):
        self._start = None
        self.elapsed_ms = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed_ms += (time.perf_counter() - self._start) * 1e3
        self._start = None
        return False

    def measure(self, fn: Callable, *a, **kw):
        with self:
            return fn(*a, **kw)


def retry_with_timeout(fn: Callable[[], Any], timeout_s: float = 60.0,
                       retries: int = 3, backoff_s: float = 0.5) -> Any:
    """Run fn with a per-attempt timeout and exponential backoff between retries.

    Reference: ``FaultToleranceUtils.retryWithTimeout`` used by NetworkManager
    (``NetworkManager.scala:114``) and VW ``trainIteration``.
    """
    last: BaseException | None = None
    for attempt in range(retries):
        # plain daemon thread, not a ThreadPoolExecutor: executor workers are
        # non-daemon and concurrent.futures' atexit hook joins them, so an
        # abandoned hung fn would block process exit
        result: list[Any] = []
        error: list[BaseException] = []

        def run():
            try:
                result.append(fn())
            except BaseException as e:  # noqa: BLE001 - reported to caller
                error.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout=timeout_s)
        if result:
            return result[0]
        last = error[0] if error else TimeoutError(
            f"call did not finish within {timeout_s}s (attempt {attempt + 1}/{retries})")
        if attempt < retries - 1:
            time.sleep(backoff_s * (2 ** attempt))
    raise last  # type: ignore[misc]


@contextlib.contextmanager
def using(*resources):
    """Close resources on exit (reference StreamUtilities.using)."""
    try:
        yield resources if len(resources) > 1 else resources[0]
    finally:
        for r in reversed(resources):
            close = getattr(r, "close", None)
            if close:
                with contextlib.suppress(Exception):
                    close()


@dataclass
class ClusterInfo:
    """Host/device topology snapshot (ClusterUtil analog, TPU edition)."""

    num_hosts: int
    host_index: int
    devices_per_host: int
    total_devices: int
    platform: str
    coordinator_address: str | None = None

    @property
    def tasks_per_executor(self) -> int:
        # one task slot per local device: the 1:1 executor<->TPU-host pinning
        return self.devices_per_host


def cluster_info() -> ClusterInfo:
    import jax

    return ClusterInfo(
        num_hosts=jax.process_count(),
        host_index=jax.process_index(),
        devices_per_host=jax.local_device_count(),
        total_devices=jax.device_count(),
        platform=jax.devices()[0].platform,
    )


def stack_vector_column(col, dtype="float32"):
    """Coerce a DataFrame vector column (rectangular ndarray or object column
    of per-row vectors) to a [N, D] array of the given dtype."""
    import numpy as np

    arr = np.asarray(col)
    if arr.dtype == object:
        if len(arr) == 0:
            return np.zeros((0, 0), dtype)
        arr = np.stack([np.asarray(v) for v in arr])
    return arr.astype(dtype)


class ParamsStringBuilder:
    """Typed params -> one native-style argument string (reference
    ``core/utils/ParamsStringBuilder.scala``: the builder behind LightGBM
    param strings and VW ``passThroughArgs``).

    Append-with-override semantics: the FIRST occurrence of a parameter wins
    (raw ``append`` text is primary and never replaced by later typed
    appends); ``append_param_value_if_not_there`` skips params already
    present under either their long name or short flag.

    >>> (ParamsStringBuilder(prefix="--", delimiter="=")
    ...  .append("--first_param=a")
    ...  .append_param_value_if_not_there("first_param", "a2")
    ...  .append_param_value_if_not_there("second_param", "b")
    ...  .append_param_value_if_not_there("third_param", None)
    ...  .result())
    '--first_param=a --second_param=b'
    """

    def __init__(self, prefix: str = "", delimiter: str = "="):
        self.prefix = prefix
        self.delimiter = delimiter
        self._parts: list[str] = []

    def _contains(self, name: str, short: str | None = None) -> bool:
        import re

        text = " ".join(self._parts)
        pats = [re.escape(self.prefix + name) + "[ =]",
                re.escape(self.prefix + name) + "$"]
        if short:
            pats += [re.escape("-" + short) + "[ =]",
                     re.escape("-" + short) + "$"]
        return any(re.search(p, text) for p in pats)

    def append(self, text: str) -> "ParamsStringBuilder":
        if text:
            self._parts.append(text)
        return self

    def append_param_value_if_not_there(self, name: str, value,
                                        short: str | None = None
                                        ) -> "ParamsStringBuilder":
        if value is None or self._contains(name, short):
            return self
        if isinstance(value, bool):
            value = str(value).lower()
        elif isinstance(value, (list, tuple)):
            value = ",".join(str(v) for v in value)
        self._parts.append(f"{self.prefix}{name}{self.delimiter}{value}")
        return self

    def append_flag_if_true(self, name: str, value: bool) -> "ParamsStringBuilder":
        if value and not self._contains(name):
            self._parts.append(f"{self.prefix}{name}")
        return self

    def result(self) -> str:
        return " ".join(self._parts)
