"""Estimator / Transformer / Pipeline — the SparkML-compatible stage API.

Reference: SparkML's ``Estimator.fit``/``Transformer.transform`` contract that
every SynapseML component implements (SURVEY.md §1 L3/L5/L6), plus
``Pipeline``/``PipelineModel`` chaining and MLWritable persistence
(``org/apache/spark/ml/ComplexParamsSerializer.scala``).

TPU-native notes: stages are plain Python objects; heavy state (jitted
executables, device arrays) is held in Model subclasses and rebuilt lazily
after load — persisted artifacts carry host-side numpy weights only, so a
pipeline saved on one mesh topology restores onto another.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Sequence

from .dataframe import DataFrame
from .logging import StageTelemetry
from .params import ComplexParam, Param, Params
from . import observability as _obs
from . import serialization

__all__ = ["PipelineStage", "Transformer", "Estimator", "Model", "Pipeline", "PipelineModel", "load_stage"]


class PipelineStage(Params, StageTelemetry):
    """Base of every stage; persists via metadata.json + out-of-band complex params."""

    def save(self, path: str, overwrite: bool = True) -> None:
        serialization.save_stage(self, path, overwrite=overwrite)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        stage = serialization.load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"loaded {type(stage).__name__}, expected {cls.__name__}")
        return stage

    def transform_schema(self, schema: dict) -> dict:
        """Best-effort schema propagation (SparkML transformSchema analog)."""
        return schema

    def require_columns(self, df: DataFrame, *cols: str) -> None:
        """Fail fast with a readable message when input columns are missing
        (SparkML validateSchema analog)."""
        missing = [c for c in cols if c not in df.columns]
        if missing:
            raise ValueError(f"{type(self).__name__} ({self.uid}): input column(s) "
                             f"{missing} not found; DataFrame has {df.columns}")


class Transformer(PipelineStage):
    def _transform(self, df: DataFrame) -> DataFrame:  # pragma: no cover - abstract
        raise NotImplementedError

    def transform(self, df: DataFrame) -> DataFrame:
        return self.log_verb("transform", self._transform, df)

    def transform_source(self, source, sink, **opts):
        """Bulk-score an out-of-core ``data.ShardedSource`` into an
        exactly-once sharded sink (``scoring.JsonlSink``/``NpySink``) — the
        Spark transform-over-arbitrarily-large-DataFrames role. Streams
        bucket-ladder batches through this transformer in bounded memory;
        kill/resume emits each input row exactly once. See
        :func:`synapseml_tpu.scoring.transform_source` for options and
        ``docs/SCORING.md`` for the contract."""
        from ..scoring.runner import transform_source as _transform_source

        return _transform_source(self, source, sink, **opts)

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    def _fit(self, df: DataFrame) -> "Model":  # pragma: no cover - abstract
        raise NotImplementedError

    def fit(self, df: DataFrame) -> "Model":
        model = self.log_verb("fit", self._fit, df)
        return model


class Model(Transformer):
    """A fitted Transformer (SparkML Model[M])."""


def load_stage(path: str) -> PipelineStage:
    return serialization.load_stage(path)


class Pipeline(Estimator):
    stages = ComplexParam("stages", "ordered list of pipeline stages")

    def __init__(self, stages: Sequence[PipelineStage] | None = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _fit(self, df: DataFrame) -> "PipelineModel":
        fitted: list[Transformer] = []
        cur = df
        stages = self.get("stages") or []
        tracer = _obs.get_tracer()
        for i, stage in enumerate(stages):
            # one span per pipeline slot (the stage's own fit/transform span
            # nests inside): a Pipeline fit exports as a span TREE —
            # Pipeline.fit -> pipeline.stage[i] -> Stage.fit/transform
            with tracer.span(f"pipeline.stage[{i}]",
                             {"stage": type(stage).__name__,
                              "uid": getattr(stage, "uid", "?")}):
                if isinstance(stage, Estimator):
                    model = stage.fit(cur)
                    fitted.append(model)
                    if i < len(stages) - 1:
                        cur = model.transform(cur)
                elif isinstance(stage, Transformer):
                    fitted.append(stage)
                    if i < len(stages) - 1:
                        cur = stage.transform(cur)
                else:
                    raise TypeError(f"pipeline stage {stage!r} is neither Estimator nor Transformer")
        return PipelineModel(stages=fitted)

    # persistence: stages are saved as numbered sub-directories
    def save(self, path: str, overwrite: bool = True) -> None:
        _save_pipeline_like(self, path, overwrite)

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        return _load_pipeline_like(path)


class PipelineModel(Model):
    stages = ComplexParam("stages", "ordered list of fitted transformers")

    def __init__(self, stages: Sequence[Transformer] | None = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _transform(self, df: DataFrame) -> DataFrame:
        cur = df
        tracer = _obs.get_tracer()
        for i, stage in enumerate(self.get("stages") or []):
            with tracer.span(f"pipeline.stage[{i}]",
                             {"stage": type(stage).__name__,
                              "uid": getattr(stage, "uid", "?")}):
                cur = stage.transform(cur)
        return cur

    def save(self, path: str, overwrite: bool = True) -> None:
        _save_pipeline_like(self, path, overwrite)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        return _load_pipeline_like(path)


def _save_pipeline_like(obj, path: str, overwrite: bool) -> None:
    serialization.prepare_dir(path, overwrite)
    stages = obj.get("stages") or []
    meta = {
        "class": f"{type(obj).__module__}.{type(obj).__qualname__}",
        "uid": obj.uid,
        "numStages": len(stages),
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)
    for i, stage in enumerate(stages):
        stage.save(os.path.join(path, f"stage_{i:03d}"), overwrite=overwrite)


def _load_pipeline_like(path: str):
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    mod_name, _, cls_name = meta["class"].rpartition(".")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    stages = [serialization.load_stage(os.path.join(path, f"stage_{i:03d}"))
              for i in range(meta["numStages"])]
    obj = cls(stages=stages)
    obj.uid = meta["uid"]
    return obj
