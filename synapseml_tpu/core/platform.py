"""Platform detection — which hosted environment is this process in.

Reference: ``logging/common/PlatformDetails.scala`` (Fabric via the
trident-context file, Synapse via ``AZURE_SERVICE``, Databricks via
``/dbfs``, Binder via env) and ``synapse/ml/core/platform`` on the Python
side. The TPU rebuild adds TPU-VM detection (libtpu accel devices / the
``TPU_NAME`` metadata env GKE and GCE TPU VMs export) since executor↔TPU-host
pinning decisions key off it.

``env``/``root`` are injectable so detection is unit-testable off-platform.
"""

from __future__ import annotations

import os

__all__ = [
    "PLATFORM_FABRIC", "PLATFORM_SYNAPSE", "PLATFORM_DATABRICKS",
    "PLATFORM_BINDER", "PLATFORM_TPU_VM", "PLATFORM_UNKNOWN",
    "current_platform", "running_on_fabric", "running_on_synapse",
    "running_on_databricks", "running_on_tpu_vm",
]

# names mirror PlatformDetails.scala (Fabric reports as synapse_internal)
PLATFORM_FABRIC = "synapse_internal"
PLATFORM_SYNAPSE = "synapse"
PLATFORM_DATABRICKS = "databricks"
PLATFORM_BINDER = "binder"
PLATFORM_TPU_VM = "tpu_vm"
PLATFORM_UNKNOWN = "unknown"

SYNAPSE_PROJECT_NAME = "Microsoft.ProjectArcadia"
TRIDENT_CONTEXT_PATH = "home/trusted-service-user/.trident-context"


def current_platform(env: dict | None = None, root: str = "/") -> str:
    """Detection precedence mirrors the reference: the trident-context file
    is authoritative for Fabric; ``AZURE_SERVICE`` marks Synapse; ``/dbfs``
    Databricks; Binder its launch-host env; then TPU-VM markers."""
    e = os.environ if env is None else env
    if os.path.exists(os.path.join(root, TRIDENT_CONTEXT_PATH)):
        return PLATFORM_FABRIC
    if e.get("AZURE_SERVICE") == SYNAPSE_PROJECT_NAME:
        return PLATFORM_SYNAPSE
    if os.path.exists(os.path.join(root, "dbfs")):
        return PLATFORM_DATABRICKS
    if "BINDER_LAUNCH_HOST" in e:
        return PLATFORM_BINDER
    if "TPU_NAME" in e or "TPU_WORKER_ID" in e \
            or os.path.exists(os.path.join(root, "dev", "accel0")):
        return PLATFORM_TPU_VM
    return PLATFORM_UNKNOWN


def running_on_fabric(env: dict | None = None, root: str = "/") -> bool:
    return current_platform(env, root) == PLATFORM_FABRIC


def running_on_synapse(env: dict | None = None, root: str = "/") -> bool:
    return current_platform(env, root) == PLATFORM_SYNAPSE


def running_on_databricks(env: dict | None = None, root: str = "/") -> bool:
    return current_platform(env, root) == PLATFORM_DATABRICKS


def running_on_tpu_vm(env: dict | None = None, root: str = "/") -> bool:
    return current_platform(env, root) == PLATFORM_TPU_VM
