"""SparkML-compatible parameter system.

Reference analogs:
  * ``Param``/``Params`` — SparkML's param machinery the whole reference rides on.
  * Complex (non-JSON) params — reference ``core/.../param/`` + the
    ``ComplexParamsSerializer`` (``org/apache/spark/ml/Serializer.scala``); here a
    ``ComplexParam`` marks values serialized out-of-band (npz/pickle) by
    :mod:`synapseml_tpu.core.serialization`.
  * ``ServiceParam`` (value-or-column per-row params,
    reference ``services/CognitiveServiceBase.scala:34-130``).
  * ``GlobalParams`` process-wide defaults registry
    (reference ``core/.../param/GlobalParams.scala:10-53``).
"""

from __future__ import annotations

import copy as _copy
import threading
import uuid
from typing import Any, Callable

__all__ = [
    "Param",
    "ComplexParam",
    "ServiceParam",
    "Params",
    "GlobalParams",
    "TypeConverters",
]


class TypeConverters:
    """Coercions applied on set(); mirrors pyspark.ml.param.TypeConverters."""

    @staticmethod
    def to_int(v):
        return int(v)

    @staticmethod
    def to_float(v):
        return float(v)

    @staticmethod
    def to_bool(v):
        if isinstance(v, str):
            return v.lower() in ("true", "1", "yes")
        return bool(v)

    @staticmethod
    def to_string(v):
        return str(v)

    @staticmethod
    def to_list(v):
        return list(v)

    @staticmethod
    def identity(v):
        return v


class Param:
    """A named, documented parameter attached to a Params class."""

    def __init__(self, name: str, doc: str = "", default: Any = None,
                 converter: Callable[[Any], Any] | None = None,
                 validator: Callable[[Any], bool] | None = None):
        self.name = name
        self.doc = doc
        self.default = default
        self.converter = converter
        self.validator = validator

    is_complex = False

    def coerce(self, value):
        if self.converter is not None and value is not None:
            value = self.converter(value)
        if self.validator is not None and value is not None and not self.validator(value):
            raise ValueError(f"invalid value for param {self.name}: {value!r}")
        return value

    def __repr__(self):
        return f"Param({self.name!r})"


class ComplexParam(Param):
    """Param whose value is not JSON-serializable (model weights, DataFrames,
    callables, estimators). Serialized out-of-band on save()."""

    is_complex = True


class ServiceParam(Param):
    """Value-or-column param: the value may be a literal applied to every row or
    the name of a column supplying a per-row value (reference
    ``HasServiceParams.getValueOpt`` pattern, ``CognitiveServiceBase.scala:34-130``)."""

    def __init__(self, name: str, doc: str = "", default: Any = None, **kw):
        super().__init__(name, doc, default, **kw)

    def coerce(self, value):
        # ("col", name) and ("lit", value) tagged tuples pass through untouched
        if isinstance(value, tuple) and len(value) == 2 and value[0] in ("col", "lit"):
            return value
        return super().coerce(value)


class _ParamsMeta(type):
    """Collects Param class attributes into a per-class registry."""

    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        registry: dict[str, Param] = {}
        for base in reversed(cls.__mro__):
            for k, v in vars(base).items():
                if isinstance(v, Param):
                    registry[k] = v
        cls._param_registry = registry
        return cls


class Params(metaclass=_ParamsMeta):
    """Base for everything with params. Generates get_X/set_X accessors
    dynamically, mirroring SparkML's ``getX``/``setX`` convention so reference
    users find the surface they expect."""

    def __init__(self, uid: str | None = None, **kwargs):
        self.uid = uid or f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._param_values: dict[str, Any] = {}
        self.set(**kwargs)

    # -------- core accessors --------
    @classmethod
    def params(cls) -> dict[str, Param]:
        return dict(cls._param_registry)

    def has_param(self, name: str) -> bool:
        return name in self._param_registry

    def is_set(self, name: str) -> bool:
        return name in self._param_values

    def is_defined(self, name: str) -> bool:
        return self.is_set(name) or self._param_registry[name].default is not None

    def get(self, name: str, default: Any = "__raise__") -> Any:
        if name not in self._param_registry:
            if default != "__raise__":
                return default
            raise KeyError(f"{type(self).__name__} has no param {name!r}")
        if name in self._param_values:
            return self._param_values[name]
        gp = GlobalParams.get_default(type(self), name)
        if gp is not _MISSING:
            return gp
        return self._param_registry[name].default

    def set(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            if k not in self._param_registry:
                raise KeyError(f"{type(self).__name__} has no param {k!r}; "
                               f"available: {sorted(self._param_registry)}")
            self._param_values[k] = self._param_registry[k].coerce(v)
        if kwargs:
            # runtime caches (jitted closures etc.) live in __dict__ under
            # "_cache_*" keys; any param change invalidates them so a baked-in
            # param value can never go stale (stages advertise mutability)
            for key in [k for k in self.__dict__ if k.startswith("_cache_")]:
                del self.__dict__[key]
        return self

    def clear(self, name: str) -> "Params":
        self._param_values.pop(name, None)
        return self

    def __getattr__(self, item: str):
        # get_foo / set_foo sugar (and camelCase setFoo/getFoo for Spark muscle memory)
        if item.startswith("get_"):
            name = item[4:]
            if name in self._param_registry:
                return lambda: self.get(name)
        elif item.startswith("set_"):
            name = item[4:]
            if name in self._param_registry:
                def setter(value, _name=name):
                    self.set(**{_name: value})
                    return self
                return setter
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {item!r}")

    # -------- lifecycle --------
    def copy(self, extra: dict | None = None) -> "Params":
        other = _copy.copy(self)
        other._param_values = dict(self._param_values)
        if extra:
            other.set(**extra)
        return other

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self._param_registry.items()):
            cur = self.get(name)
            lines.append(f"{name}: {p.doc} (default: {p.default!r}, current: {cur!r})")
        return "\n".join(lines)

    # -------- serialization split --------
    def simple_param_values(self) -> dict:
        return {k: v for k, v in self._param_values.items()
                if not self._param_registry[k].is_complex}

    def complex_param_values(self) -> dict:
        return {k: v for k, v in self._param_values.items()
                if self._param_registry[k].is_complex}

    # -------- ServiceParam resolution --------
    def resolve_row_param(self, name: str, partition: dict, n: int) -> list:
        """Resolve a ServiceParam into one value per row of a partition."""
        v = self.get(name)
        if isinstance(v, tuple) and len(v) == 2 and v[0] == "col":
            return list(partition[v[1]])
        if isinstance(v, tuple) and len(v) == 2 and v[0] == "lit":
            v = v[1]
        return [v] * n


_MISSING = object()


class GlobalParams:
    """Process-wide param defaults keyed by (class-or-ancestor, param name).

    Reference: ``core/.../param/GlobalParams.scala:10-53`` — e.g. setting
    ``OpenAISubscriptionKey`` once for every OpenAI stage in the session.
    """

    _lock = threading.Lock()
    _defaults: dict[tuple[str, str], Any] = {}

    @classmethod
    def set_default(cls, klass_or_name, param_name: str, value: Any) -> None:
        key = klass_or_name if isinstance(klass_or_name, str) else klass_or_name.__name__
        with cls._lock:
            cls._defaults[(key, param_name)] = value

    @classmethod
    def get_default(cls, klass: type, param_name: str):
        with cls._lock:
            for base in klass.__mro__:
                hit = cls._defaults.get((base.__name__, param_name), _MISSING)
                if hit is not _MISSING:
                    return hit
        return _MISSING

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._defaults.clear()
