"""Shared resilience kernel: retry policies, circuit breakers, deadlines.

Reference (SURVEY.md §2.5): the reference scatters failure handling across
``HandlingUtils.advancedUDF`` (retry/backoff/429), ``DistributedHTTPSource``
worker loss, and LightGBM's ``NetworkManager`` connect retries. Here one
kernel serves every plane — ``io/http.py`` (client retries),
``services/base.py`` (cognitive services + LRO polling),
``io/distributed_serving.py`` (per-worker circuit breakers replacing the bare
dead-timestamp map) and ``parallel/backend.py`` (deadline-bounded rendezvous)
— so semantics and instrumentation cannot diverge per module.

Three primitives:

* ``RetryPolicy`` — a backoff schedule with FULL JITTER (concurrent executors
  otherwise synchronize their retries into storms) and an optional
  ``RetryBudget`` (token bucket: each retry spends a token, each first-attempt
  success deposits a fraction back — a fleet-wide storm drains the bucket and
  clients fail fast instead of amplifying load);
* ``CircuitBreaker`` — closed/open/half-open with a failure-rate window and a
  probe interval (the distributed-serving "resurrection" timer becomes the
  half-open probe);
* ``Deadline`` — a propagated total time budget capping every attempt's
  timeout, so retries can never multiply worst-case latency.

Every plane increments counters on a per-plane ``InstrumentationMeasures``
(``resilience_measures(plane)``) so retries, breaker transitions, deadline
expiries, and injected faults (``core/faults.py``) show up in
``train_measures`` / serving stats as ``retry_count`` / ``breaker_open_count``
/ ``deadline_expired_count`` / ``faults_injected_count``.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import threading
import time
from typing import Callable

from .instrumentation import InstrumentationMeasures

__all__ = ["RetryPolicy", "RetryBudget", "CircuitBreaker", "Deadline",
           "DeadlineExpired", "resilience_measures", "reset_resilience_measures",
           "all_resilience_measures"]


# ---------------------------------------------------------------------------
# per-plane instrumentation registry
# ---------------------------------------------------------------------------

_COUNTERS = ("retry", "breaker_open", "deadline_expired", "faults_injected")
_PLANES: dict[str, InstrumentationMeasures] = {}
_PLANES_LOCK = threading.Lock()


def resilience_measures(plane: str) -> InstrumentationMeasures:
    """The shared ``InstrumentationMeasures`` for a named plane (``"http"``,
    ``"distributed_serving"``, ``"services"``, ``"parallel"``). Counters are
    pre-seeded at 0 so ``to_dict()`` always exports the full set."""
    with _PLANES_LOCK:
        m = _PLANES.get(plane)
        if m is None:
            m = InstrumentationMeasures()
            for name in _COUNTERS:
                m.count(name, 0)
            _PLANES[plane] = m
        return m


def reset_resilience_measures(plane: str | None = None) -> None:
    """Drop accumulated measures (tests; per-run stats snapshots)."""
    with _PLANES_LOCK:
        if plane is None:
            _PLANES.clear()
        else:
            _PLANES.pop(plane, None)


def all_resilience_measures() -> dict[str, dict]:
    with _PLANES_LOCK:
        planes = dict(_PLANES)
    return {name: m.to_dict() for name, m in planes.items()}


# ---------------------------------------------------------------------------
# retry budget + policy
# ---------------------------------------------------------------------------

class RetryBudget:
    """Token bucket bounding the RATE of retries, not just the count per call
    (the SRE "retry budget" pattern): each retry spends one token; each
    successful first attempt deposits ``deposit_per_success`` back, capped at
    ``max_tokens``. When the bucket is empty retries are skipped and the
    caller fails fast — a storm of failures cannot amplify itself into
    ``max_attempts x`` the offered load. Thread-safe, shared per client."""

    def __init__(self, max_tokens: float = 10.0,
                 deposit_per_success: float = 0.1,
                 initial_tokens: float | None = None):
        self.max_tokens = float(max_tokens)
        self.deposit_per_success = float(deposit_per_success)
        self._tokens = self.max_tokens if initial_tokens is None \
            else float(initial_tokens)
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def try_spend(self, n: float = 1.0) -> bool:
        """True (and spends) when the budget allows another retry."""
        with self._lock:
            if self._tokens < n:
                return False
            self._tokens -= n
            return True

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens,
                               self._tokens + self.deposit_per_success)


@dataclasses.dataclass
class RetryPolicy:
    """Jittered exponential backoff schedule + optional retry budget.

    ``backoffs_ms`` keeps the existing tuple shape used across the codebase
    (attempt i sleeps ~backoffs_ms[i]; total attempts = len + 1). With
    ``jitter`` (default), each sleep is drawn uniform(0, backoff] — FULL
    jitter, so concurrent executors never synchronize their retries. Pass a
    seeded ``random.Random`` as ``rng`` for reproducible schedules."""

    backoffs_ms: tuple = (100, 500, 1000)
    jitter: bool = True
    budget: RetryBudget | None = None
    rng: random.Random | None = None
    max_backoff_ms: float = 30_000.0

    @property
    def max_attempts(self) -> int:
        return len(self.backoffs_ms) + 1

    def backoff_ms(self, attempt: int) -> float:
        if not self.backoffs_ms:
            return 0.0
        base = min(float(self.backoffs_ms[min(attempt, len(self.backoffs_ms) - 1)]),
                   self.max_backoff_ms)
        if not self.jitter:
            return base
        r = self.rng if self.rng is not None else _SHARED_RNG
        return r.uniform(0.0, base)

    def acquire_retry(self) -> bool:
        """True when another retry is allowed (spends budget if present)."""
        return self.budget is None or self.budget.try_spend()

    def on_success(self, first_attempt: bool = True) -> None:
        """Report a successful request. Only FIRST-attempt successes deposit
        into the budget — a success that itself consumed a retry token must
        not replenish it, or the bucket drains far slower than the retry-rate
        bound intends."""
        if self.budget is not None and first_attempt:
            self.budget.deposit()


# module-shared rng: deterministic tests pass their own seeded Random
_SHARED_RNG = random.Random()


# ---------------------------------------------------------------------------
# deadline
# ---------------------------------------------------------------------------

class DeadlineExpired(TimeoutError):
    """The total time budget for an operation (all attempts) ran out."""


class Deadline:
    """A propagated total time budget. ``cap(timeout_s)`` bounds each
    attempt's timeout by the remaining budget so N retries can never take
    N x timeout; ``sleep_allowed(s)`` gates backoff sleeps the same way.
    ``clock`` is injectable for tests."""

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self._expires_at = clock() + self.budget_s

    def remaining(self) -> float:
        return self._expires_at - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def cap(self, timeout_s: float) -> float:
        """min(timeout_s, remaining); raises ``DeadlineExpired`` at 0."""
        rem = self.remaining()
        if rem <= 0.0:
            raise DeadlineExpired(
                f"deadline of {self.budget_s:.3f}s expired")
        return min(float(timeout_s), rem)

    def sleep_allowed(self, wait_s: float) -> bool:
        return wait_s < self.remaining()

    def __repr__(self):
        return f"Deadline(budget_s={self.budget_s}, remaining={self.remaining():.3f})"


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Closed / open / half-open breaker with a failure-RATE window.

    * closed: outcomes feed a ring of the last ``window`` results; when at
      least ``min_samples`` are present, at least one failed, and the failure
      fraction >= ``failure_rate_threshold``, the breaker OPENS (counted as
      ``breaker_open`` on ``measures``).
    * open: calls are refused until ``probe_interval_s`` has elapsed since
      the (most recent) failure that opened it, then the breaker moves to
      half-open — the distributed-serving "resurrection" timer.
    * half-open: up to ``half_open_probes`` leased probes; any success closes
      the breaker (clearing the window), a failure re-opens it. Probe leases
      that are never resolved (caller routed elsewhere) expire after another
      ``probe_interval_s`` so a leaked lease cannot wedge the breaker.

    ``failure_rate_threshold=0.0`` (with window=1) reproduces the old
    any-failure-marks-dead front semantics. ``clock`` is injectable so tests
    drive transitions without sleeping. Thread-safe."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_rate_threshold: float = 0.5, window: int = 10,
                 min_samples: int = 1, probe_interval_s: float = 2.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 measures: InstrumentationMeasures | None = None,
                 name: str = ""):
        self.failure_rate_threshold = float(failure_rate_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.half_open_probes = int(half_open_probes)
        self.min_samples = max(int(min_samples), 1)
        self.name = name
        self._outcomes: collections.deque = collections.deque(maxlen=max(int(window), 1))
        self._clock = clock
        self._measures = measures
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.opened_at: float | None = None
        self.last_failure_at: float | None = None
        self._half_open_at: float | None = None
        self._probes_leased = 0

    # -- transitions (lock held) ------------------------------------------
    def _to_open(self, now: float) -> None:
        self.state = self.OPEN
        self.opened_at = now
        self._probes_leased = 0
        if self._measures is not None:
            self._measures.count("breaker_open")

    def _to_half_open(self, now: float) -> None:
        self.state = self.HALF_OPEN
        self._half_open_at = now
        self._probes_leased = 0

    def _to_closed(self) -> None:
        self.state = self.CLOSED
        self._outcomes.clear()
        self._probes_leased = 0
        self.opened_at = None

    # -- queries / outcomes -----------------------------------------------
    def available(self) -> bool:
        """Read-only: would ``allow()`` grant a call right now? (Does not
        transition state or lease probes — safe for building candidate
        lists.)"""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            now = self._clock()
            if self.state == self.OPEN:
                return now - (self.opened_at or 0.0) >= self.probe_interval_s
            return self._probes_leased < self.half_open_probes or \
                now - (self._half_open_at or 0.0) >= self.probe_interval_s

    def allow(self) -> bool:
        """Lease one call: True in closed; in open, True only once the probe
        interval elapsed (transitioning to half-open); in half-open, True for
        up to ``half_open_probes`` outstanding probes."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            now = self._clock()
            if self.state == self.OPEN:
                if now - (self.opened_at or 0.0) < self.probe_interval_s:
                    return False
                self._to_half_open(now)
            elif now - (self._half_open_at or 0.0) >= self.probe_interval_s:
                # stale probe leases (caller never reported back): re-arm
                self._half_open_at = now
                self._probes_leased = 0
            if self._probes_leased < self.half_open_probes:
                self._probes_leased += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self.state != self.CLOSED:
                self._to_closed()   # probe (or desperation call) succeeded
                return
            self._outcomes.append(True)

    def record_failure(self) -> None:
        with self._lock:
            now = self._clock()
            self.last_failure_at = now
            if self.state == self.HALF_OPEN:
                self._to_open(now)   # probe failed: back to open
                return
            if self.state == self.OPEN:
                self.opened_at = now  # desperation probe failed: re-stamp
                return
            self._outcomes.append(False)
            n = len(self._outcomes)
            failures = n - sum(self._outcomes)
            if (n >= self.min_samples and failures >= 1
                    and failures / n >= self.failure_rate_threshold):
                self._to_open(now)

    def __repr__(self):
        return (f"CircuitBreaker({self.name or 'unnamed'}: {self.state}, "
                f"window={list(self._outcomes)})")
