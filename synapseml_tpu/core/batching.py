"""Shared shape-bucketed batching + compiled-executable cache.

The serve/predict hot path's central problem: Spark-style dynamic row counts
vs XLA's static shapes. ``ONNXModel`` solved it privately (fixed-size padded
microbatches -> one cached executable, ``onnx/model.py``); every other stage
re-traced whenever the request batch size changed. This module makes the
trick a framework-level service (the HFTA horizontal-fusion lesson crossed
with TVM's ahead-of-time executable reuse — PAPERS.md):

* :class:`ShapeBucketer` — a pow-2 (or configurable) bucket ladder for batch
  and sequence dims with pad/unpad helpers. A variable request stream maps
  onto a handful of static shapes, so the number of compiled executables is
  bounded by the ladder, not by the number of distinct request sizes.
* :class:`CompiledCache` — process-wide LRU of compiled callables keyed by
  ``(fn_id, bucket_shape, dtype)``. Thread-safe; hit/miss/evict counters and
  a trace-time histogram land in the :mod:`~synapseml_tpu.core.observability`
  registry, and every miss's first trace runs under a ``compile`` span so
  recompiles are visible in the serving timeline.

Adoption convention (enforced by the static check in ``test_codegen.py``):
stage transform paths never call ``jax.jit`` inline — the jit lives inside a
builder function (named ``build``/``_build*``) handed to
:meth:`CompiledCache.get`, so acquisition is always counted, bounded, and
warmable (``/admin/load`` precompiles the serve ladder through this cache).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from . import observability as obs

__all__ = [
    "ShapeBucketer", "CompiledCache",
    "get_compiled_cache", "reset_compiled_cache",
    "default_bucketer", "set_default_bucketer",
    "default_trial_bucketer", "set_default_trial_bucketer", "TRIAL_LADDER",
    "instance_token", "invalidate_token", "release_executables",
    "pad_rows", "unpad_rows", "round_up_to_multiple",
]


# hot-path metric handles (HandleCache: one registry-identity check per
# event instead of get-or-create lock traffic)
_CACHE_METRICS = obs.HandleCache(lambda reg: {
    "hits": reg.counter(
        "synapseml_compile_cache_hits_total",
        "CompiledCache lookups served by an existing executable", ("fn",)),
    "misses": reg.counter(
        "synapseml_compile_cache_misses_total",
        "CompiledCache lookups that built a new executable", ("fn",)),
    "aot_hits": reg.counter(
        "synapseml_compile_cache_aot_hits_total",
        "CompiledCache lookups served by a precompiled AOT executable "
        "blob instead of tracing", ("fn",)),
    "evictions": reg.counter(
        "synapseml_compile_cache_evictions_total",
        "CompiledCache LRU evictions", ("fn",)),
    "trace_ms": reg.histogram(
        "synapseml_compile_trace_ms",
        "wall time of the first (tracing/compiling) call of a cache miss",
        ("fn",)),
})


def _pow2_rungs(min_bucket: int, max_bucket: int, what: str) -> list[int]:
    rungs, b = [], max(int(min_bucket), 1)
    while b <= int(max_bucket):
        rungs.append(b)
        b *= 2
    if not rungs:
        raise ValueError(f"empty pow-2 {what} ladder: min={min_bucket} > "
                         f"max={max_bucket}")
    return rungs


def _smallest_rung_geq(ladder: tuple, n: int) -> int:
    """Smallest rung >= n; n itself past the top rung (beyond-ladder sizes
    keep their exact shape). The one bucketing scan for BOTH the batch and
    the sequence dimension."""
    for rung in ladder:
        if rung >= n:
            return rung
    return n


class ShapeBucketer:
    """Pow-2 / configurable bucket ladders for the batch AND sequence dims.

    ``bucket_for(n)`` returns the smallest batch-ladder rung >= n, so any
    stream of sizes compiles at most ``len(ladder)`` executables per
    function. ``cap`` arguments (a stage's ``batch_size``/
    ``mini_batch_size``) bound memory: :meth:`slices` chunks at the largest
    rung <= cap and pads only the final partial chunk to its own rung — a
    3-row request pays a rung-of-8 executable, not the full-cap one.

    The SEQUENCE ladder (``seq_ladder``, pow-2 16..4096 by default) buckets
    the token/page dimension the same way: a variable-length prompt pads up
    to :meth:`seq_bucket_for` so the token-serving plane compiles at most
    ladder-many prefill executables (bucketed prompt lens) and ladder-many
    decode executables (bucketed active-slot counts), never one per distinct
    length."""

    def __init__(self, ladder: Sequence[int] | None = None,
                 min_bucket: int = 8, max_bucket: int = 1024,
                 seq_ladder: Sequence[int] | None = None,
                 min_seq_bucket: int = 16, max_seq_bucket: int = 4096):
        if ladder is not None:
            rungs = sorted({int(b) for b in ladder})
            if not rungs or rungs[0] < 1:
                raise ValueError(f"bucket ladder must be positive ints: {ladder}")
        else:
            rungs = _pow2_rungs(min_bucket, max_bucket, "batch")
        self.ladder: tuple[int, ...] = tuple(rungs)
        if seq_ladder is not None:
            seq_rungs = sorted({int(b) for b in seq_ladder})
            if not seq_rungs or seq_rungs[0] < 1:
                raise ValueError(
                    f"seq ladder must be positive ints: {seq_ladder}")
        else:
            seq_rungs = _pow2_rungs(min_seq_bucket, max_seq_bucket, "seq")
        self.seq_ladder: tuple[int, ...] = tuple(seq_rungs)

    def __repr__(self):
        return (f"ShapeBucketer(ladder={list(self.ladder)}, "
                f"seq_ladder={list(self.seq_ladder)})")

    @property
    def max_bucket(self) -> int:
        return self.ladder[-1]

    def bucket_for(self, n: int, multiple_of: int = 1) -> int:
        """Smallest rung >= n (rounded up to ``multiple_of`` for mesh
        data-parallel divisibility). Sizes beyond the ladder keep their own
        exact shape — large offline scoring batches must not pad toward the
        next pow-2 (up to 2x wasted compute); only serving-sized batches
        bucket."""
        n = max(int(n), 1)
        return _round_up(_smallest_rung_geq(self.ladder, n), multiple_of)

    def cap_for(self, max_rows: int, multiple_of: int = 1) -> int:
        """Chunking cap: the largest rung <= max_rows, EXCEPT when max_rows
        sits outside the ladder entirely — below the smallest rung it stays
        a hard memory bound (never rounded up), above the largest rung it is
        honored exactly (a configured batch_size of 2048 must not be
        silently halved to the top rung on offline scans)."""
        cap = max(int(max_rows), 1)
        if cap <= self.ladder[-1]:
            for rung in reversed(self.ladder):
                if rung <= cap:
                    cap = rung
                    break
        return _round_up(cap, multiple_of)

    def buckets_upto(self, max_rows: int, multiple_of: int = 1) -> list[int]:
        """Every bucket :meth:`slices` can emit for a stream capped at
        ``max_rows`` — the warmup/precompile set, and the compile-count bound
        a mixed-size request stream must stay under."""
        cap = self.cap_for(max_rows, multiple_of)
        out = sorted({_round_up(r, multiple_of)
                      for r in self.ladder if r <= cap} | {cap})
        return out

    # ---- sequence/page dimension (token-serving plane) ----
    def seq_bucket_for(self, n: int, multiple_of: int = 1,
                       cap: int | None = None) -> int:
        """Smallest seq-ladder rung >= n (rounded up to ``multiple_of``; KV
        block lengths pass their block size so every prompt bucket tiles
        whole pages). ``cap`` clamps at a model's max_len: lengths beyond
        the ladder (or the cap) keep the cap's exact shape rather than
        padding toward the next pow-2."""
        n = max(int(n), 1)
        bucket = _round_up(_smallest_rung_geq(self.seq_ladder, n),
                           multiple_of)
        if cap is not None:
            cap = _round_up(int(cap), multiple_of)
            if n > cap:
                raise ValueError(f"sequence length {n} exceeds cap {cap}")
            bucket = min(bucket, cap)
        return bucket

    def seq_buckets_upto(self, max_len: int, multiple_of: int = 1) -> list[int]:
        """Every bucket :meth:`seq_bucket_for` can emit for lengths up to
        ``max_len`` — the prefill warmup/precompile set and the prefill
        compile-count bound for a variable-prompt-length stream."""
        cap = _round_up(int(max_len), multiple_of)
        out = sorted({_round_up(r, multiple_of)
                      for r in self.seq_ladder if r <= cap})
        if not out or out[-1] < cap:
            out.append(cap)
        return out

    def slices(self, n: int, max_rows: int,
               multiple_of: int = 1) -> Iterator[tuple[int, int, int]]:
        """Yield ``(start, stop, bucket)`` chunks covering ``n`` rows: full
        chunks of the ladder-aligned cap, the final partial chunk padded to
        its own (smaller) rung."""
        if n <= 0:
            return
        cap = self.cap_for(max_rows, multiple_of)
        for start in range(0, n, cap):
            stop = min(start + cap, n)
            yield start, stop, min(self.bucket_for(stop - start, multiple_of),
                                   cap)


def round_up_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` >= ``n`` (the one shared implementation;
    ``parallel/batching.py`` — the training-side batcher — re-exports it)."""
    m = max(int(m), 1)
    return ((int(n) + m - 1) // m) * m


_round_up = round_up_to_multiple


def pad_rows(a: np.ndarray, bucket: int, mode: str = "zero",
             constant: float = 0) -> np.ndarray:
    """Pad the leading (row) dim up to ``bucket``. ``mode='edge'`` repeats
    the last real row (ONNXModel's padding — safe for models where an
    all-zero row could hit a different numeric path); ``'constant'`` fills
    with ``constant`` (attention masks pad with 1 so pooled denominators
    stay nonzero)."""
    if a.dtype == object:
        raise TypeError("cannot pad an object-dtype column; featurize it "
                        "into a rectangular array first")
    n = a.shape[0]
    pad = int(bucket) - n
    if pad <= 0:
        return a
    if mode == "edge" and n:
        block = np.repeat(a[-1:], pad, axis=0)
    else:
        fill = constant if mode == "constant" else 0
        block = np.full((pad,) + a.shape[1:], fill, dtype=a.dtype)
    return np.concatenate([a, block], axis=0)


def unpad_rows(a, n_valid: int) -> np.ndarray:
    """Strip padded rows off a device result."""
    return np.asarray(a)[: int(n_valid)]


class CompiledCache:
    """Thread-safe LRU of compiled callables keyed by
    ``(fn_id, instance, bucket_shape, dtype)``.

    ``get`` returns the cached callable or invokes ``build`` (which returns
    the jitted callable — the only place stage code may touch ``jax.jit``).
    The miss's FIRST invocation is wrapped in a ``compile`` tracer span and
    its wall time lands in ``synapseml_compile_trace_ms{fn=...}`` — that
    first call is where JAX actually traces/compiles, so recompile stalls
    show up attributed in the serving timeline. Eviction drops the jit
    wrapper (and with it the underlying executables) once the cache exceeds
    ``capacity``."""

    def __init__(self, capacity: int = 128):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Callable]" = OrderedDict()
        # persistent second tier: AOT executable sets installed by the
        # deploy plane (registry/aot.py) — a miss consults these blobs
        # before tracing; capture is the publish-time recorder
        self._aot_providers: list = []
        self._capture = None
        # local mirrors of the registry counters: cheap to read in tests and
        # bench loops without parsing the exposition
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.aot_hits = 0
        self.trace_ms_total = 0.0  # wall spent in first (tracing) calls

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions, "size": len(self._entries),
                    "aot_hits": self.aot_hits,
                    "trace_ms_total": self.trace_ms_total}

    # ---- AOT second tier (registry/aot.py) ----
    def install_aot_provider(self, provider) -> None:
        """Add an artifact's executable-blob set as a lookup tier: misses
        consult it before tracing (the zero-cold-start deploy path)."""
        with self._lock:
            if provider not in self._aot_providers:
                self._aot_providers.append(provider)

    def remove_aot_provider(self, provider) -> None:
        """Detach a swapped-out artifact's blob tier (its in-memory entries
        stay until evicted with the pipeline's tokens)."""
        with self._lock:
            try:
                self._aot_providers.remove(provider)
            except ValueError:
                pass

    def set_capture(self, capture) -> None:
        """Install/clear the publish-time miss recorder
        (``registry.aot.AOTCapture``); capture itself is thread-scoped."""
        with self._lock:
            self._capture = capture

    def miss_count(self, fn_id: str) -> float:
        """Registry-backed per-function miss count (the acceptance surface:
        a mixed-size stream must stay <= the ladder size)."""
        return _CACHE_METRICS.get()["misses"].labels(fn=fn_id).value

    def get(self, fn_id: str, shape: tuple, build: Callable[[], Callable],
            *, instance: Any = None, dtype: Any = None) -> Callable:
        """The one jit-acquisition door. ``fn_id`` labels the metric series
        (e.g. ``"onnx_model"``); ``shape`` is the bucketed static shape key;
        ``instance`` discriminates stage instances/configs (use
        :func:`instance_token`, NOT ``id(self)`` — ids get reused after GC);
        ``dtype`` joins the key for dtype-polymorphic functions."""
        key = (fn_id, instance, tuple(shape), dtype)
        m = _CACHE_METRICS.get()
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                m["hits"].inc(fn=fn_id)
                return fn
            providers = tuple(self._aot_providers)
            capture = self._capture
        # second tier: the deploy plane's precompiled executable blobs — a
        # hit maps in a ready executable (no trace, no compile) and does
        # NOT count as a miss (the zero-cold-start acceptance surface)
        for provider in providers:
            try:
                fn = provider.lookup(fn_id, instance, key[2], dtype)
            except Exception:  # noqa: BLE001 - a broken provider must never
                fn = None      # take down serving; it just demotes to JIT
            if fn is not None:
                with self._lock:
                    existing = self._entries.get(key)
                    if existing is not None:
                        self._entries.move_to_end(key)
                        self.hits += 1
                        m["hits"].inc(fn=fn_id)
                        return existing
                    self._entries[key] = fn
                    self.aot_hits += 1
                    m["aot_hits"].inc(fn=fn_id)
                    while len(self._entries) > self.capacity:
                        evicted_key, _ = self._entries.popitem(last=False)
                        self.evictions += 1
                        m["evictions"].inc(fn=evicted_key[0])
                return fn
        # build outside the lock: builders are cheap (a jax.jit wrapper) but
        # may import jax lazily; a concurrent duplicate build is harmless
        # (last writer wins, both callables compute the same thing)
        built = build()
        if capture is not None:
            built = capture.wrap(key, built)
        fn = self._traced_first_call(built, fn_id, key)
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                m["hits"].inc(fn=fn_id)
                return existing
            self._entries[key] = fn
            self.misses += 1
            m["misses"].inc(fn=fn_id)
            while len(self._entries) > self.capacity:
                evicted_key, _ = self._entries.popitem(last=False)
                self.evictions += 1
                # attribute the eviction to the EVICTED entry's function —
                # that's the stage whose next request pays the recompile
                m["evictions"].inc(fn=evicted_key[0])
        return fn

    def _traced_first_call(self, fn: Callable, fn_id: str,
                           key: tuple) -> Callable:
        """Wrap so the first invocation (the real trace+compile) runs under
        a ``compile`` span + trace-time histogram; later calls pay one bool
        check."""
        state = {"first": True}
        first_lock = threading.Lock()

        def wrapper(*args, **kwargs):
            if state["first"]:
                with first_lock:
                    if state["first"]:
                        t0 = time.perf_counter()
                        with obs.get_tracer().span(
                                "compile",
                                {"fn": fn_id, "shape": str(key[2])}):
                            out = fn(*args, **kwargs)
                        dur_ms = (time.perf_counter() - t0) * 1e3
                        _CACHE_METRICS.get()["trace_ms"].observe(
                            dur_ms, fn=fn_id)
                        with self._lock:
                            self.trace_ms_total += dur_ms
                        state["first"] = False
                        return out
            return fn(*args, **kwargs)

        return wrapper

    def evict_instance(self, instance: Any) -> int:
        """Drop every entry keyed to ``instance`` (a stage's token). Called
        when a token is invalidated or a pipeline is hot-swapped out — an
        orphaned entry's build() closure pins the dead stage's full weights
        until LRU churn, which an idle server never generates. In-flight
        calls holding the callable keep working; only the cache's reference
        is dropped."""
        m = _CACHE_METRICS.get()
        with self._lock:
            doomed = [k for k in self._entries if k[1] == instance]
            for k in doomed:
                del self._entries[k]
                self.evictions += 1
                m["evictions"].inc(fn=k[0])
        return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# ---------------------------------------------------------------------------
# process-wide defaults
# ---------------------------------------------------------------------------

# the TRIAL-count ladder for horizontally fused training arrays (HPO):
# pow-2 from 1 so a compacting sweep (8 -> 5 -> 2 live trials) compiles at
# most len(ladder) step executables, never one per distinct trial count
TRIAL_LADDER: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)

_DEFAULT_CACHE = CompiledCache()
_DEFAULT_BUCKETER = ShapeBucketer()
_DEFAULT_TRIAL_BUCKETER = ShapeBucketer(ladder=TRIAL_LADDER)
_DEFAULT_LOCK = threading.Lock()


def get_compiled_cache() -> CompiledCache:
    """The process-wide cache every adopted stage acquires its jits from."""
    return _DEFAULT_CACHE


def reset_compiled_cache(capacity: int = 128) -> CompiledCache:
    """Fresh process-wide cache (tests). Registry counters are NOT reset —
    use ``observability.reset_registry()`` for that."""
    global _DEFAULT_CACHE
    with _DEFAULT_LOCK:
        _DEFAULT_CACHE = CompiledCache(capacity)
        return _DEFAULT_CACHE


def default_bucketer() -> ShapeBucketer:
    """The process-wide bucket ladder (pow-2 from 8 to 1024 unless
    replaced)."""
    return _DEFAULT_BUCKETER


def set_default_bucketer(bucketer: ShapeBucketer) -> ShapeBucketer:
    """Swap the process-wide ladder (serving config / tests); returns the
    previous one so callers can restore it."""
    global _DEFAULT_BUCKETER
    with _DEFAULT_LOCK:
        previous = _DEFAULT_BUCKETER
        _DEFAULT_BUCKETER = bucketer
        return previous


def default_trial_bucketer() -> ShapeBucketer:
    """The process-wide TRIAL-count ladder shared by every fused training
    array (``models.fused_trainer`` and the fused GBDT sweep): trial counts
    bucket to :data:`TRIAL_LADDER` rungs, so compile counts are bounded by
    the ladder size, not by how many distinct sweep widths a process runs."""
    return _DEFAULT_TRIAL_BUCKETER


def set_default_trial_bucketer(bucketer: ShapeBucketer) -> ShapeBucketer:
    """Swap the process-wide trial ladder (tests); returns the previous."""
    global _DEFAULT_TRIAL_BUCKETER
    with _DEFAULT_LOCK:
        previous = _DEFAULT_TRIAL_BUCKETER
        _DEFAULT_TRIAL_BUCKETER = bucketer
        return previous


# ---------------------------------------------------------------------------
# instance tokens: stable cache-key discriminators per stage instance
# ---------------------------------------------------------------------------

_TOKEN_SLOT = "_compiled_cache_token"


def instance_token(obj: Any) -> str:
    """Random per-instance token for CompiledCache keys. Unlike ``id(obj)``
    it is never reused after GC, and unlike a process-local counter it
    cannot collide across pickling boundaries (a stage pickled into a
    distributed-serving worker keeps its token, and any stage freshly
    minted in that worker draws a disjoint uuid — two DIFFERENT stages can
    never alias one executable, while identical pickled copies share theirs
    safely: any config change invalidates the token). Lazily created so
    stages built via ``cls.__new__`` (deserialization) work. Minting is
    locked: two serve-loop threads racing the first call on a shared stage
    must agree on ONE token, or each would populate the cache under its own
    and duplicate every compile."""
    tok = obj.__dict__.get(_TOKEN_SLOT)
    if tok is None:
        with _DEFAULT_LOCK:
            tok = obj.__dict__.get(_TOKEN_SLOT)
            if tok is None:
                tok = obj.__dict__[_TOKEN_SLOT] = uuid.uuid4().hex
    return tok


def invalidate_token(obj: Any) -> None:
    """Drop the instance token — the next :func:`instance_token` call mints
    a fresh one — and evict the old token's executables from the default
    cache (a dead config's closures pin its captured weights otherwise)."""
    tok = obj.__dict__.pop(_TOKEN_SLOT, None)
    if tok is not None:
        get_compiled_cache().evict_instance(tok)


def release_executables(stage: Any) -> None:
    """Invalidate the tokens of ``stage`` and any nested stages (Pipeline /
    PipelineModel ``stages`` param), evicting their cached executables —
    the hot-swap path calls this on the REPLACED pipeline so serving
    workers don't accumulate one dead model's weights per swap."""
    seen: set[int] = set()

    def walk(obj):
        if obj is None or id(obj) in seen:
            return
        seen.add(id(obj))
        invalidate_token(obj)
        getter = getattr(obj, "get", None)
        if callable(getter):
            try:
                children = getter("stages")
            except Exception:  # noqa: BLE001 — not every stage has 'stages'
                return
            if isinstance(children, (list, tuple)):
                for child in children:
                    walk(child)

    walk(stage)
