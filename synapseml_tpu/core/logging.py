"""Uniform stage telemetry — the SynapseMLLogging equivalent.

Reference: ``core/.../logging/SynapseMLLogging.scala:94-172`` — every stage wraps
fit/transform in ``logFit``/``logTransform`` emitting structured JSON (uid, class,
method, duration, schema size) with secrets scrubbed
(``logging/common/Scrubber.scala``). Here the same contract is a decorator pair
used by :class:`synapseml_tpu.core.pipeline.Transformer`/``Estimator``.
"""

from __future__ import annotations

import json
import logging
import re
import time
from typing import Any

logger = logging.getLogger("synapseml_tpu")

_SECRET_PAT = re.compile(
    r"(?i)(sig|key|token|secret|password|authorization|api[-_]?key)=([^&\s\"]+)")
_BEARER_PAT = re.compile(r"(?i)bearer\s+[a-z0-9\-_\.=]+")


def scrub(text: str) -> str:
    """Strip secrets out of log payloads (reference ``SASScrubber``)."""
    text = _SECRET_PAT.sub(lambda m: f"{m.group(1)}=####", text)
    return _BEARER_PAT.sub("Bearer ####", text)


_TELEMETRY_SINKS: list = []


def add_telemetry_sink(fn) -> None:
    """Register an extra consumer of every stage-event payload (e.g. Fabric
    certified events — ``services.fabric.install_certified_events``; the
    reference fans SynapseMLLogging out the same way)."""
    _TELEMETRY_SINKS.append(fn)


def remove_telemetry_sink(fn) -> None:
    if fn in _TELEMETRY_SINKS:
        _TELEMETRY_SINKS.remove(fn)


def log_stage_event(payload: dict) -> None:
    text = scrub(json.dumps(payload, default=str))
    logger.info(text)
    if _TELEMETRY_SINKS:
        # sinks get the SCRUBBED payload — they forward off-box (certified
        # events), so the secret-stripping must cover the fan-out path too
        sanitized = json.loads(text)
        for sink in _TELEMETRY_SINKS:
            try:
                sink(sanitized)
            except Exception:  # noqa: BLE001 — telemetry must never break a stage
                logger.debug("telemetry sink failed", exc_info=True)


class StageTelemetry:
    """Mixin providing log_fit / log_transform / log_verb wrappers."""

    feature_name: str = "core"

    def _emit(self, method: str, duration_ms: float, extra: dict[str, Any] | None = None,
              error: BaseException | None = None) -> None:
        payload = {
            "uid": getattr(self, "uid", "?"),
            "className": type(self).__name__,
            "featureName": self.feature_name,
            "method": method,
            "durationMs": round(duration_ms, 3),
        }
        if extra:
            payload.update(extra)
        if error is not None:
            payload["error"] = f"{type(error).__name__}: {error}"
        log_stage_event(payload)

    def log_verb(self, method: str, fn, *args, **kwargs):
        t0 = time.perf_counter()
        try:
            out = fn(*args, **kwargs)
        except BaseException as e:
            self._emit(method, (time.perf_counter() - t0) * 1e3, error=e)
            raise
        self._emit(method, (time.perf_counter() - t0) * 1e3)
        return out
