"""Uniform stage telemetry — the SynapseMLLogging equivalent.

Reference: ``core/.../logging/SynapseMLLogging.scala:94-172`` — every stage wraps
fit/transform in ``logFit``/``logTransform`` emitting structured JSON (uid, class,
method, duration, schema size) with secrets scrubbed
(``logging/common/Scrubber.scala``). Here the same contract is a decorator pair
used by :class:`synapseml_tpu.core.pipeline.Transformer`/``Estimator``.
"""

from __future__ import annotations

import json
import logging
import re
import time
from typing import Any

logger = logging.getLogger("synapseml_tpu")

_SECRET_WORDS = r"(?:sig|key|token|secret|password|authorization|api[-_]?key)"
_SECRET_PAT = re.compile(
    rf"(?i){_SECRET_WORDS}=[^&\s\"]+")
# JSON-style key/value pairs: the query-string pattern above only matched
# `key=value`, so `"apiKey": "abc"` / `"Ocp-Apim-Subscription-Key": "..."`
# sailed through log_stage_event and the telemetry sinks unscrubbed. Matches
# a quoted key CONTAINING a secret word followed by a quoted string value or
# a bare scalar (number / null / unquoted token).
_JSON_SECRET_PAT = re.compile(
    rf"(?i)(\"[^\"]*{_SECRET_WORDS}[^\"]*\"\s*:\s*)(\"(?:[^\"\\]|\\.)*\"|[^,}}\]\s]+)")
_BEARER_PAT = re.compile(r"(?i)bearer\s+[a-z0-9\-_\.=]+")
# Free-text PII the request logs the continual plane trains on actually
# carry — the key=value/JSON patterns above only catch NAMED secrets:
#   * email addresses,
#   * standalone JWT-shaped tokens (three dot-joined base64url segments,
#     the `eyJ` header prefix — bearer-less Authorization payloads),
#   * long digit runs (12+ digits, separators allowed: card/account/phone
#     numbers). Epoch-millisecond timestamps are 13 digits and DO scrub —
#     deliberate: over-scrubbing is visible in the counter, a leaked card
#     number is not.
_EMAIL_PAT = re.compile(r"[\w.+%-]+@[\w-]+\.[\w.-]{2,}")
_JWT_PAT = re.compile(r"eyJ[A-Za-z0-9_-]{6,}\.[A-Za-z0-9_-]{4,}"
                      r"\.[A-Za-z0-9_-]*")
_DIGITS_PAT = re.compile(r"\d(?:[ \-]?\d){11,}")

_SCRUB_KINDS = (("keyvalue", None), ("json", None), ("bearer", None),
                ("email", None), ("jwt", None), ("digits", None))


def _count_scrubs(counts: dict[str, int]) -> None:
    """Publish per-kind substitution counts on the observability plane
    (``synapseml_scrub_fields_total{kind}``) — silent over/under-scrubbing
    of the training logs becomes a visible series instead of a guess.
    Lazy import: core.logging must stay importable before observability."""
    if not counts:
        return
    try:
        from . import observability as obs

        counter = obs.get_registry().counter(
            "synapseml_scrub_fields_total",
            "fields masked by the log scrubber, by pattern kind", ("kind",))
        for kind, n in counts.items():
            counter.inc(n, kind=kind)
    except Exception:  # noqa: BLE001 — scrubbing must never fail a log call
        logger.debug("scrub counter emission failed", exc_info=True)


def scrub(text: str, counts: dict[str, int] | None = None) -> str:
    """Strip secrets AND free-text PII out of log payloads (reference
    ``SASScrubber``): query-string pairs (``sig=...``), JSON pairs
    (``"apiKey": "..."``), bearer tokens, emails, JWT-shaped tokens and
    long digit runs. Every substitution counts into
    ``synapseml_scrub_fields_total{kind}``; pass ``counts`` (mutated in
    place) to ALSO receive the per-kind tally — the request logger stamps
    it into each shard's DONE marker."""
    tally: dict[str, int] = {}

    def _sub(kind: str, pat: re.Pattern, repl, s: str) -> str:
        out, n = pat.subn(repl, s)
        if n:
            tally[kind] = tally.get(kind, 0) + n
        return out

    text = _sub("keyvalue", _SECRET_PAT,
                lambda m: m.group(0).split("=", 1)[0] + "=####", text)
    text = _sub("json", _JSON_SECRET_PAT,
                lambda m: m.group(1) + '"####"', text)
    text = _sub("bearer", _BEARER_PAT, "Bearer ####", text)
    text = _sub("jwt", _JWT_PAT, "####", text)
    text = _sub("email", _EMAIL_PAT, "####@####", text)
    text = _sub("digits", _DIGITS_PAT, "####", text)
    _count_scrubs(tally)
    if counts is not None:
        for kind, n in tally.items():
            counts[kind] = counts.get(kind, 0) + n
    return text


_SECRET_KEY_PAT = re.compile(rf"(?i){_SECRET_WORDS}")


def scrub_json(value, counts: dict[str, int] | None = None):
    """Scrub a decoded JSON value IN STRUCTURE (vs :func:`scrub`'s
    serialized-text patterns): secret-worded dict keys mask their scalar
    value, string values go through :func:`scrub`, and card-shaped
    numerics (12+ digits stored as a JSON number — invisible to the text
    patterns, and masking them textually would break the JSON) become
    ``"####"``. Always returns a JSON-serializable structure — what
    :func:`log_stage_event` and the continual plane's request logger
    write. ``counts`` (mutated in place) receives the per-kind tally."""
    if isinstance(value, str):
        return scrub(value, counts)
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, int) and len(str(abs(value))) >= 12:
        if counts is not None:
            counts["digits"] = counts.get("digits", 0) + 1
        _count_scrubs({"digits": 1})
        return "####"
    if isinstance(value, dict):
        out = {}
        for k, v in value.items():
            if isinstance(k, str) and _SECRET_KEY_PAT.search(k) \
                    and isinstance(v, (str, int, float)) \
                    and not isinstance(v, bool):
                if counts is not None:
                    counts["json"] = counts.get("json", 0) + 1
                _count_scrubs({"json": 1})
                out[k] = "####"
            else:
                out[k] = scrub_json(v, counts)
        return out
    if isinstance(value, (list, tuple)):
        return [scrub_json(v, counts) for v in value]
    return value


_TELEMETRY_SINKS: list = []


def add_telemetry_sink(fn) -> None:
    """Register an extra consumer of every stage-event payload (e.g. Fabric
    certified events — ``services.fabric.install_certified_events``; the
    reference fans SynapseMLLogging out the same way)."""
    _TELEMETRY_SINKS.append(fn)


def remove_telemetry_sink(fn) -> None:
    if fn in _TELEMETRY_SINKS:
        _TELEMETRY_SINKS.remove(fn)


def log_stage_event(payload: dict) -> None:
    # normalize (objects stringified) THEN scrub structurally: the masked
    # payload is valid JSON by construction — a textual digit-run mask on
    # a bare numeric token would have broken the sink round trip
    normalized = json.loads(json.dumps(payload, default=str))
    sanitized = scrub_json(normalized)
    logger.info(json.dumps(sanitized))
    # sinks get the SCRUBBED payload — they forward off-box (certified
    # events), so the secret-stripping must cover the fan-out path too
    for sink in _TELEMETRY_SINKS:
        try:
            sink(sanitized)
        except Exception:  # noqa: BLE001 — telemetry must never break a stage
            logger.debug("telemetry sink failed", exc_info=True)


class StageTelemetry:
    """Mixin providing log_fit / log_transform / log_verb wrappers.

    Every verb now ALSO lands on the unified observability plane
    (``core/observability.py``): a ``synapseml_stage_duration_ms`` histogram
    sample + event counter, and one trace span per fit/transform — so a
    ``Pipeline`` fit renders as a span tree (pipeline span -> per-stage
    spans) in the Chrome/Perfetto export."""

    feature_name: str = "core"

    def _emit(self, method: str, duration_ms: float, extra: dict[str, Any] | None = None,
              error: BaseException | None = None) -> None:
        payload = {
            "uid": getattr(self, "uid", "?"),
            "className": type(self).__name__,
            "featureName": self.feature_name,
            "method": method,
            "durationMs": round(duration_ms, 3),
        }
        if extra:
            payload.update(extra)
        if error is not None:
            payload["error"] = f"{type(error).__name__}: {error}"
        log_stage_event(payload)

    def log_verb(self, method: str, fn, *args, **kwargs):
        from . import observability as obs

        tracer = obs.get_tracer()
        cls = type(self).__name__
        t0 = time.perf_counter()
        with tracer.span(f"{cls}.{method}",
                         {"uid": getattr(self, "uid", "?"),
                          "featureName": self.feature_name}):
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:
                dt = (time.perf_counter() - t0) * 1e3
                obs.observe_stage(cls, method, dt, error=True)
                self._emit(method, dt, error=e)
                raise
        dt = (time.perf_counter() - t0) * 1e3
        obs.observe_stage(cls, method, dt)
        self._emit(method, dt)
        return out
