"""Uniform stage telemetry — the SynapseMLLogging equivalent.

Reference: ``core/.../logging/SynapseMLLogging.scala:94-172`` — every stage wraps
fit/transform in ``logFit``/``logTransform`` emitting structured JSON (uid, class,
method, duration, schema size) with secrets scrubbed
(``logging/common/Scrubber.scala``). Here the same contract is a decorator pair
used by :class:`synapseml_tpu.core.pipeline.Transformer`/``Estimator``.
"""

from __future__ import annotations

import json
import logging
import re
import time
from typing import Any

logger = logging.getLogger("synapseml_tpu")

_SECRET_WORDS = r"(?:sig|key|token|secret|password|authorization|api[-_]?key)"
_SECRET_PAT = re.compile(
    rf"(?i){_SECRET_WORDS}=[^&\s\"]+")
# JSON-style key/value pairs: the query-string pattern above only matched
# `key=value`, so `"apiKey": "abc"` / `"Ocp-Apim-Subscription-Key": "..."`
# sailed through log_stage_event and the telemetry sinks unscrubbed. Matches
# a quoted key CONTAINING a secret word followed by a quoted string value or
# a bare scalar (number / null / unquoted token).
_JSON_SECRET_PAT = re.compile(
    rf"(?i)(\"[^\"]*{_SECRET_WORDS}[^\"]*\"\s*:\s*)(\"(?:[^\"\\]|\\.)*\"|[^,}}\]\s]+)")
_BEARER_PAT = re.compile(r"(?i)bearer\s+[a-z0-9\-_\.=]+")


def scrub(text: str) -> str:
    """Strip secrets out of log payloads (reference ``SASScrubber``):
    query-string pairs (``sig=...``), JSON pairs (``"apiKey": "..."``,
    ``"Ocp-Apim-Subscription-Key": ...``) and bearer tokens."""
    text = _SECRET_PAT.sub(
        lambda m: m.group(0).split("=", 1)[0] + "=####", text)
    text = _JSON_SECRET_PAT.sub(lambda m: m.group(1) + '"####"', text)
    return _BEARER_PAT.sub("Bearer ####", text)


_TELEMETRY_SINKS: list = []


def add_telemetry_sink(fn) -> None:
    """Register an extra consumer of every stage-event payload (e.g. Fabric
    certified events — ``services.fabric.install_certified_events``; the
    reference fans SynapseMLLogging out the same way)."""
    _TELEMETRY_SINKS.append(fn)


def remove_telemetry_sink(fn) -> None:
    if fn in _TELEMETRY_SINKS:
        _TELEMETRY_SINKS.remove(fn)


def log_stage_event(payload: dict) -> None:
    text = scrub(json.dumps(payload, default=str))
    logger.info(text)
    if _TELEMETRY_SINKS:
        # sinks get the SCRUBBED payload — they forward off-box (certified
        # events), so the secret-stripping must cover the fan-out path too
        sanitized = json.loads(text)
        for sink in _TELEMETRY_SINKS:
            try:
                sink(sanitized)
            except Exception:  # noqa: BLE001 — telemetry must never break a stage
                logger.debug("telemetry sink failed", exc_info=True)


class StageTelemetry:
    """Mixin providing log_fit / log_transform / log_verb wrappers.

    Every verb now ALSO lands on the unified observability plane
    (``core/observability.py``): a ``synapseml_stage_duration_ms`` histogram
    sample + event counter, and one trace span per fit/transform — so a
    ``Pipeline`` fit renders as a span tree (pipeline span -> per-stage
    spans) in the Chrome/Perfetto export."""

    feature_name: str = "core"

    def _emit(self, method: str, duration_ms: float, extra: dict[str, Any] | None = None,
              error: BaseException | None = None) -> None:
        payload = {
            "uid": getattr(self, "uid", "?"),
            "className": type(self).__name__,
            "featureName": self.feature_name,
            "method": method,
            "durationMs": round(duration_ms, 3),
        }
        if extra:
            payload.update(extra)
        if error is not None:
            payload["error"] = f"{type(error).__name__}: {error}"
        log_stage_event(payload)

    def log_verb(self, method: str, fn, *args, **kwargs):
        from . import observability as obs

        tracer = obs.get_tracer()
        cls = type(self).__name__
        t0 = time.perf_counter()
        with tracer.span(f"{cls}.{method}",
                         {"uid": getattr(self, "uid", "?"),
                          "featureName": self.feature_name}):
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:
                dt = (time.perf_counter() - t0) * 1e3
                obs.observe_stage(cls, method, dt, error=True)
                self._emit(method, dt, error=e)
                raise
        dt = (time.perf_counter() - t0) * 1e3
        obs.observe_stage(cls, method, dt)
        self._emit(method, dt)
        return out
