"""Per-phase instrumentation measures + profiler trace helper.

Reference: ``lightgbm/.../LightGBMPerformance.scala`` —
``TaskInstrumentationMeasures`` mark columnStatistics/rowStatistics/sampling/
network-init/dataset-prep/training windows and travel back with results; VW
returns ``TrainingStats`` per partition (``VowpalWabbitBaseLearner.scala:71-96``).
Here one collector serves every engine: estimators thread an
``InstrumentationMeasures`` through fit and attach ``.to_dict()`` to the model
(``train_measures`` param), and ``profile_trace`` wraps ``jax.profiler.trace``
for on-demand XLA-level traces.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator

__all__ = ["InstrumentationMeasures", "profile_trace", "chip_peak_tflops"]

# bf16 peak TFLOPs per chip, by device_kind substring (for MFU reporting)
_CHIP_PEAK_TFLOPS = [
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0), ("v6", 918.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]


def chip_peak_tflops(device_kind: str) -> float | None:
    kind = (device_kind or "").lower()
    for key, peak in _CHIP_PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


class InstrumentationMeasures:
    """Named wall-clock phase windows + point marks + counters.

    ``measure(name)`` windows accumulate across repeated entries (loop
    phases); ``count(name)`` tallies events; everything exports as one flat
    dict of ``*_ms`` / ``*_count`` / mark timestamps.
    """

    def __init__(self):
        self._t0 = time.perf_counter()
        self._phases: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._marks: dict[str, float] = {}
        # every mutation is bumped from serving/executor threads (the
        # resilience planes share one collector per plane): ONE lock guards
        # phases, marks AND counts — measure()/mark() racing count() was a
        # real lost-update hole when threads shared a plane collector
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def measure(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed_ms = (time.perf_counter() - start) * 1e3
            with self._lock:
                self._phases[name] = self._phases.get(name, 0.0) + elapsed_ms

    def mark(self, name: str) -> None:
        at_ms = (time.perf_counter() - self._t0) * 1e3
        with self._lock:
            self._marks[name] = at_ms

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def phase_ms(self, name: str) -> float:
        with self._lock:
            return self._phases.get(name, 0.0)

    def to_dict(self) -> dict:
        with self._lock:  # snapshot under the lock: a half-applied measure()
            phases = dict(self._phases)  # must never tear the export
            counts = dict(self._counts)
            marks = dict(self._marks)
        out = {f"{k}_ms": round(v, 3) for k, v in phases.items()}
        out.update({f"{k}_count": v for k, v in counts.items()})
        out.update({f"{k}_at_ms": round(v, 3) for k, v in marks.items()})
        out["total_ms"] = round((time.perf_counter() - self._t0) * 1e3, 3)
        return out


@contextlib.contextmanager
def profile_trace(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """``jax.profiler.trace`` context: captures an XLA/TPU trace viewable in
    TensorBoard/Perfetto. The SURVEY §5 tracing-subsystem analog — wrap any
    fit/transform/bench region."""
    import jax.profiler

    with jax.profiler.trace(log_dir, create_perfetto_trace=False):
        yield
