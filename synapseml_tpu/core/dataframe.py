"""Partitioned columnar DataFrame — the data plane of the framework.

The reference (SynapseML) rides on Spark DataFrames: every estimator/transformer
consumes and produces a distributed, partitioned, schema'd table
(see reference ``core/src/main/scala/.../stages/*.scala`` usage of ``Dataset[Row]``).
This module provides the TPU-native equivalent: an eager, partitioned, columnar
table whose columns are numpy arrays, designed so that partitions map 1:1 onto
host feeding units for a TPU mesh (one partition == one host-local microbatch
producer, cf. SURVEY.md §2.7 item 1).

Design notes (TPU-first, not a Spark port):
  * Columns are numpy arrays, so a partition converts to device arrays with zero
    copies for numeric data; strings/objects stay host-side for tokenizers.
  * Partitioning is explicit and cheap (list of column dicts) — `repartition`
    re-slices views, it does not shuffle bytes through a JVM.
  * All transforms are eager; heavy compute belongs in jitted JAX functions,
    not in the data plane, so there is nothing for a lazy optimizer to fuse.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Mapping, Sequence

import numpy as np

__all__ = ["DataFrame", "Partition", "schema_of", "concat_partitions", "scalar_of"]

Partition = dict  # name -> np.ndarray, all the same length


def scalar_of(v: Any) -> Any:
    """Unwrap numpy scalars to python scalars (stable dict keys / comparisons)."""
    return v.item() if isinstance(v, np.generic) else v


def _as_column(values: Any, n: int | None = None) -> np.ndarray:
    """Coerce python values to a column array, keeping ragged/object data as dtype=object."""
    if isinstance(values, np.ndarray):
        return values
    if np.isscalar(values) or values is None:
        if n is None:
            raise ValueError("scalar column requires a length")
        arr = np.empty(n, dtype=object) if isinstance(values, (str, bytes, type(None))) else None
        if arr is not None:
            arr[:] = values
            return arr
        return np.full(n, values)
    values = list(values)
    if values and isinstance(values[0], (str, bytes, dict, list, tuple, np.ndarray, type(None))):
        # ragged / nested: keep as object column so downstream code can tokenize etc.
        if values and isinstance(values[0], (list, tuple, np.ndarray)):
            try:
                arr = np.asarray(values)
                if arr.dtype != object and arr.ndim >= 2:
                    return arr  # rectangular numeric nested column -> real ndarray
            except (ValueError, TypeError):
                pass
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr
    return np.asarray(values)


def _column_len(arr: np.ndarray) -> int:
    return arr.shape[0]


def schema_of(part: Partition) -> dict:
    """Lightweight schema: name -> (dtype string, per-row shape)."""
    out = {}
    for name, arr in part.items():
        shape = tuple(arr.shape[1:]) if isinstance(arr, np.ndarray) else ()
        dtype = str(arr.dtype) if isinstance(arr, np.ndarray) else type(arr).__name__
        out[name] = (dtype, shape)
    return out


def concat_partitions(parts: Sequence[Partition]) -> Partition:
    if not parts:
        return {}
    keys = list(parts[0].keys())
    out = {}
    for k in keys:
        cols = [p[k] for p in parts]
        if any(c.dtype == object for c in cols):
            merged = np.empty(sum(len(c) for c in cols), dtype=object)
            i = 0
            for c in cols:
                if c.dtype == object:
                    merged[i : i + len(c)] = c
                else:
                    # rectangular partition merging into a ragged column:
                    # assign row-by-row so numpy doesn't try to broadcast
                    for j in range(len(c)):
                        merged[i + j] = c[j]
                i += len(c)
            out[k] = merged
        else:
            out[k] = np.concatenate(cols, axis=0)
    return out


class DataFrame:
    """An eager, partitioned columnar table.

    Mirrors the portion of the Spark DataFrame API the reference's stages rely
    on (select/withColumn/mapPartitions/repartition/randomSplit/union/cache),
    cf. reference ``core/.../stages/`` and ``LightGBMBase.prepareDataframe``
    (``lightgbm/.../LightGBMBase.scala:109-144``).
    """

    def __init__(self, partitions: Sequence[Partition]):
        parts = [dict(p) for p in partitions if p]
        if not parts:
            parts = [{}]
        cols = list(parts[0].keys())
        for p in parts:
            if list(p.keys()) != cols:
                raise ValueError(f"inconsistent partition schemas: {list(p.keys())} vs {cols}")
        self._parts: list[Partition] = parts

    # ---------------- constructors ----------------
    @staticmethod
    def from_dict(data: Mapping[str, Any], num_partitions: int = 1) -> "DataFrame":
        cols = {}
        n = None
        for k, v in data.items():
            arr = _as_column(v, n)
            n = _column_len(arr) if n is None else n
            if _column_len(arr) != n:
                raise ValueError(f"column {k} length {_column_len(arr)} != {n}")
            cols[k] = arr
        df = DataFrame([cols])
        return df.repartition(num_partitions) if num_partitions > 1 else df

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]], num_partitions: int = 1) -> "DataFrame":
        if not rows:
            return DataFrame([{}])
        keys = list(rows[0].keys())
        data = {k: [r[k] for r in rows] for k in keys}
        return DataFrame.from_dict(data, num_partitions)

    @staticmethod
    def from_pandas(pdf, num_partitions: int = 1) -> "DataFrame":
        data = {c: pdf[c].to_numpy() for c in pdf.columns}
        return DataFrame.from_dict(data, num_partitions)

    # ---------------- introspection ----------------
    @property
    def columns(self) -> list[str]:
        return list(self._parts[0].keys())

    @property
    def schema(self) -> dict:
        return schema_of(self._parts[0])

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    @property
    def partitions(self) -> list[Partition]:
        return self._parts

    def count(self) -> int:
        return sum(_column_len(next(iter(p.values()))) if p else 0 for p in self._parts)

    def is_empty(self) -> bool:
        return self.count() == 0

    def __repr__(self) -> str:
        return f"DataFrame(rows={self.count()}, partitions={self.num_partitions}, schema={self.schema})"

    # ---------------- column ops ----------------
    def select(self, *cols: str) -> "DataFrame":
        names = list(cols[0]) if len(cols) == 1 and isinstance(cols[0], (list, tuple)) else list(cols)
        missing = [c for c in names if c not in self.columns]
        if missing:
            raise KeyError(f"columns not found: {missing}; have {self.columns}")
        return DataFrame([{c: p[c] for c in names} for p in self._parts])

    def drop(self, *cols: str) -> "DataFrame":
        names = set(cols[0]) if len(cols) == 1 and isinstance(cols[0], (list, tuple)) else set(cols)
        keep = [c for c in self.columns if c not in names]
        return self.select(keep)

    def with_column(self, name: str, fn_or_values: Any) -> "DataFrame":
        """Add/replace a column. ``fn_or_values`` is either a per-partition
        callable ``Partition -> array`` or a full-length array/list."""
        new_parts = []
        if callable(fn_or_values):
            for p in self._parts:
                col = _as_column(fn_or_values(p), _column_len(next(iter(p.values()))) if p else 0)
                q = dict(p)
                q[name] = col
                new_parts.append(q)
        else:
            arr = _as_column(fn_or_values, self.count())
            if _column_len(arr) != self.count():
                raise ValueError(f"column length {_column_len(arr)} != row count {self.count()}")
            i = 0
            for p in self._parts:
                n = _column_len(next(iter(p.values()))) if p else 0
                q = dict(p)
                q[name] = arr[i : i + n]
                i += n
                new_parts.append(q)
        return DataFrame(new_parts)

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        return DataFrame([{(new if k == old else k): v for k, v in p.items()} for p in self._parts])

    def with_columns(self, mapping: Mapping[str, Any]) -> "DataFrame":
        df = self
        for k, v in mapping.items():
            df = df.with_column(k, v)
        return df

    def __getitem__(self, name: str) -> np.ndarray:
        return self.collect_column(name)

    # ---------------- row ops ----------------
    def filter(self, fn: Callable[[Partition], np.ndarray]) -> "DataFrame":
        """fn: Partition -> boolean mask array."""
        out = []
        for p in self._parts:
            mask = np.asarray(fn(p), dtype=bool)
            out.append({k: v[mask] for k, v in p.items()})
        return DataFrame([p for p in out if p and _column_len(next(iter(p.values()))) > 0] or out[:1])

    def limit(self, n: int) -> "DataFrame":
        taken, out = 0, []
        for p in self._parts:
            if taken >= n:
                break
            cnt = _column_len(next(iter(p.values()))) if p else 0
            take = min(cnt, n - taken)
            out.append({k: v[:take] for k, v in p.items()})
            taken += take
        return DataFrame(out or [self._parts[0]])

    def map_partitions(self, fn: Callable[[Partition], Partition]) -> "DataFrame":
        """The workhorse — reference analog: ``df.rdd.mapPartitions`` used by every
        engine adapter (e.g. ``ONNXModel.scala:242``, ``HTTPTransformer.scala:122``)."""
        return DataFrame([fn(p) for p in self._parts])

    def map_rows(self, fn: Callable[[dict], dict]) -> "DataFrame":
        def per_part(p: Partition) -> Partition:
            n = _column_len(next(iter(p.values()))) if p else 0
            rows = [fn({k: v[i] for k, v in p.items()}) for i in range(n)]
            if not rows:
                return p
            return {k: _as_column([r[k] for r in rows]) for k in rows[0]}

        return self.map_partitions(per_part)

    # ---------------- partitioning ----------------
    def repartition(self, n: int) -> "DataFrame":
        if n <= 0:
            raise ValueError("num partitions must be positive")
        whole = concat_partitions(self._parts)
        total = _column_len(next(iter(whole.values()))) if whole else 0
        bounds = [round(i * total / n) for i in range(n + 1)]
        parts = [{k: v[bounds[i] : bounds[i + 1]] for k, v in whole.items()} for i in range(n)]
        return DataFrame(parts)

    def coalesce(self, n: int) -> "DataFrame":
        if n >= self.num_partitions:
            return self
        groups: list[list[Partition]] = [[] for _ in range(n)]
        per = math.ceil(self.num_partitions / n)
        for i, p in enumerate(self._parts):
            groups[min(i // per, n - 1)].append(p)
        return DataFrame([concat_partitions(g) for g in groups if g])

    # ---------------- combination ----------------
    def union(self, other: "DataFrame") -> "DataFrame":
        if self.columns != other.columns:
            raise ValueError(f"union schema mismatch: {self.columns} vs {other.columns}")
        return DataFrame(self._parts + other._parts)

    def random_split(self, weights: Sequence[float], seed: int = 0) -> list["DataFrame"]:
        whole = concat_partitions(self._parts)
        n = _column_len(next(iter(whole.values()))) if whole else 0
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n)
        w = np.asarray(weights, dtype=float)
        w = w / w.sum()
        bounds = np.concatenate([[0], np.round(np.cumsum(w) * n).astype(int)])
        out = []
        for i in range(len(weights)):
            idx = np.sort(perm[bounds[i] : bounds[i + 1]])
            out.append(DataFrame([{k: v[idx] for k, v in whole.items()}]))
        return out

    def sample(self, fraction: float, seed: int = 0, with_replacement: bool = False) -> "DataFrame":
        rng = np.random.default_rng(seed)
        out = []
        for p in self._parts:
            n = _column_len(next(iter(p.values()))) if p else 0
            if with_replacement:
                idx = rng.integers(0, max(n, 1), size=int(round(n * fraction)))
            else:
                idx = np.nonzero(rng.random(n) < fraction)[0]
            out.append({k: v[idx] for k, v in p.items()})
        return DataFrame(out)

    def sort(self, col: str, ascending: bool = True) -> "DataFrame":
        whole = concat_partitions(self._parts)
        order = np.argsort(whole[col], kind="stable")
        if not ascending:
            order = order[::-1]
        return DataFrame([{k: v[order] for k, v in whole.items()}])

    def cache(self) -> "DataFrame":
        return self  # eager: everything already materialized

    def group_by(self, *keys: str) -> "GroupedDataFrame":
        """Group rows by key column(s); aggregate with ``.agg(...)``.

        Host-side (collect + pandas groupby): the reference delegates this to
        Spark's shuffle; here grouping is metadata-scale work — the TPU plane
        carries the numeric compute, not the relational shuffle.
        """
        missing = [k for k in keys if k not in self.columns]
        if missing:
            raise KeyError(f"group_by keys {missing} not in {self.columns}")
        return GroupedDataFrame(self, keys)

    def join(self, other: "DataFrame", on: str | Sequence[str],
             how: str = "inner") -> "DataFrame":
        """Relational join on key column(s) (host-side pandas merge;
        ``how``: inner | left | right | outer). Result is single-partition —
        repartition() for parallel downstream stages."""
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"how must be inner|left|right|outer, got {how!r}")
        keys = [on] if isinstance(on, str) else list(on)
        for k in keys:
            if k not in self.columns:
                raise KeyError(f"join key {k!r} not in left columns {self.columns}")
            if k not in other.columns:
                raise KeyError(f"join key {k!r} not in right columns {other.columns}")
        merged = self.to_pandas().merge(other.to_pandas(), on=keys, how=how)
        return DataFrame.from_pandas(merged)

    # ---------------- materialization ----------------
    def collect(self) -> Partition:
        return concat_partitions(self._parts)

    def collect_column(self, name: str) -> np.ndarray:
        if name not in self.columns:
            raise KeyError(f"no column {name}; have {self.columns}")
        return concat_partitions([{name: p[name]} for p in self._parts])[name]

    def collect_rows(self) -> list[dict]:
        whole = self.collect()
        n = _column_len(next(iter(whole.values()))) if whole else 0
        return [{k: v[i] for k, v in whole.items()} for i in range(n)]

    def first(self) -> dict:
        rows = self.limit(1).collect_rows()
        if not rows:
            raise ValueError("empty DataFrame")
        return rows[0]

    def to_pandas(self):
        import pandas as pd

        whole = self.collect()
        flat = {}
        for k, v in whole.items():
            flat[k] = list(v) if v.ndim > 1 else v
        return pd.DataFrame(flat)


class GroupedDataFrame:
    """Result of :meth:`DataFrame.group_by`; terminal ``agg``/``count``."""

    _AGGS = ("sum", "mean", "min", "max", "count", "first", "std", "nunique")

    def __init__(self, df: DataFrame, keys):
        self._df = df
        self._keys = list(keys)

    def agg(self, spec: Mapping[str, str]) -> DataFrame:
        """``{column: aggregation}`` -> one row per group. Aggregations:
        sum | mean | min | max | count | first | std | nunique. Output
        columns are named ``{col}_{agg}`` (Spark's default naming)."""
        bad = {c: a for c, a in spec.items() if a not in self._AGGS}
        if bad:
            raise ValueError(f"unsupported aggregations {bad}; "
                             f"choose from {self._AGGS}")
        missing = [c for c in spec if c not in self._df.columns]
        if missing:
            raise KeyError(f"agg columns {missing} not in {self._df.columns}")
        pdf = self._df.to_pandas()
        out = pdf.groupby(self._keys, sort=True).agg(
            **{f"{c}_{a}": (c, a) for c, a in spec.items()}).reset_index()
        return DataFrame.from_pandas(out)

    def count(self) -> DataFrame:
        """Rows per group as a ``count`` column."""
        pdf = self._df.to_pandas()
        out = pdf.groupby(self._keys, sort=True).size().rename("count").reset_index()
        return DataFrame.from_pandas(out)
