"""Deterministic, seeded fault injection for the resilience layer.

The resilience kernel (``core/resilience.py``) is only trustworthy if every
failure path can be driven on demand, offline. This module installs a
process-wide ``FaultPlan`` via the ``inject_faults`` context manager; the two
transport hook points consult it before touching the network:

* ``io/http.py`` — the ``_urlopen`` send path calls ``plan.on_http_send(url)``
  (connection errors, 429/503 with ``Retry-After``, added latency, blackhole
  timeouts);
* ``io/distributed_serving.py`` — ``_ConnPool.get`` calls
  ``plan.on_connect((host, port))`` (worker crash / blackhole / connect
  refusal before any socket is opened);
* ``data/source.py`` — every guarded shard read calls
  ``plan.on_read(target)`` (slow / failing shard reads on the ``"data"``
  plane; the source retries them under its ``RetryPolicy``). Target the
  plane explicitly: ``FaultSpec(..., planes=("data",))``;
* ``continual/supervisor.py`` — the supervised train loop calls
  ``plan.on_training(target)`` at every attempt start and heartbeat
  (``target`` is ``attempt:<n>`` / ``step:<n>``), so a trainer crash at
  any step is one seeded ``FaultSpec(..., planes=("training",))`` away;
* ``parallel/gang.py`` — every worker heartbeat send calls
  ``plan.on_gang("beat:rank=<r>:step=<n>")`` on the ``"gang"`` plane:
  ``drop`` suppresses the beat (the driver's missed-beat detector fires),
  ``latency`` delays it (straggler), ``crash`` kills the worker at an
  exact step — chaos runs stay seeded-deterministic;
* ``continual/loop.py`` + ``continual/logger.py`` — every flywheel seam
  (watch / snapshot / train / eval / publish / canary / promote, and the
  request logger's shard commits) calls ``plan.on_continual(target)``.
  The loop contains the injected failure as one aborted iteration with
  ``prod`` untouched — the degradation contract ``tests/test_continual.py``
  drives seam by seam.

Faults are matched in order against the target (URL or ``host:port``
substring), gated by a per-spec remaining ``times`` count and a probability
drawn from ONE seeded ``random.Random`` — the same seed and the same call
sequence always yield the same injected sequence (asserted by
``tests/test_resilience.py``). Every injection is appended to
``plan.injected`` and counted as ``faults_injected`` on the plane's
``resilience_measures``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import email.message
import io
import random
import threading
import time
import urllib.error

from .resilience import resilience_measures

__all__ = ["FaultSpec", "FaultPlan", "inject_faults", "active_fault_plan"]

FAULT_KINDS = ("connection_error", "status", "latency", "blackhole", "crash",
               "drop")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault rule. ``kind``:

    * ``connection_error`` — raise ``ConnectionRefusedError`` (OSError);
    * ``status`` — raise ``urllib.error.HTTPError(status)`` with an optional
      ``Retry-After`` header (seconds or an HTTP-date string; http plane only);
    * ``latency`` — sleep ``latency_ms`` then proceed normally;
    * ``blackhole`` — sleep ``latency_ms`` then raise ``TimeoutError`` (the
      worker accepts nothing, the client's timeout fires);
    * ``crash`` — raise ``ConnectionResetError`` (the worker died mid-flight);
    * ``drop`` — silently suppress the guarded action (``gang`` plane:
      the heartbeat is not sent, modeling a lost datagram/partition —
      after ``latency_ms``, if set).
    """

    kind: str
    probability: float = 1.0
    times: int | None = None          # max injections; None = unlimited
    match: str | None = None          # substring of the target; None = all
    status: int = 503
    retry_after: str | float | None = None
    latency_ms: float = 0.0
    planes: tuple = ("http", "distributed_serving")

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {FAULT_KINDS}")


class FaultPlan:
    """An ordered list of ``FaultSpec`` rules + one seeded RNG. Thread-safe;
    ``injected`` is the deterministic log of (plane, kind, target) tuples."""

    def __init__(self, faults, seed: int = 0):
        self.faults: list[FaultSpec] = list(faults)
        self.seed = seed
        self._rng = random.Random(seed)
        self._fired = [0] * len(self.faults)
        self._lock = threading.RLock()
        self.injected: list[tuple[str, str, str]] = []

    def fired(self, index: int) -> int:
        with self._lock:
            return self._fired[index]

    def _select(self, plane: str, target: str) -> FaultSpec | None:
        with self._lock:
            for i, f in enumerate(self.faults):
                if plane not in f.planes:
                    continue
                if f.kind == "status" and plane != "http":
                    continue   # an HTTP status needs the urllib send path
                if f.times is not None and self._fired[i] >= f.times:
                    continue
                if f.match is not None and f.match not in target:
                    continue
                if f.probability < 1.0 and self._rng.random() >= f.probability:
                    continue
                self._fired[i] += 1
                self.injected.append((plane, f.kind, target))
                resilience_measures(plane).count("faults_injected")
                return f
        return None

    @staticmethod
    def _raise_fault(f: FaultSpec, target: str) -> None:
        if f.latency_ms > 0:
            time.sleep(f.latency_ms / 1000.0)
        if f.kind in ("latency", "drop"):
            # 'drop' only SUPPRESSES on hooks that can express it (the
            # gang plane returns True before reaching here); on a
            # raise-only hook it degrades to a recorded no-op rather than
            # falling through to a nonsense HTTP status
            return
        if f.kind == "connection_error":
            raise ConnectionRefusedError(f"injected connection error: {target}")
        if f.kind == "blackhole":
            raise TimeoutError(f"injected blackhole (timed out): {target}")
        if f.kind == "crash":
            raise ConnectionResetError(f"injected worker crash: {target}")
        # status
        headers = email.message.Message()
        if f.retry_after is not None:
            headers["Retry-After"] = str(f.retry_after)
        raise urllib.error.HTTPError(target, f.status,
                                     f"injected HTTP {f.status}", headers,
                                     io.BytesIO(b""))

    # -- hook points --------------------------------------------------------
    def on_http_send(self, url: str) -> None:
        """Called by the io/http send path before each real request."""
        f = self._select("http", url)
        if f is not None:
            self._raise_fault(f, url)

    def on_connect(self, key: tuple) -> None:
        """Called by the distributed-serving connection pool before handing
        out a (pooled or fresh) worker connection."""
        target = f"{key[0]}:{key[1]}"
        f = self._select("distributed_serving", target)
        if f is not None:
            self._raise_fault(f, target)

    def on_read(self, target: str) -> None:
        """Called by the streaming data plane before each physical shard
        read (``data/source.py``). ``connection_error``/``blackhole``/
        ``crash``/``latency`` model slow or failing storage; reads are
        retried by the source's ``RetryPolicy``."""
        f = self._select("data", target)
        if f is not None:
            self._raise_fault(f, target)

    def on_training(self, target: str) -> None:
        """Called by the training supervisor (``continual/supervisor.py``)
        at attempt starts and step heartbeats — ``crash`` models a dying
        trainer process; the supervisor restarts it under its
        ``RetryPolicy`` from the latest verified checkpoint."""
        f = self._select("training", target)
        if f is not None:
            self._raise_fault(f, target)

    def on_continual(self, target: str) -> None:
        """Called by the continual-training flywheel at every seam
        (``continual/loop.py`` / ``logger.py``) — an injected fault must
        abort ONE loop iteration without touching ``prod``."""
        f = self._select("continual", target)
        if f is not None:
            self._raise_fault(f, target)

    def on_gang(self, target: str) -> bool:
        """Called by the elastic gang channel (``parallel/gang.py``) before
        each worker heartbeat send — ``target`` is
        ``beat:rank=<r>:step=<n>``, so a seeded plan can drop or delay a
        specific host's beats (``drop``/``latency``) or kill the worker at
        an exact step (``crash`` → the heartbeat raises, the training
        process dies, the gang's failure detector takes over). Returns
        True when the beat must be SUPPRESSED (``drop``)."""
        f = self._select("gang", target)
        if f is None:
            return False
        if f.kind == "drop":
            if f.latency_ms > 0:
                time.sleep(f.latency_ms / 1000.0)
            return True
        self._raise_fault(f, target)
        return False


_ACTIVE: FaultPlan | None = None
_ACTIVE_LOCK = threading.Lock()


def active_fault_plan() -> FaultPlan | None:
    return _ACTIVE


@contextlib.contextmanager
def inject_faults(plan_or_faults, seed: int = 0):
    """Install a fault plan process-wide for the duration of the block::

        with inject_faults([FaultSpec("status", status=429, retry_after=0,
                                      times=2)]) as plan:
            resp = send_with_retries(req)
        assert len(plan.injected) == 2

    Accepts a ``FaultPlan`` or an iterable of ``FaultSpec``. Nesting is
    refused — one deterministic sequence at a time."""
    plan = plan_or_faults if isinstance(plan_or_faults, FaultPlan) \
        else FaultPlan(plan_or_faults, seed=seed)
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a fault plan is already active")
        _ACTIVE = plan
    try:
        yield plan
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
