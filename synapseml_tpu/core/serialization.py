"""Stage persistence: metadata.json + out-of-band complex params.

Reference: ``org/apache/spark/ml/{Serializer,ComplexParamsSerializer}.scala`` —
JSON for simple params, object serialization for complex ones (models,
DataFrames, UDFs). Here: JSON metadata + npz for numpy/pytree leaves + pickle
fallback for callables/objects, per complex param.
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
import shutil
from typing import Any

import numpy as np

__all__ = ["save_stage", "load_stage", "prepare_dir", "save_pytree",
           "load_pytree", "flatten_pytree", "tree_structure", "rebuild_pytree"]


def prepare_dir(path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)


def _flatten_pytree(tree: Any, prefix: str = "",
                    leaf_fn=np.asarray) -> dict[str, np.ndarray]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_pytree(v, f"{prefix}{k}/", leaf_fn))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_pytree(v, f"{prefix}{i}/", leaf_fn))
    else:
        out[prefix.rstrip("/")] = leaf_fn(tree)
    return out


# public aliases: the sharded checkpointer flattens each host's shard with
# the SAME naming/structure scheme as the single-file format, so an N-shard
# assembly and a plain save_pytree round-trip are byte-interchangeable.
# ``leaf_fn`` lets that caller keep RAW leaves (cross-process jax arrays
# cannot survive np.asarray) while sharing this one traversal/naming codec.
def flatten_pytree(tree: Any, prefix: str = "",
                   leaf_fn=np.asarray) -> dict[str, np.ndarray]:
    return _flatten_pytree(tree, prefix, leaf_fn)


def tree_structure(tree: Any) -> Any:
    return _tree_structure(tree)


def save_pytree(tree: Any, path: str) -> None:
    """Save a (possibly nested dict) pytree of arrays as one npz + structure JSON."""
    flat = _flatten_pytree(tree)
    np.savez(path + ".npz", **flat)
    structure = _tree_structure(tree)
    with open(path + ".tree.json", "w") as f:
        json.dump(structure, f)


def _tree_structure(tree: Any) -> Any:
    if isinstance(tree, dict):
        return {"__kind__": "dict", "items": {k: _tree_structure(v) for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        kind = "list" if isinstance(tree, list) else "tuple"
        return {"__kind__": kind, "items": [_tree_structure(v) for v in tree]}
    return {"__kind__": "leaf"}


def rebuild_pytree(structure: Any, flat: Any) -> Any:
    """Inverse of :func:`flatten_pytree` + :func:`tree_structure`:
    ``flat`` is any mapping of slash-joined leaf path -> array (an open
    npz works). The sharded-checkpoint assembler reuses this so its
    multi-shard reconstruction cannot drift from the single-file format."""

    def rebuild(node, prefix=""):
        kind = node["__kind__"]
        if kind == "dict":
            return {k: rebuild(v, f"{prefix}{k}/") for k, v in node["items"].items()}
        if kind in ("list", "tuple"):
            seq = [rebuild(v, f"{prefix}{i}/") for i, v in enumerate(node["items"])]
            return seq if kind == "list" else tuple(seq)
        return flat[prefix.rstrip("/")]

    return rebuild(structure)


def load_pytree(path: str) -> Any:
    data = np.load(path + ".npz", allow_pickle=False)
    with open(path + ".tree.json") as f:
        structure = json.load(f)
    return rebuild_pytree(structure, data)


def _is_array_pytree(v: Any) -> bool:
    if isinstance(v, (bytes, bytearray, str)):
        return False  # np.isscalar says True, but npz round-trips these as
        # 0-d S/U arrays that break len()/indexing consumers — pickle instead
    if isinstance(v, np.ndarray) or np.isscalar(v):
        return True
    if hasattr(v, "__array__") and hasattr(v, "dtype"):  # jax arrays
        return True
    if isinstance(v, dict):
        # non-str keys would be stringified by the npz flatten and not restored
        return (bool(v) and all(isinstance(k, str) for k in v)
                and all(_is_array_pytree(x) for x in v.values()))
    if isinstance(v, (list, tuple)):
        return bool(v) and all(_is_array_pytree(x) for x in v)
    return False


def save_stage(stage, path: str, overwrite: bool = True) -> None:
    from .pipeline import PipelineStage  # local import to avoid cycle

    prepare_dir(path, overwrite)
    complex_vals = stage.complex_param_values()
    meta = {
        "class": f"{type(stage).__module__}.{type(stage).__qualname__}",
        "uid": stage.uid,
        "params": _jsonify(stage.simple_param_values()),
        "complexParams": {},
    }
    for name, value in complex_vals.items():
        entry: dict[str, Any] = {}
        target = os.path.join(path, f"complex_{name}")
        if isinstance(value, PipelineStage):
            entry["kind"] = "stage"
            save_stage(value, target, overwrite=overwrite)
        elif isinstance(value, list) and value and all(isinstance(v, PipelineStage) for v in value):
            entry["kind"] = "stage_list"
            entry["n"] = len(value)
            for i, v in enumerate(value):
                save_stage(v, f"{target}_{i:03d}", overwrite=overwrite)
        elif _is_array_pytree(value):
            entry["kind"] = "pytree"
            save_pytree(_to_numpy_tree(value), target)
        else:
            entry["kind"] = "pickle"
            with open(target + ".pkl", "wb") as f:
                pickle.dump(value, f)
        meta["complexParams"][name] = entry
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)


def _to_numpy_tree(v: Any) -> Any:
    if isinstance(v, dict):
        return {k: _to_numpy_tree(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        t = [_to_numpy_tree(x) for x in v]
        return t if isinstance(v, list) else tuple(t)
    return np.asarray(v)


def _jsonify(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


def _unjsonify(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.asarray(v["__ndarray__"], dtype=v["dtype"])
        else:
            out[k] = v
    return out


def load_stage(path: str):
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    mod_name, _, cls_name = meta["class"].rpartition(".")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    stage = cls.__new__(cls)
    # re-run Params.__init__ machinery without subclass ctor side effects
    from .params import Params

    Params.__init__(stage, uid=meta["uid"])
    stage.set(**_unjsonify(meta["params"]))
    for name, entry in meta.get("complexParams", {}).items():
        target = os.path.join(path, f"complex_{name}")
        if entry["kind"] == "stage":
            value = load_stage(target)
        elif entry["kind"] == "stage_list":
            value = [load_stage(f"{target}_{i:03d}") for i in range(entry["n"])]
        elif entry["kind"] == "pytree":
            value = load_pytree(target)
        else:
            with open(target + ".pkl", "rb") as f:
                value = pickle.load(f)
        stage.set(**{name: value})
    # where this stage was loaded FROM: stages whose artifact carries
    # sidecar trees next to metadata.json (retrieval index shards,
    # published via ``ModelRegistry.publish(extra_tree=...)``) resolve
    # them lazily through this attribute
    stage._artifact_dir = os.path.abspath(path)
    if hasattr(stage, "_post_load"):
        stage._post_load()
    return stage
