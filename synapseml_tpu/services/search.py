"""AzureSearchWriter (reference ``search/AzureSearch.scala``): index DataFrame
rows into a search index via the batched documents/index REST API."""

from __future__ import annotations

import json

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam, TypeConverters
from ..io.http import AsyncHTTPClient, HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["AzureSearchWriter"]


class AzureSearchWriter(CognitiveServiceBase):
    index_name = Param("index_name", "target index")
    key_col = Param("key_col", "document key column", default="id")
    action_col = Param("action_col", "per-row @search.action column (None = upload)",
                       default=None)
    batch_size = Param("batch_size", "documents per request", default=100,
                       converter=TypeConverters.to_int)
    api_version = Param("api_version", "API version", default="2023-11-01")
    output_col = Param("output_col", "per-batch status column", default="status")

    def _endpoint(self) -> str:
        return (f"{(self.get('url') or '').rstrip('/')}/indexes/"
                f"{self.get('index_name')}/docs/index"
                f"?api-version={self.get('api_version')}")

    def write(self, df: DataFrame) -> list[dict]:
        """Push all rows; returns per-batch parsed replies."""
        self.require_columns(df, self.get("key_col"))
        client = AsyncHTTPClient(self.get("concurrency"), self.get("timeout_s"))
        rows = df.collect_rows()
        action_col = self.get("action_col")
        docs = []
        for r in rows:
            doc = {k: (v.item() if isinstance(v, np.generic) else
                       v.tolist() if isinstance(v, np.ndarray) else v)
                   for k, v in r.items() if k != action_col}
            doc["@search.action"] = (str(r[action_col]) if action_col else "upload")
            docs.append(doc)
        B = self.get("batch_size")
        key = self.get("subscription_key")
        if isinstance(key, tuple) and key[0] == "col":
            raise ValueError("AzureSearchWriter: subscription_key must be a "
                             "literal (the whole table writes with one key), "
                             "not a column binding")
        if isinstance(key, tuple) and key[0] == "lit":
            key = key[1]
        headers = {"Content-Type": "application/json",
                   **({"api-key": key} if key else {})}
        requests = [HTTPRequest(url=self._endpoint(), method="POST", headers=headers,
                                entity=json.dumps({"value": docs[i : i + B]}))
                    for i in range(0, len(docs), B)]
        out = []
        for resp in client.send_all(requests):
            parsed, err = self.handle_response(resp)
            out.append(parsed if err is None else {"error": err})
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        statuses = self.write(df)
        failed = [s for s in statuses if isinstance(s, dict) and s.get("error")]
        if failed:
            raise RuntimeError(f"AzureSearchWriter: {len(failed)} failed batches; "
                               f"first: {failed[0]}")
        return df
