"""AzureSearchWriter (reference ``search/AzureSearch.scala``): index DataFrame
rows into a search index via the batched documents/index REST API."""

from __future__ import annotations

import json

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam, TypeConverters
from ..io.http import AsyncHTTPClient, HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["AzureSearchWriter", "infer_index_schema"]


def _edm_type(value) -> str:
    """Map a sample column value to an EDM field type (the reference infers
    the index schema from the Spark schema, ``AzureSearch.scala:147``
    ``sparkTypeToEdmType``)."""
    if isinstance(value, (bool, np.bool_)):
        return "Edm.Boolean"
    if isinstance(value, (int, np.integer)):
        return "Edm.Int64"
    if isinstance(value, (float, np.floating)):
        return "Edm.Double"
    if isinstance(value, (list, tuple, np.ndarray)):
        inner = value[0] if len(value) else ""
        return f"Collection({_edm_type(inner)})"
    return "Edm.String"


def infer_index_schema(df: DataFrame, index_name: str, key_col: str = "id",
                       action_col: str | None = None,
                       sample_rows: int = 64) -> dict:
    """Build the index-definition JSON from the DataFrame's columns (reference
    ``AzureSearch.scala:147`` generates the fields list the same way; the key
    field is marked ``key`` and collections are non-sortable). Types come from
    the first non-None value per column within a bounded sample (this runtime
    has no static column schema to read, unlike the reference's Spark schema);
    an all-None column falls back to Edm.String."""
    rows = df.limit(sample_rows).collect_rows()
    if not rows:
        raise ValueError("cannot infer an index schema from an empty DataFrame")
    if key_col not in rows[0]:
        raise ValueError(f"key column {key_col!r} not in DataFrame columns "
                         f"{sorted(rows[0])}")
    fields = []
    for name in rows[0]:
        if name == action_col:
            continue
        value = next((r[name] for r in rows if r.get(name) is not None), None)
        edm = _edm_type(value)
        field = {"name": name, "type": edm,
                 "searchable": edm in ("Edm.String", "Collection(Edm.String)"),
                 "filterable": True, "retrievable": True,
                 "sortable": not edm.startswith("Collection"),
                 "facetable": not edm.startswith("Collection")}
        if name == key_col:
            field.update(type="Edm.String", key=True, sortable=True)
        fields.append(field)
    return {"name": index_name, "fields": fields}


class AzureSearchWriter(CognitiveServiceBase):
    index_name = Param("index_name", "target index")
    key_col = Param("key_col", "document key column", default="id")
    action_col = Param("action_col", "per-row @search.action column (None = upload)",
                       default=None)
    batch_size = Param("batch_size", "documents per request", default=100,
                       converter=TypeConverters.to_int)
    api_version = Param("api_version", "API version", default="2023-11-01")
    output_col = Param("output_col", "per-batch status column", default="status")
    create_index_if_not_exists = Param(
        "create_index_if_not_exists", "before writing, create the target "
        "index when absent, with a schema inferred from the DataFrame or "
        "taken from index_json (reference AzureSearchAPI.scala:64 "
        "createIfNoneExists)", default=False, converter=TypeConverters.to_bool)
    index_json = Param("index_json", "explicit index definition (dict or JSON "
                       "string); None = infer from the DataFrame", default=None)

    def _endpoint(self) -> str:
        return (f"{(self.get('url') or '').rstrip('/')}/indexes/"
                f"{self.get('index_name')}/docs/index"
                f"?api-version={self.get('api_version')}")

    def _literal_key(self) -> str | None:
        key = self.get("subscription_key")
        if isinstance(key, tuple) and key[0] == "col":
            raise ValueError("AzureSearchWriter: subscription_key must be a "
                             "literal (the whole table writes with one key), "
                             "not a column binding")
        if isinstance(key, tuple) and key[0] == "lit":
            key = key[1]
        return key

    def ensure_index(self, df: DataFrame, client: AsyncHTTPClient | None = None) -> bool:
        """Create the index when it doesn't exist (reference
        ``AzureSearchAPI.scala:64``): list existing index names, POST the
        definition when absent. Returns True when a create happened."""
        client = client or AsyncHTTPClient(1, self.get("timeout_s"))
        base = (self.get("url") or "").rstrip("/")
        ver = self.get("api_version")
        key = self._literal_key()
        headers = {"Content-Type": "application/json",
                   **({"api-key": key} if key else {})}
        listing = client.send_all([HTTPRequest(
            url=f"{base}/indexes?api-version={ver}&$select=name",
            method="GET", headers=headers)])[0]
        parsed, err = self.handle_response(listing)
        if err is not None:
            raise RuntimeError(f"AzureSearchWriter: listing indexes failed: {err}")
        names = {i.get("name") for i in (parsed or {}).get("value", [])}
        if self.get("index_name") in names:
            return False
        schema = self.get("index_json")
        if schema is None:
            schema = infer_index_schema(df, self.get("index_name"),
                                        self.get("key_col"),
                                        self.get("action_col"))
        elif isinstance(schema, str):
            schema = json.loads(schema)
        if schema.get("name") != self.get("index_name"):
            raise ValueError(f"index_json name {schema.get('name')!r} != "
                             f"index_name {self.get('index_name')!r}")
        created = client.send_all([HTTPRequest(
            url=f"{base}/indexes?api-version={ver}", method="POST",
            headers=headers, entity=json.dumps(schema))])[0]
        if created is None or created.status_code != 201:
            raise RuntimeError(
                "AzureSearchWriter: index creation failed: "
                f"{getattr(created, 'status_code', None)} "
                f"{getattr(created, 'text', '')[:300]}")
        return True

    def write(self, df: DataFrame) -> list[dict]:
        """Push all rows; returns per-batch parsed replies."""
        self.require_columns(df, self.get("key_col"))
        client = AsyncHTTPClient(self.get("concurrency"), self.get("timeout_s"))
        if self.get("create_index_if_not_exists"):
            self.ensure_index(df, client)
        rows = df.collect_rows()
        action_col = self.get("action_col")
        docs = []
        key_col = self.get("key_col")
        for r in rows:
            doc = {k: (v.item() if isinstance(v, np.generic) else
                       v.tolist() if isinstance(v, np.ndarray) else v)
                   for k, v in r.items() if k != action_col}
            # the index key field is always Edm.String (see infer_index_schema)
            doc[key_col] = str(doc[key_col])
            doc["@search.action"] = (str(r[action_col]) if action_col else "upload")
            docs.append(doc)
        B = self.get("batch_size")
        key = self._literal_key()
        headers = {"Content-Type": "application/json",
                   **({"api-key": key} if key else {})}
        requests = [HTTPRequest(url=self._endpoint(), method="POST", headers=headers,
                                entity=json.dumps({"value": docs[i : i + B]}))
                    for i in range(0, len(docs), B)]
        out = []
        for resp in client.send_all(requests):
            parsed, err = self.handle_response(resp)
            out.append(parsed if err is None else {"error": err})
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        statuses = self.write(df)
        failed = [s for s in statuses if isinstance(s, dict) and s.get("error")]
        if failed:
            raise RuntimeError(f"AzureSearchWriter: {len(failed)} failed batches; "
                               f"first: {failed[0]}")
        return df
