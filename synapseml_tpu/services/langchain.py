"""LangChain transformer.

Reference: ``cognitive/src/main/python/synapse/ml/services/langchain/
LangchainTransform.py`` — wraps a LangChain chain as a SparkML transformer
(text column in, chain output column out). Here the chain may be any object
exposing ``invoke``/``run``/``__call__`` (a langchain chain when that package
is present, or any callable), applied per row with per-row error capture.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Transformer

__all__ = ["LangChainTransformer"]


def _call_chain(chain, text: str):
    if hasattr(chain, "invoke"):
        return chain.invoke(text)
    if hasattr(chain, "run"):
        return chain.run(text)
    if callable(chain):
        return chain(text)
    raise TypeError(f"chain {type(chain).__name__} has no invoke/run/__call__")


class LangChainTransformer(Transformer):
    feature_name = "services"

    chain = ComplexParam("chain", "langchain chain (or any callable)")
    input_col = Param("input_col", "text input column", default="text")
    output_col = Param("output_col", "chain output column", default="out")
    error_col = Param("error_col", "per-row error column", default="errors")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        chain = self.get("chain")
        if chain is None:
            raise ValueError("LangChainTransformer requires chain=")

        def per_part(p):
            texts = p[self.get("input_col")]
            out = np.empty(len(texts), dtype=object)
            errs = np.empty(len(texts), dtype=object)
            for i, t in enumerate(texts):
                try:
                    out[i] = _call_chain(chain, str(t))
                    errs[i] = None
                except Exception as e:  # chain errors are data errors, not crashes
                    out[i] = None
                    errs[i] = f"{type(e).__name__}: {e}"
            q = dict(p)
            q[self.get("output_col")] = out
            q[self.get("error_col")] = errs
            return q

        return df.map_partitions(per_part)
