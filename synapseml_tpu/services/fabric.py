"""Microsoft Fabric platform glue — workspace context, tokens, endpoints,
certified-event telemetry.

Reference: ``fabric/FabricClient.scala`` (context-file parsing, workspace/
capacity/artifact IDs, ML workload endpoint construction incl. the
private-endpoint host form), ``fabric/FabricTokenParser.scala`` (JWT expiry),
``fabric/TokenLibrary.scala`` (platform token provider, reached by
reflection there — here an injectable callable), and
``logging/fabric/CertifiedEventClient.scala`` (usage telemetry posted to the
admin workload endpoint when running on Fabric, wired into every stage's
``SynapseMLLogging`` emission).

Everything is instance-based with injectable ``root``/``env``/token provider
so the full surface unit-tests off-platform (the reference needs a live
Trident runtime; SURVEY §2.5 "Fabric platform glue").
"""

from __future__ import annotations

import base64
import binascii
import json
import os
import time
import uuid

from ..core.platform import running_on_fabric

__all__ = ["FabricClient", "parse_jwt_expiry", "InvalidJwtToken",
           "JwtExpiryMissing", "log_to_certified_events",
           "install_certified_events"]


class InvalidJwtToken(ValueError):
    pass


class JwtExpiryMissing(ValueError):
    pass


def parse_jwt_expiry(token: str) -> int:
    """Expiry of a JWT in epoch **milliseconds** (FabricTokenParser.getExpiry).

    Decodes the base64url payload ([header].[payload].[signature]); a
    malformed token raises :class:`InvalidJwtToken`, a payload without
    ``exp`` raises :class:`JwtExpiryMissing`.
    """
    parts = token.split(".")
    if len(parts) != 3:
        raise InvalidJwtToken(f"JWT must have 3 segments, got {len(parts)}")
    payload = parts[1].replace("-", "+").replace("_", "/")
    payload += "=" * (-len(payload) % 4)
    try:
        decoded = json.loads(base64.b64decode(payload))
    except (binascii.Error, ValueError) as e:
        raise InvalidJwtToken(f"undecodable JWT payload: {e}") from e
    exp = decoded.get("exp")
    if not isinstance(exp, (int, float)):
        raise JwtExpiryMissing("JWT payload has no numeric 'exp' claim")
    return int(exp) * 1000


_CONTEXT_PATH = "home/trusted-service-user/.trident-context"
_SPARK_CONF_PATH = "opt/spark/conf/spark-defaults.conf"
_CLUSTER_INFO_PATH = "opt/health-agent/conf/cluster-info.json"

# pbienv -> shared PBI API host (FabricClient.getPbiSharedHost)
_PBI_HOSTS = {
    "edog": "powerbiapi.analysis-df.windows.net",
    "daily": "dailyapi.fabric.microsoft.com",
    "dxt": "dxtapi.fabric.microsoft.com",
    "msit": "msitapi.fabric.microsoft.com",
}


class FabricClient:
    """Workspace context + ML workload endpoints + authenticated usage POSTs.

    ``root`` points at the filesystem root holding the Trident context files
    (injectable for tests); ``token_provider`` returns the AAD access token
    (the reference reaches the Trident TokenLibrary by reflection — here the
    provider defaults to the ``SYNAPSEML_TPU_FABRIC_TOKEN`` env var).
    """

    def __init__(self, root: str = "/", env: dict | None = None,
                 token_provider=None, http_send=None):
        self.root = root
        self.env = os.environ if env is None else env
        self._token_provider = token_provider
        self._http_send = http_send  # injectable for tests
        self._context: dict | None = None

    # -------- context files --------
    def _read_kv(self, rel: str, sep) -> dict:
        """key/value lines; a VALUE still containing the separator marks an
        ambiguous entry and is dropped (the reference's rule). ``sep=None``
        splits on any whitespace run (spark-defaults.conf uses spaces OR
        tabs), stripping the value before the ambiguity check so ordinary
        multi-space alignment doesn't drop real entries."""
        out = {}
        try:
            with open(os.path.join(self.root, rel)) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    parts = line.split(sep, 1)
                    if len(parts) != 2:
                        continue
                    key, value = parts[0].strip(), parts[1].strip()
                    ambiguous = (any(c.isspace() for c in value)
                                 if sep is None else sep in value)
                    if key and value and not ambiguous:
                        out[key] = value
        except OSError:
            pass
        return out

    @property
    def context(self) -> dict:
        if self._context is None:
            ctx = self._read_kv(_CONTEXT_PATH, "=")
            ctx.update(self._read_kv(_SPARK_CONF_PATH, None))
            self._context = ctx
        return self._context

    def _cluster_metadata(self) -> dict:
        try:
            with open(os.path.join(self.root, _CLUSTER_INFO_PATH)) as f:
                return json.load(f).get("cluster_metadata", {}) or {}
        except (OSError, ValueError):
            return {}

    # -------- identity --------
    @property
    def capacity_id(self):
        return self.context.get("trident.capacity.id")

    @property
    def workspace_id(self):
        return (self.context.get("trident.artifact.workspace.id")
                or self.context.get("trident.workspace.id"))

    @property
    def artifact_id(self):
        return self.context.get("trident.artifact.id")

    @property
    def pbi_env(self) -> str:
        return self.context.get("spark.trident.pbienv", "public").lower()

    @property
    def workspace_pe_enabled(self) -> bool:
        return str(self._cluster_metadata().get("workspace-pe-enabled", "")
                   ).lower() == "true"

    # -------- hosts / endpoints --------
    @property
    def ml_workload_host(self):
        if self.workspace_pe_enabled:
            ws = self.workspace_id
            if not ws:
                return None
            cleaned = ws.lower().replace("-", "")
            mark = (f"{self.pbi_env}-"
                    if self.pbi_env in ("daily", "dxt", "msit") else "")
            return (f"https://{cleaned}.z{cleaned[:2]}."
                    f"{mark}c.fabric.microsoft.com")
        ep = self.context.get("trident.lakehouse.tokenservice.endpoint")
        if not ep:
            return None
        from urllib.parse import urlparse

        u = urlparse(ep)
        return f"{u.scheme}://{u.hostname}" if u.scheme and u.hostname else None

    @property
    def pbi_shared_host(self):
        if self.workspace_pe_enabled:
            ws = self.workspace_id
            if not ws:
                return None
            cleaned = ws.lower().replace("-", "")
            mark = self.pbi_env if self.pbi_env in ("daily", "dxt", "msit") else ""
            return (f"https://{cleaned}.z{cleaned[:2]}.w."
                    f"{mark}api.fabric.microsoft.com")
        host = self.context.get("spark.trident.pbiHost", "").strip()
        if host:
            host = host.replace("https://", "").replace("http://", "")
        else:
            host = _PBI_HOSTS.get(self.pbi_env, "api.fabric.microsoft.com")
        return "https://" + host

    def ml_workload_endpoint(self, endpoint_type: str) -> str:
        """(FabricClient.getMLWorkloadEndpoint) — ML | LlmPlugin | Automatic |
        Registry | MLAdmin."""
        return (f"{self.ml_workload_host or ''}/webapi/capacities/"
                f"{self.capacity_id or ''}/workloads/ML/{endpoint_type}/"
                f"Automatic/workspaceid/{self.workspace_id or ''}/")

    @property
    def cognitive_endpoint(self) -> str:
        return self.ml_workload_endpoint("ML") + "cognitive/"

    @property
    def openai_endpoint(self) -> str:
        return self.cognitive_endpoint + "openai/"

    # -------- auth / posting --------
    def access_token(self) -> str:
        if self._token_provider is not None:
            return self._token_provider()
        tok = self.env.get("SYNAPSEML_TPU_FABRIC_TOKEN")
        if not tok:
            raise RuntimeError(
                "no Fabric token available: pass token_provider= or set "
                "SYNAPSEML_TPU_FABRIC_TOKEN (the reference reaches the "
                "Trident TokenLibrary, which only exists on-platform)")
        return tok

    def auth_headers(self) -> dict:
        return {"Authorization": f"Bearer {self.access_token()}",
                "RequestId": str(uuid.uuid4()),
                "Content-Type": "application/json"}

    def usage_post(self, url: str, body: dict | str):
        from ..io.http import HTTPRequest, send_with_retries

        payload = body if isinstance(body, str) else json.dumps(body)
        req = HTTPRequest(url=url, method="POST", headers=self.auth_headers(),
                          entity=payload.encode())
        send = self._http_send or send_with_retries
        return send(req)


def log_to_certified_events(feature_name: str, activity_name: str,
                            attributes: dict | None = None,
                            client: FabricClient | None = None,
                            force: bool = False) -> bool:
    """(CertifiedEventClient.logToCertifiedEvents) — POST a usage event to
    the MLAdmin telemetry endpoint; no-op (returns False) off-Fabric."""
    client = client or FabricClient()
    if not force and not running_on_fabric(env=client.env, root=client.root):
        return False
    payload = {"timestamp": int(time.time()),
               "feature_name": feature_name,
               "activity_name": activity_name,
               "attributes": attributes or {}}
    client.usage_post(client.ml_workload_endpoint("MLAdmin") + "telemetry",
                      payload)
    return True


def assert_model_status(model_name: str, client: FabricClient | None = None) -> None:
    """(OpenAIFabricSetting.assertModelStatus) — check the Fabric tenant
    setting for a default OpenAI model and raise with the admin-facing
    guidance when it is disallowed/missing. A transport failure is tolerated
    (the reference: "likely running in the system context of Fabric")."""
    c = client or FabricClient()
    try:
        resp = c.usage_post(c.openai_endpoint + "tenantsetting", [model_name])
        body = resp.json()
        # the service keys by lowercase; tolerate verbatim-keyed responses
        status = body.get(model_name.lower(), body.get(model_name))
    except Exception:  # noqa: BLE001 — status check is advisory off-tenant
        return
    messages = {
        "Disallowed": f"Default OpenAI model {model_name} is Disallowed; "
                      "contact your admin to enable the default Fabric LLM "
                      "model, or set your own Azure OpenAI credentials.",
        "DisallowedForCrossGeo": f"Default OpenAI model {model_name} is "
                                 "Disallowed for Cross Geo; contact your "
                                 "admin or set your own Azure OpenAI "
                                 "credentials.",
        "ModelNotFound": f"Default OpenAI model {model_name} not found; "
                         "check the deployment name.",
        "InvalidResult": "Cannot get tenant admin setting status correctly",
    }
    if status in messages:
        raise RuntimeError(messages[status])
    if status not in ("Allowed", None):
        raise RuntimeError(
            f"Unexpected Fabric tenant-setting status {status!r} for "
            f"{model_name}")


_installed_sink = None
_install_lock = __import__("threading").Lock()
_WORKER_SHUTDOWN = object()  # sentinel: tells a replaced sink's worker to exit


def install_certified_events(client: FabricClient | None = None,
                             max_queue: int = 256):
    """Register certified-event emission as a telemetry sink: every stage's
    fit/transform log line also posts a usage event when on Fabric.

    ASYNCHRONOUS, like the reference (SynapseMLLogging posts certified
    events off-thread): the sink only enqueues; a daemon worker drains the
    bounded queue and events are DROPPED when it is full — stage latency can
    never be held hostage by the telemetry endpoint. Idempotent: re-running
    an install cell replaces the previous sink instead of stacking
    duplicates. Returns the sink (pass to ``remove_telemetry_sink`` to
    uninstall)."""
    import queue
    import threading

    from ..core import logging as stage_logging

    global _installed_sink
    c = client or FabricClient()
    q: queue.Queue = queue.Queue(maxsize=max_queue)

    def worker():
        while True:
            payload = q.get()
            try:
                if payload is _WORKER_SHUTDOWN:
                    return
                log_to_certified_events(payload.get("featureName", "core"),
                                        payload.get("method", "unknown"),
                                        {"uid": str(payload.get("uid", ""))},
                                        client=c)
            except Exception:  # noqa: BLE001 — telemetry must never raise
                pass
            finally:
                q.task_done()

    thread = threading.Thread(target=worker, daemon=True,
                              name="fabric-certified-events")
    thread.start()

    def sink(payload: dict) -> None:
        try:
            q.put_nowait(payload)
        except queue.Full:
            pass  # drop: telemetry must never block a stage

    sink._queue = q  # tests drain this to assert delivery
    sink._thread = thread
    with _install_lock:
        replaced = _installed_sink
        if replaced is not None:
            stage_logging.remove_telemetry_sink(replaced)
        stage_logging.add_telemetry_sink(sink)
        _installed_sink = sink
    if replaced is not None:
        # release the replaced worker — without the sentinel it would block
        # on its queue's get() forever, leaking one thread per re-run of the
        # install cell. Done AFTER dropping the lock so a wedged worker
        # can't stall other installers. The worker drains concurrently, so
        # every queue op here can race (Full/Empty both possible at any
        # attempt); retry, then fall back to a bounded blocking put.
        old_q = replaced._queue
        for _ in range(4):
            try:
                old_q.put_nowait(_WORKER_SHUTDOWN)
                break
            except queue.Full:
                try:
                    old_q.get_nowait()  # make room for the sentinel
                    old_q.task_done()
                except queue.Empty:
                    pass
        else:
            try:
                old_q.put(_WORKER_SHUTDOWN, timeout=1.0)
            except queue.Full:
                pass  # worker wedged mid-post; it is a daemon — abandon
    return sink
