"""Form recognizer / document intelligence services.

Reference: ``cognitive/.../services/form/FormRecognizer.scala`` (AnalyzeDocument
family — LRO transformers posting a document URL or bytes and polling the
result) and ``FormOntologyLearner.scala`` (an Estimator that unions the
per-document field schemas of analyzed forms into one ontology).
"""

from __future__ import annotations

import json
from collections import Counter

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam, TypeConverters
from ..core.pipeline import Estimator, Model
from ..io.http import HTTPRequest
from .base import HasAsyncReply

__all__ = ["AnalyzeDocument", "AnalyzeLayout", "AnalyzeReceipts",
           "AnalyzeInvoices", "AnalyzeBusinessCards", "AnalyzeIDDocuments",
           "FormOntologyLearner", "FormOntologyTransformer"]


class AnalyzeDocument(HasAsyncReply):
    """(ref ``FormRecognizer.scala`` AnalyzeDocument) — POST a document (URL
    column or bytes column) to a prebuilt/custom model; 202 + poll."""

    model_id = Param("model_id", "prebuilt-* or custom model id",
                     default="prebuilt-document")
    image_url_col = Param("image_url_col", "column of document URLs (exclusive "
                          "with image_bytes_col)", default=None)
    image_bytes_col = Param("image_bytes_col", "column of raw document bytes",
                            default=None)
    api_version = Param("api_version", "API version", default="2023-07-31")
    pages = ServiceParam("pages", "page range, e.g. '1-3'", default=None)
    locale = ServiceParam("locale", "document locale hint", default=None)

    def input_bindings(self):
        out = {}
        if self.get("image_url_col"):
            out["_url"] = "image_url_col"
        if self.get("image_bytes_col"):
            out["_bytes"] = "image_bytes_col"
        if not out:
            raise ValueError(f"{type(self).__name__} needs image_url_col or "
                             f"image_bytes_col")
        return out

    def _endpoint(self) -> str:
        query = f"api-version={self.get('api_version')}"
        return (f"{(self.get('url') or '').rstrip('/')}/formrecognizer/"
                f"documentModels/{self.get('model_id')}:analyze?{query}")

    def build_request(self, rp: dict) -> HTTPRequest | None:
        url = self._endpoint()
        params = {k: rp.get(k) for k in ("pages", "locale") if rp.get(k)}
        if params:
            url += "&" + "&".join(f"{k}={v}" for k, v in params.items())
        if rp.get("_url") is not None:
            return self.json_request(rp, url, {"urlSource": str(rp["_url"])})
        if rp.get("_bytes") is not None:
            headers = {"Content-Type": "application/octet-stream",
                       **self.auth_headers(rp)}
            return HTTPRequest(url=url, method="POST", headers=headers,
                               entity=bytes(rp["_bytes"]))
        return None

    def parse_response(self, payload):
        if isinstance(payload, dict) and "analyzeResult" in payload:
            return payload["analyzeResult"]
        return payload


class AnalyzeLayout(AnalyzeDocument):
    model_id = Param("model_id", "fixed model", default="prebuilt-layout")


class AnalyzeReceipts(AnalyzeDocument):
    model_id = Param("model_id", "fixed model", default="prebuilt-receipt")


class AnalyzeInvoices(AnalyzeDocument):
    model_id = Param("model_id", "fixed model", default="prebuilt-invoice")


class AnalyzeBusinessCards(AnalyzeDocument):
    model_id = Param("model_id", "fixed model", default="prebuilt-businessCard")


class AnalyzeIDDocuments(AnalyzeDocument):
    model_id = Param("model_id", "fixed model", default="prebuilt-idDocument")


def _walk_fields(fields: dict, prefix: str = "") -> list[tuple[str, str]]:
    """Flatten a documents[].fields dict into (dotted name, value type)."""
    out = []
    for name, spec in (fields or {}).items():
        if not isinstance(spec, dict):
            continue
        t = spec.get("type", "string")
        path = f"{prefix}{name}"
        out.append((path, t))
        if t == "object":
            out.extend(_walk_fields(spec.get("valueObject", {}), path + "."))
        elif t == "array":
            for item in spec.get("valueArray", [])[:1]:
                if isinstance(item, dict) and item.get("type") == "object":
                    out.extend(_walk_fields(item.get("valueObject", {}),
                                            path + "[]."))
    return out


class FormOntologyLearner(Estimator):
    """(ref ``FormOntologyLearner.scala``) — unions the field schemas seen in
    a column of AnalyzeDocument results into one ontology, producing a
    transformer that projects each document onto the learned columns."""

    feature_name = "services"

    input_col = Param("input_col", "column of analyzeResult payloads",
                      default="analysis")
    output_col = Param("output_col", "projected ontology struct column",
                       default="ontology")
    min_frequency = Param("min_frequency", "drop fields seen fewer times",
                          default=1, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> "FormOntologyTransformer":
        self.require_columns(df, self.get("input_col"))
        counts: Counter = Counter()
        types: dict[str, str] = {}
        for payload in df.collect_column(self.get("input_col")):
            if not isinstance(payload, dict):
                continue
            for doc in payload.get("documents", []):
                for path, t in _walk_fields(doc.get("fields", {})):
                    counts[path] += 1
                    types.setdefault(path, t)
        fields = sorted(p for p, c in counts.items()
                        if c >= self.get("min_frequency"))
        return FormOntologyTransformer(
            input_col=self.get("input_col"), output_col=self.get("output_col"),
            ontology={p: types[p] for p in fields})


class FormOntologyTransformer(Model):
    feature_name = "services"

    input_col = Param("input_col", "column of analyzeResult payloads",
                      default="analysis")
    output_col = Param("output_col", "projected struct column", default="ontology")
    ontology = Param("ontology", "learned {field path: type}", default=None)

    @staticmethod
    def _value_of(spec: dict):
        if not isinstance(spec, dict):
            return None
        t = spec.get("type", "string")
        t_key = t[0].upper() + t[1:] if t else ""  # camelCase-safe (phoneNumber)
        for key in (f"value{t_key}", "valueString", "valueNumber",
                    "valueDate", "content"):
            if key in spec:
                return spec[key]
        return spec.get("content")

    def _project(self, payload) -> dict:
        out = {p: None for p in (self.get("ontology") or {})}
        if not isinstance(payload, dict):
            return out
        for doc in payload.get("documents", []):
            flat: dict[str, dict] = {}

            def flatten(fields, prefix=""):
                for name, spec in (fields or {}).items():
                    if not isinstance(spec, dict):
                        continue
                    flat[f"{prefix}{name}"] = spec
                    if spec.get("type") == "object":
                        flatten(spec.get("valueObject", {}), f"{prefix}{name}.")

            flatten(doc.get("fields", {}))
            for p in out:
                if out[p] is None and p in flat:
                    out[p] = self._value_of(flat[p])
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            vals = p[self.get("input_col")]
            col = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                col[i] = self._project(v)
            q = dict(p)
            q[self.get("output_col")] = col
            return q

        return df.map_partitions(per_part)
