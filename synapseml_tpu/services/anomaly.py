"""Anomaly detector services.

Reference: ``cognitive/.../services/anomaly/AnomalyDetection.scala``
(DetectLastAnomaly / DetectAnomalies / SimpleDetectAnomalies over timestamped
series) and ``MultivariateAnomalyDetection.scala:184-269`` (FitMultivariate-
AnomalyDetector: an *Estimator* whose fit() runs an LRO training job and whose
model polls inference jobs).
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam, TypeConverters
from ..core.pipeline import Estimator, Model
from ..io.http import HTTPRequest, send_with_retries
from .base import CognitiveServiceBase, HasAsyncReply

__all__ = ["DetectLastAnomaly", "DetectAnomalies", "SimpleDetectAnomalies",
           "FitMultivariateAnomaly", "DetectMultivariateAnomaly"]


class _AnomalyBase(CognitiveServiceBase):
    granularity = ServiceParam("granularity", "series granularity "
                               "(yearly|monthly|weekly|daily|hourly|minutely)",
                               default="daily")
    max_anomaly_ratio = ServiceParam("max_anomaly_ratio", "expected anomaly "
                                     "fraction", default=None)
    sensitivity = ServiceParam("sensitivity", "detection sensitivity 0-99",
                               default=None)

    def _base(self) -> str:
        return f"{(self.get('url') or '').rstrip('/')}/anomalydetector/v1.0"

    def _series_body(self, rp: dict, series) -> dict:
        body = {"series": list(series), "granularity": rp.get("granularity") or "daily"}
        if rp.get("max_anomaly_ratio") is not None:
            body["maxAnomalyRatio"] = float(rp["max_anomaly_ratio"])
        if rp.get("sensitivity") is not None:
            body["sensitivity"] = int(rp["sensitivity"])
        return body


class DetectLastAnomaly(_AnomalyBase):
    """(ref ``DetectLastAnomaly``) — is the latest point of the series anomalous."""

    series_col = Param("series_col", "column of [{timestamp, value}] lists",
                       default="series")

    def input_bindings(self):
        return {"_series": "series_col"}

    def build_request(self, rp):
        if rp.get("_series") is None:
            return None
        return self.json_request(rp, f"{self._base()}/timeseries/last/detect",
                                 self._series_body(rp, rp["_series"]))


class DetectAnomalies(_AnomalyBase):
    """(ref ``DetectAnomalies``) — whole-series batch detection."""

    series_col = Param("series_col", "column of [{timestamp, value}] lists",
                       default="series")

    def input_bindings(self):
        return {"_series": "series_col"}

    def build_request(self, rp):
        if rp.get("_series") is None:
            return None
        return self.json_request(rp, f"{self._base()}/timeseries/entire/detect",
                                 self._series_body(rp, rp["_series"]))


class SimpleDetectAnomalies(_AnomalyBase):
    """(ref ``SimpleDetectAnomalies``) — long-format rows (group, timestamp,
    value): groups are assembled into series, detected in one call per group,
    and the per-point verdict is joined back onto the rows."""

    group_col = Param("group_col", "series grouping column", default="group")
    timestamp_col = Param("timestamp_col", "timestamp column", default="timestamp")
    value_col = Param("value_col", "value column", default="value")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("group_col"), self.get("timestamp_col"),
                             self.get("value_col"))
        gcol, tcol, vcol = (self.get("group_col"), self.get("timestamp_col"),
                            self.get("value_col"))
        # assemble one series per group (driver-side; series are small)
        groups: dict = {}
        for p in df.partitions:
            for g, t, v in zip(p[gcol], p[tcol], p[vcol]):
                groups.setdefault(g, []).append({"timestamp": str(t),
                                                 "value": float(v)})
        rp0 = {}
        for name in self.service_param_names():
            v = self.get(name)
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "lit":
                v = v[1]
            elif isinstance(v, tuple) and len(v) == 2 and v[0] == "col":
                raise ValueError(
                    f"SimpleDetectAnomalies resolves {name!r} once per group; "
                    f"column-bound values are not supported — pass a literal")
            rp0[name] = v
        results: dict = {}
        for g, series in groups.items():
            series = sorted(series, key=lambda d: d["timestamp"])
            req = self.json_request(rp0, f"{self._base()}/timeseries/entire/detect",
                                    self._series_body(rp0, series))
            resp = send_with_retries(req, timeout_s=self.get("timeout_s"))
            parsed, err = self.handle_response(resp)
            results[g] = ({d["timestamp"]: i for i, d in enumerate(series)},
                          parsed, err)

        def per_part(p):
            n = len(p[gcol])
            out_v = np.empty(n, dtype=object)
            out_e = np.empty(n, dtype=object)
            for i in range(n):
                index, parsed, err = results[p[gcol][i]]
                out_e[i] = err
                if err or not isinstance(parsed, dict):
                    out_v[i] = None
                    continue
                j = index.get(str(p[tcol][i]))
                flags = parsed.get("isAnomaly", [])
                out_v[i] = bool(flags[j]) if j is not None and j < len(flags) else None
            q = dict(p)
            q[self.get("output_col")] = out_v
            q[self.get("error_col")] = out_e
            return q

        return df.map_partitions(per_part)


class FitMultivariateAnomaly(Estimator):
    """(ref ``MultivariateAnomalyDetection.scala:184-269`` FitMultivariate-
    AnomalyDetector) — POSTs a training job over a blob of aligned series,
    polls the model until ready, and returns a DetectMultivariateAnomaly
    carrying the trained model id."""

    feature_name = "services"

    subscription_key = ServiceParam("subscription_key", "API key")
    url = Param("url", "service endpoint URL")
    source = Param("source", "SAS URL (or path) of the training data blob")
    start_time = Param("start_time", "training window start (ISO8601)")
    end_time = Param("end_time", "training window end (ISO8601)")
    sliding_window = Param("sliding_window", "model sliding window", default=300,
                           converter=TypeConverters.to_int)
    align_mode = Param("align_mode", "Inner | Outer", default="Outer")
    fill_na_method = Param("fill_na_method", "Previous | Linear | Fixed | Zero",
                           default="Linear")
    polling_interval_s = Param("polling_interval_s", "poll sleep", default=0.25,
                               converter=TypeConverters.to_float)
    max_poll_attempts = Param("max_poll_attempts", "max polls", default=100,
                              converter=TypeConverters.to_int)
    timeout_s = Param("timeout_s", "request timeout", default=60.0,
                      converter=TypeConverters.to_float)

    def _headers(self) -> dict:
        key = self.get("subscription_key")
        if isinstance(key, tuple):
            key = None
        h = {"Content-Type": "application/json"}
        if key:
            h["Ocp-Apim-Subscription-Key"] = key
        return h

    def _fit(self, df: DataFrame) -> "DetectMultivariateAnomaly":
        base = f"{(self.get('url') or '').rstrip('/')}/anomalydetector/v1.1-preview/multivariate"
        body = {"source": self.get("source"),
                "startTime": self.get("start_time"),
                "endTime": self.get("end_time"),
                "slidingWindow": self.get("sliding_window"),
                "alignPolicy": {"alignMode": self.get("align_mode"),
                                "fillNAMethod": self.get("fill_na_method")}}
        resp = send_with_retries(
            HTTPRequest(url=f"{base}/models", method="POST",
                        headers=self._headers(), entity=json.dumps(body)),
            timeout_s=self.get("timeout_s"))
        if resp is None or resp.status_code not in (200, 201, 202):
            raise RuntimeError(f"multivariate training submit failed: "
                               f"{getattr(resp, 'status_code', None)} "
                               f"{getattr(resp, 'error', '')}")
        loc = (resp.headers.get("Location") or resp.headers.get("location") or "")
        model_id = loc.rstrip("/").rsplit("/", 1)[-1] if loc else ""
        if not model_id:
            try:
                model_id = resp.json().get("modelId", "")
            except Exception:
                model_id = ""
        if not model_id:
            raise RuntimeError(
                f"training submit returned no model id (no Location header, "
                f"no modelId in body): HTTP {resp.status_code}")
        # poll model status until READY/FAILED
        for _ in range(self.get("max_poll_attempts")):
            time.sleep(self.get("polling_interval_s"))
            st = send_with_retries(HTTPRequest(url=f"{base}/models/{model_id}",
                                               headers=self._headers()),
                                   timeout_s=self.get("timeout_s"))
            if st is None:
                continue
            info = st.json()
            status = str(info.get("modelInfo", {}).get("status", "")).upper()
            if status == "READY":
                return DetectMultivariateAnomaly(
                    url=self.get("url"), subscription_key=self.get("subscription_key"),
                    model_id=model_id)
            if status == "FAILED":
                raise RuntimeError(f"multivariate training failed: "
                                   f"{info.get('modelInfo', {}).get('errors')}")
        raise TimeoutError(f"multivariate model {model_id} not ready after "
                           f"{self.get('max_poll_attempts')} polls")


class DetectMultivariateAnomaly(Model, HasAsyncReply):
    """Inference side: POST detect job for a window, poll the result."""

    feature_name = "services"

    model_id = Param("model_id", "trained model id")
    source_col = Param("source_col", "column of data SAS URLs", default="source")
    start_time_col = Param("start_time_col", "window start column", default="startTime")
    end_time_col = Param("end_time_col", "window end column", default="endTime")

    def input_bindings(self):
        return {"_source": "source_col", "_start": "start_time_col",
                "_end": "end_time_col"}

    def build_request(self, rp):
        if rp.get("_source") is None:
            return None
        base = (f"{(self.get('url') or '').rstrip('/')}/anomalydetector/"
                f"v1.1-preview/multivariate/models/{self.get('model_id')}/detect")
        body = {"source": str(rp["_source"]), "startTime": str(rp["_start"]),
                "endTime": str(rp["_end"])}
        return self.json_request(rp, base, body)

    def poll_location(self, resp):
        # this API family returns the result job URL in the plain Location
        # header (cf. FitMultivariateAnomaly), not Operation-Location
        return (super().poll_location(resp) or resp.headers.get("Location")
                or resp.headers.get("location"))

    def is_done(self, payload):
        status = str(payload.get("summary", {}).get("status", "")).upper() \
            if isinstance(payload, dict) else ""
        return status in ("READY", "FAILED")

    def parse_response(self, payload):
        if isinstance(payload, dict) and "results" in payload:
            return payload["results"]
        return payload
