"""Computer vision services.

Reference: ``cognitive/.../services/vision/ComputerVision.scala`` —
AnalyzeImage / DescribeImage / TagImage / OCR / ReadImage (LRO) /
GenerateThumbnails / RecognizeDomainSpecificContent, each posting an image URL
or raw bytes.
"""

from __future__ import annotations

import json

from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase, HasAsyncReply

__all__ = ["AnalyzeImage", "DescribeImage", "TagImage", "OCR", "ReadImage",
           "GenerateThumbnails", "RecognizeDomainSpecificContent"]


class _ImageInput(CognitiveServiceBase):
    """Shared image-url-or-bytes input handling (ref ``HasImageInput``)."""

    image_url_col = Param("image_url_col", "column of image URLs", default=None)
    image_bytes_col = Param("image_bytes_col", "column of raw image bytes",
                            default=None)

    def input_bindings(self):
        out = {}
        if self.get("image_url_col"):
            out["_url"] = "image_url_col"
        if self.get("image_bytes_col"):
            out["_bytes"] = "image_bytes_col"
        if not out:
            raise ValueError(f"{type(self).__name__} needs image_url_col or "
                             f"image_bytes_col")
        return out

    def _image_request(self, rp: dict, url: str) -> HTTPRequest | None:
        if rp.get("_url") is not None:
            return self.json_request(rp, url, {"url": str(rp["_url"])})
        if rp.get("_bytes") is not None:
            headers = {"Content-Type": "application/octet-stream",
                       **self.auth_headers(rp)}
            return HTTPRequest(url=url, method="POST", headers=headers,
                               entity=bytes(rp["_bytes"]))
        return None

    def _base(self) -> str:
        return f"{(self.get('url') or '').rstrip('/')}/vision/v3.2"


class AnalyzeImage(_ImageInput):
    """(ref ``AnalyzeImage``)"""

    visual_features = ServiceParam(
        "visual_features", "comma-joined features (Categories, Tags, "
        "Description, Faces, Objects, Color, Adult, Brands)", default="Tags")
    details = ServiceParam("details", "Celebrities and/or Landmarks", default=None)
    language = ServiceParam("language", "response language", default="en")

    def build_request(self, rp):
        q = [f"visualFeatures={rp.get('visual_features') or 'Tags'}",
             f"language={rp.get('language') or 'en'}"]
        if rp.get("details"):
            q.append(f"details={rp['details']}")
        return self._image_request(rp, f"{self._base()}/analyze?{'&'.join(q)}")


class DescribeImage(_ImageInput):
    max_candidates = ServiceParam("max_candidates", "caption candidates", default=1)

    def build_request(self, rp):
        return self._image_request(
            rp, f"{self._base()}/describe?maxCandidates={rp.get('max_candidates') or 1}")

    def parse_response(self, payload):
        return payload.get("description", payload) if isinstance(payload, dict) else payload


class TagImage(_ImageInput):
    def build_request(self, rp):
        return self._image_request(rp, f"{self._base()}/tag")

    def parse_response(self, payload):
        return payload.get("tags", payload) if isinstance(payload, dict) else payload


class OCR(_ImageInput):
    """(ref ``OCR``) — synchronous printed-text recognition."""

    detect_orientation = ServiceParam("detect_orientation", "detect rotation",
                                      default=True)

    def build_request(self, rp):
        return self._image_request(
            rp, f"{self._base()}/ocr?detectOrientation="
                f"{str(bool(rp.get('detect_orientation'))).lower()}")


class ReadImage(_ImageInput, HasAsyncReply):
    """(ref ``ReadImage``) — the async Read API: 202 + Operation-Location."""

    def build_request(self, rp):
        return self._image_request(rp, f"{self._base()}/read/analyze")

    def parse_response(self, payload):
        if isinstance(payload, dict) and "analyzeResult" in payload:
            return payload["analyzeResult"]
        return payload


class GenerateThumbnails(_ImageInput):
    width = ServiceParam("width", "thumbnail width", default=64)
    height = ServiceParam("height", "thumbnail height", default=64)
    smart_cropping = ServiceParam("smart_cropping", "smart crop", default=True)

    def build_request(self, rp):
        return self._image_request(
            rp, f"{self._base()}/generateThumbnail?width={rp.get('width') or 64}"
                f"&height={rp.get('height') or 64}"
                f"&smartCropping={str(bool(rp.get('smart_cropping'))).lower()}")

    def handle_response(self, resp):
        # binary thumbnail body, not JSON
        if resp is None:
            return None, None
        if resp.error or resp.status_code // 100 != 2:
            return None, resp.error or f"HTTP {resp.status_code}: {resp.reason}"
        return resp.entity, None


class RecognizeDomainSpecificContent(_ImageInput):
    model = Param("model", "domain model: celebrities | landmarks",
                  default="celebrities")

    def build_request(self, rp):
        return self._image_request(
            rp, f"{self._base()}/models/{self.get('model')}/analyze")

    def parse_response(self, payload):
        return payload.get("result", payload) if isinstance(payload, dict) else payload
