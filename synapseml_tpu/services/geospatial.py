"""Azure Maps geospatial services.

Reference: ``cognitive/.../services/geospatial/{AzureMapsGeocode,
CheckPointInPolygon}.scala`` — address geocoding, reverse geocoding, and
point-in-polygon checks (subscription key rides the query string for Maps).
"""

from __future__ import annotations

from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["AddressGeocoder", "ReverseAddressGeocoder", "CheckPointInPolygon"]


class _MapsBase(CognitiveServiceBase):
    api_version = Param("api_version", "maps API version", default="1.0")

    def _key(self, rp: dict) -> str:
        return rp.get("subscription_key") or ""

    def _base(self) -> str:
        return (self.get("url") or "https://atlas.microsoft.com").rstrip("/")


class AddressGeocoder(_MapsBase):
    """(ref ``AzureMapsGeocode``) — address string -> lat/lon candidates."""

    address_col = Param("address_col", "address column", default="address")
    limit = ServiceParam("limit", "max results", default=1)

    def input_bindings(self):
        return {"_address": "address_col"}

    def build_request(self, rp):
        if rp.get("_address") is None:
            return None
        from urllib.parse import quote

        url = (f"{self._base()}/search/address/json?api-version="
               f"{self.get('api_version')}&subscription-key={self._key(rp)}"
               f"&query={quote(str(rp['_address']))}&limit={rp.get('limit') or 1}")
        return HTTPRequest(url=url, method="GET")

    def parse_response(self, payload):
        return payload.get("results", payload) if isinstance(payload, dict) else payload


class ReverseAddressGeocoder(_MapsBase):
    """(ref reverse geocode) — (lat, lon) -> nearest address."""

    lat_col = Param("lat_col", "latitude column", default="lat")
    lon_col = Param("lon_col", "longitude column", default="lon")

    def input_bindings(self):
        return {"_lat": "lat_col", "_lon": "lon_col"}

    def build_request(self, rp):
        if rp.get("_lat") is None or rp.get("_lon") is None:
            return None
        url = (f"{self._base()}/search/address/reverse/json?api-version="
               f"{self.get('api_version')}&subscription-key={self._key(rp)}"
               f"&query={float(rp['_lat'])},{float(rp['_lon'])}")
        return HTTPRequest(url=url, method="GET")

    def parse_response(self, payload):
        return payload.get("addresses", payload) if isinstance(payload, dict) else payload


class CheckPointInPolygon(_MapsBase):
    """(ref ``CheckPointInPolygon``) — is (lat, lon) inside a stored geofence
    polygon (udid references uploaded geojson)."""

    lat_col = Param("lat_col", "latitude column", default="lat")
    lon_col = Param("lon_col", "longitude column", default="lon")
    user_data_id = ServiceParam("user_data_id", "uploaded polygon udid")

    def input_bindings(self):
        return {"_lat": "lat_col", "_lon": "lon_col"}

    def build_request(self, rp):
        if rp.get("_lat") is None or rp.get("_lon") is None:
            return None
        url = (f"{self._base()}/spatial/pointInPolygon/json?api-version="
               f"{self.get('api_version')}&subscription-key={self._key(rp)}"
               f"&udid={rp.get('user_data_id') or ''}"
               f"&lat={float(rp['_lat'])}&lon={float(rp['_lon'])}")
        return HTTPRequest(url=url, method="GET")

    def parse_response(self, payload):
        if isinstance(payload, dict) and "result" in payload:
            return payload["result"]
        return payload
