"""Translate (reference ``services/translate/Translate.scala``)."""

from __future__ import annotations

import json

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["Translate", "Transliterate", "BreakSentence", "DictionaryLookup",
           "DictionaryExamples"]


class Translate(CognitiveServiceBase):
    text_col = Param("text_col", "text column", default="text")
    to_language = ServiceParam("to_language", "target language(s), str or list")
    from_language = ServiceParam("from_language", "source language", default=None)
    output_col = Param("output_col", "translations column", default="translation")
    api_version = Param("api_version", "API version", default="3.0")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None:
            return None
        to = rp.get("to_language")
        to = [to] if isinstance(to, str) else list(to or [])
        qs = f"api-version={self.get('api_version')}" + "".join(f"&to={t}" for t in to)
        if rp.get("from_language"):
            qs += f"&from={rp['from_language']}"
        url = f"{(self.get('url') or '').rstrip('/')}/translate?{qs}"
        headers = {"Content-Type": "application/json", **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers,
                           entity=json.dumps([{"Text": str(rp["_text"])}]))

    def parse_response(self, payload):
        try:
            return [t["text"] for t in payload[0]["translations"]]
        except (KeyError, IndexError, TypeError):
            return payload


class _TranslatorOp(CognitiveServiceBase):
    """Shared plumbing for the single-text translator operations (reference
    ``services/translate/Translate.scala`` sibling transformers)."""

    text_col = Param("text_col", "text column", default="text")
    api_version = Param("api_version", "API version", default="3.0")

    def input_bindings(self):
        return {"_text": "text_col"}

    def _query(self, rp: dict) -> str:
        raise NotImplementedError

    def _path(self) -> str:
        raise NotImplementedError

    def _require(self, rp: dict, *names: str) -> None:
        missing = [n for n in names if rp.get(n) in (None, "")]
        if missing:
            raise ValueError(f"{type(self).__name__} requires "
                             f"{', '.join(missing)} to be set")

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None:
            return None
        url = (f"{(self.get('url') or '').rstrip('/')}/{self._path()}"
               f"?api-version={self.get('api_version')}{self._query(rp)}")
        return self.json_request(rp, url, [{"Text": str(rp["_text"])}])


class Transliterate(_TranslatorOp):
    """Convert text between scripts (reference ``Transliterate``):
    POST /transliterate with language + fromScript + toScript."""

    language = ServiceParam("language", "language of the input text")
    from_script = ServiceParam("from_script", "script of the input text")
    to_script = ServiceParam("to_script", "target script")
    output_col = Param("output_col", "transliteration column",
                       default="transliteration")

    def _path(self) -> str:
        return "transliterate"

    def _query(self, rp: dict) -> str:
        self._require(rp, "language", "from_script", "to_script")
        return (f"&language={rp['language']}"
                f"&fromScript={rp['from_script']}"
                f"&toScript={rp['to_script']}")

    def parse_response(self, payload):
        try:
            return payload[0]["text"]
        except (KeyError, IndexError, TypeError):
            return payload


class BreakSentence(_TranslatorOp):
    """Sentence boundary lengths (reference ``BreakSentence``):
    POST /breaksentence -> sentLen list."""

    language = ServiceParam("language", "language hint", default=None)
    output_col = Param("output_col", "sentence-length column",
                       default="sent_len")

    def _path(self) -> str:
        return "breaksentence"

    def _query(self, rp: dict) -> str:
        return f"&language={rp['language']}" if rp.get("language") else ""

    def parse_response(self, payload):
        try:
            return payload[0]["sentLen"]
        except (KeyError, IndexError, TypeError):
            return payload


class DictionaryLookup(_TranslatorOp):
    """Alternative translations for a word/phrase (reference
    ``DictionaryLookup``): POST /dictionary/lookup with from + to."""

    from_language = ServiceParam("from_language", "source language")
    to_language = ServiceParam("to_language", "target language")
    output_col = Param("output_col", "translations column",
                       default="translations")

    def _path(self) -> str:
        return "dictionary/lookup"

    def _query(self, rp: dict) -> str:
        self._require(rp, "from_language", "to_language")
        return (f"&from={rp['from_language']}"
                f"&to={rp['to_language']}")

    def parse_response(self, payload):
        try:
            return [t["normalizedTarget"] for t in payload[0]["translations"]]
        except (KeyError, IndexError, TypeError):
            return payload


class DictionaryExamples(_TranslatorOp):
    """Usage examples for a (text, translation) pair (reference
    ``DictionaryExamples``): POST /dictionary/examples."""

    translation_col = Param("translation_col", "chosen translation column",
                            default="translation")
    from_language = ServiceParam("from_language", "source language")
    to_language = ServiceParam("to_language", "target language")
    output_col = Param("output_col", "examples column", default="examples")

    def input_bindings(self):
        return {"_text": "text_col", "_translation": "translation_col"}

    def _path(self) -> str:
        return "dictionary/examples"

    def _query(self, rp: dict) -> str:
        self._require(rp, "from_language", "to_language")
        return (f"&from={rp['from_language']}"
                f"&to={rp['to_language']}")

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None or rp.get("_translation") is None:
            return None
        url = (f"{(self.get('url') or '').rstrip('/')}/{self._path()}"
               f"?api-version={self.get('api_version')}{self._query(rp)}")
        return self.json_request(rp, url, [{"Text": str(rp["_text"]),
                                            "Translation": str(rp["_translation"])}])

    def parse_response(self, payload):
        try:
            return [e["targetPrefix"] + e["targetTerm"] + e["targetSuffix"]
                    for e in payload[0]["examples"]]
        except (KeyError, IndexError, TypeError):
            return payload
