"""Translate (reference ``services/translate/Translate.scala``)."""

from __future__ import annotations

import json

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["Translate"]


class Translate(CognitiveServiceBase):
    text_col = Param("text_col", "text column", default="text")
    to_language = ServiceParam("to_language", "target language(s), str or list")
    from_language = ServiceParam("from_language", "source language", default=None)
    output_col = Param("output_col", "translations column", default="translation")
    api_version = Param("api_version", "API version", default="3.0")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None:
            return None
        to = rp.get("to_language")
        to = [to] if isinstance(to, str) else list(to or [])
        qs = f"api-version={self.get('api_version')}" + "".join(f"&to={t}" for t in to)
        if rp.get("from_language"):
            qs += f"&from={rp['from_language']}"
        url = f"{(self.get('url') or '').rstrip('/')}/translate?{qs}"
        headers = {"Content-Type": "application/json", **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers,
                           entity=json.dumps([{"Text": str(rp["_text"])}]))

    def parse_response(self, payload):
        try:
            return [t["text"] for t in payload[0]["translations"]]
        except (KeyError, IndexError, TypeError):
            return payload
