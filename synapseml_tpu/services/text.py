"""Text analytics services (reference ``services/text/TextAnalytics.scala`` /
``language/AnalyzeText.scala``): the analyze-text task surface — sentiment,
key phrases, language detection, entity recognition."""

from __future__ import annotations

import json

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["AnalyzeText", "TextSentiment", "KeyPhraseExtractor",
           "LanguageDetector", "EntityRecognizer"]


class AnalyzeText(CognitiveServiceBase):
    """(ref ``AnalyzeText.scala``) generic analyze-text task."""

    kind = Param("kind", "SentimentAnalysis | KeyPhraseExtraction | "
                 "LanguageDetection | EntityRecognition",
                 default="SentimentAnalysis")
    text_col = Param("text_col", "document text column", default="text")
    language = ServiceParam("language", "document language", default="en")
    api_version = Param("api_version", "API version", default="2023-04-01")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None:
            return None
        kind = self.get("kind")
        doc = {"id": "0", "text": str(rp["_text"])}
        if kind != "LanguageDetection":
            doc["language"] = rp.get("language") or "en"
        body = {"kind": kind,
                "analysisInput": {"documents": [doc]},
                "parameters": {}}
        url = (f"{(self.get('url') or '').rstrip('/')}"
               f"/language/:analyze-text?api-version={self.get('api_version')}")
        headers = {"Content-Type": "application/json", **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers,
                           entity=json.dumps(body))

    def parse_response(self, payload):
        try:
            return payload["results"]["documents"][0]
        except (KeyError, IndexError, TypeError):
            return payload


class TextSentiment(AnalyzeText):
    """(ref ``TextSentiment``)"""

    kind = Param("kind", "fixed task", default="SentimentAnalysis")
    output_col = Param("output_col", "sentiment column", default="sentiment")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        return doc.get("sentiment", doc) if isinstance(doc, dict) else doc


class KeyPhraseExtractor(AnalyzeText):
    kind = Param("kind", "fixed task", default="KeyPhraseExtraction")
    output_col = Param("output_col", "key phrase column", default="keyPhrases")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        return doc.get("keyPhrases", doc) if isinstance(doc, dict) else doc


class LanguageDetector(AnalyzeText):
    kind = Param("kind", "fixed task", default="LanguageDetection")
    output_col = Param("output_col", "language column", default="language")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        if isinstance(doc, dict) and "detectedLanguage" in doc:
            return doc["detectedLanguage"]
        return doc


class EntityRecognizer(AnalyzeText):
    kind = Param("kind", "fixed task", default="EntityRecognition")
    output_col = Param("output_col", "entities column", default="entities")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        return doc.get("entities", doc) if isinstance(doc, dict) else doc
