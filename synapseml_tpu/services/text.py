"""Text analytics services (reference ``services/text/TextAnalytics.scala`` /
``language/AnalyzeText.scala``): the analyze-text task surface — sentiment,
key phrases, language detection, entity recognition."""

from __future__ import annotations

import json

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase, HasAsyncReply

__all__ = ["AnalyzeText", "AnalyzeTextLRO", "TextSentiment",
           "KeyPhraseExtractor", "LanguageDetector", "EntityRecognizer"]


class AnalyzeText(CognitiveServiceBase):
    """(ref ``AnalyzeText.scala``) generic analyze-text task."""

    kind = Param("kind", "SentimentAnalysis | KeyPhraseExtraction | "
                 "LanguageDetection | EntityRecognition",
                 default="SentimentAnalysis")
    text_col = Param("text_col", "document text column", default="text")
    language = ServiceParam("language", "document language", default="en")
    api_version = Param("api_version", "API version", default="2023-04-01")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None:
            return None
        kind = self.get("kind")
        doc = {"id": "0", "text": str(rp["_text"])}
        if kind != "LanguageDetection":
            doc["language"] = rp.get("language") or "en"
        body = {"kind": kind,
                "analysisInput": {"documents": [doc]},
                "parameters": {}}
        url = (f"{(self.get('url') or '').rstrip('/')}"
               f"/language/:analyze-text?api-version={self.get('api_version')}")
        headers = {"Content-Type": "application/json", **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers,
                           entity=json.dumps(body))

    def parse_response(self, payload):
        try:
            return payload["results"]["documents"][0]
        except (KeyError, IndexError, TypeError):
            return payload


class TextSentiment(AnalyzeText):
    """(ref ``TextSentiment``)"""

    kind = Param("kind", "fixed task", default="SentimentAnalysis")
    output_col = Param("output_col", "sentiment column", default="sentiment")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        return doc.get("sentiment", doc) if isinstance(doc, dict) else doc


class KeyPhraseExtractor(AnalyzeText):
    kind = Param("kind", "fixed task", default="KeyPhraseExtraction")
    output_col = Param("output_col", "key phrase column", default="keyPhrases")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        return doc.get("keyPhrases", doc) if isinstance(doc, dict) else doc


class LanguageDetector(AnalyzeText):
    kind = Param("kind", "fixed task", default="LanguageDetection")
    output_col = Param("output_col", "language column", default="language")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        if isinstance(doc, dict) and "detectedLanguage" in doc:
            return doc["detectedLanguage"]
        return doc


class EntityRecognizer(AnalyzeText):
    kind = Param("kind", "fixed task", default="EntityRecognition")
    output_col = Param("output_col", "entities column", default="entities")

    def parse_response(self, payload):
        doc = super().parse_response(payload)
        return doc.get("entities", doc) if isinstance(doc, dict) else doc


class AnalyzeTextLRO(HasAsyncReply):
    """Long-running analyze-text jobs (reference
    ``language/AnalyzeTextLongRunningOperations.scala:65-145``): PII
    redaction, healthcare entity extraction, extractive/abstractive
    summarization. POSTs ``/language/analyze-text/jobs``, polls the
    operation-location until the job completes, and returns the first task's
    documents."""

    kind = Param("kind", "PiiEntityRecognition | Healthcare | "
                 "ExtractiveSummarization | AbstractiveSummarization "
                 "| EntityRecognition | KeyPhraseExtraction",
                 default="PiiEntityRecognition")
    text_col = Param("text_col", "document text column", default="text")
    language = ServiceParam("language", "document language", default="en")
    task_parameters = Param("task_parameters", "per-kind task parameters, e.g. "
                            "{'sentenceCount': 2} for summarization or "
                            "{'domain': 'phi'} for PII", default=None)
    api_version = Param("api_version", "API version", default="2023-04-01")
    output_col = Param("output_col", "result column", default="analysis")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None:
            return None
        doc = {"id": "0", "language": rp.get("language") or "en",
               "text": str(rp["_text"])}
        body = {"analysisInput": {"documents": [doc]},
                "tasks": [{"kind": self.get("kind"),
                           "parameters": self.get("task_parameters") or {}}]}
        url = (f"{(self.get('url') or '').rstrip('/')}"
               f"/language/analyze-text/jobs?api-version={self.get('api_version')}")
        return self.json_request(rp, url, body)

    def handle_response(self, resp):
        parsed, err = super().handle_response(resp)
        if err is None and parsed is not None:
            # a completed-but-failed job is still HTTP 200; surface it as an
            # error, not a result (the raw job state has no task documents, so
            # parse_response passed it through unchanged)
            payload = resp.json()
            if (isinstance(payload, dict)
                    and str(payload.get("status", "")).lower() == "failed"):
                return None, (f"analyze-text job failed: "
                              f"{json.dumps(payload.get('errors', []))[:500]}")
        return parsed, err

    def parse_response(self, payload):
        try:
            return payload["tasks"]["items"][0]["results"]["documents"][0]
        except (KeyError, IndexError, TypeError):
            return payload
