"""AI services as transformers (reference ``cognitive/`` module, SURVEY.md
§2.6): CognitiveServicesBase composition over the HTTP fabric, the OpenAI
family (chat/completion/embedding/prompt), text analytics, translation,
form recognizer (+ ontology learner), computer vision, face, anomaly
detection (simple + multivariate LRO), geospatial, speech, AI Foundry,
LangChain, and the Azure Search writer.

All engine-independent: each service builds authenticated per-row requests
from ServiceParams (value-or-column) and parses JSON replies; transport is
:mod:`synapseml_tpu.io.http` (retry/backoff/429 built in).
"""

from .base import CognitiveServiceBase, HasAsyncReply
from .openai import (
    OpenAIChatCompletion,
    OpenAICompletion,
    OpenAIDefaults,
    OpenAIEmbedding,
    OpenAIPrompt,
    OpenAIResponses,
)
from .text import (AnalyzeText, AnalyzeTextLRO, EntityRecognizer,
                   KeyPhraseExtractor, LanguageDetector, TextSentiment)
from .translate import (BreakSentence, DictionaryExamples,
                        DictionaryLookup, Translate, Transliterate)
from .search import AzureSearchWriter, infer_index_schema
from .form import (
    AnalyzeBusinessCards,
    AnalyzeDocument,
    AnalyzeIDDocuments,
    AnalyzeInvoices,
    AnalyzeLayout,
    AnalyzeReceipts,
    FormOntologyLearner,
    FormOntologyTransformer,
)
from .vision import (
    OCR,
    AnalyzeImage,
    DescribeImage,
    GenerateThumbnails,
    ReadImage,
    RecognizeDomainSpecificContent,
    TagImage,
)
from .face import DetectFace, FindSimilarFace, GroupFaces, IdentifyFaces, VerifyFaces
from .anomaly import (
    DetectAnomalies,
    DetectLastAnomaly,
    DetectMultivariateAnomaly,
    FitMultivariateAnomaly,
    SimpleDetectAnomalies,
)
from .geospatial import AddressGeocoder, CheckPointInPolygon, ReverseAddressGeocoder
from .speech import ConversationTranscriber, SpeechToText, TextToSpeech
from .aifoundry import AIFoundryChatCompletion
from .langchain import LangChainTransformer
from .fabric import (
    FabricClient,
    install_certified_events,
    log_to_certified_events,
    parse_jwt_expiry,
)

__all__ = [
    "CognitiveServiceBase", "HasAsyncReply",
    "OpenAIChatCompletion", "OpenAICompletion", "OpenAIEmbedding",
    "OpenAIPrompt", "OpenAIResponses", "OpenAIDefaults",
    "AnalyzeText", "AnalyzeTextLRO", "TextSentiment", "KeyPhraseExtractor",
    "LanguageDetector", "EntityRecognizer", "Translate", "Transliterate",
    "BreakSentence", "DictionaryLookup", "DictionaryExamples",
    "AzureSearchWriter", "infer_index_schema",
    "AnalyzeDocument", "AnalyzeLayout", "AnalyzeReceipts", "AnalyzeInvoices",
    "AnalyzeBusinessCards", "AnalyzeIDDocuments", "FormOntologyLearner",
    "FormOntologyTransformer",
    "AnalyzeImage", "DescribeImage", "TagImage", "OCR", "ReadImage",
    "GenerateThumbnails", "RecognizeDomainSpecificContent",
    "DetectFace", "FindSimilarFace", "GroupFaces", "IdentifyFaces", "VerifyFaces",
    "DetectLastAnomaly", "DetectAnomalies", "SimpleDetectAnomalies",
    "FitMultivariateAnomaly", "DetectMultivariateAnomaly",
    "AddressGeocoder", "ReverseAddressGeocoder", "CheckPointInPolygon",
    "SpeechToText", "TextToSpeech", "ConversationTranscriber", "AIFoundryChatCompletion",
    "LangChainTransformer",
    "FabricClient", "parse_jwt_expiry", "log_to_certified_events",
    "install_certified_events",
]
