"""AI services as transformers (reference ``cognitive/`` module, SURVEY.md
§2.6): CognitiveServicesBase composition over the HTTP fabric, the OpenAI
family (chat/completion/embedding/prompt), text analytics, translation, and
the Azure Search writer.

All engine-independent: each service builds authenticated per-row requests
from ServiceParams (value-or-column) and parses JSON replies; transport is
:mod:`synapseml_tpu.io.http` (retry/backoff/429 built in).
"""

from .base import CognitiveServiceBase, HasAsyncReply
from .openai import (
    OpenAIChatCompletion,
    OpenAICompletion,
    OpenAIDefaults,
    OpenAIEmbedding,
    OpenAIPrompt,
)
from .text import AnalyzeText, EntityRecognizer, KeyPhraseExtractor, LanguageDetector, TextSentiment
from .translate import Translate
from .search import AzureSearchWriter

__all__ = [
    "CognitiveServiceBase", "HasAsyncReply",
    "OpenAIChatCompletion", "OpenAICompletion", "OpenAIEmbedding",
    "OpenAIPrompt", "OpenAIDefaults",
    "AnalyzeText", "TextSentiment", "KeyPhraseExtractor", "LanguageDetector",
    "EntityRecognizer", "Translate", "AzureSearchWriter",
]
