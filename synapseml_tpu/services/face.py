"""Face services.

Reference: ``cognitive/.../services/face/Face.scala`` — DetectFace,
FindSimilarFace, GroupFaces, IdentifyFaces, VerifyFaces over the v1.0 face API.
"""

from __future__ import annotations

import json

from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["DetectFace", "FindSimilarFace", "GroupFaces", "IdentifyFaces",
           "VerifyFaces"]


class _FaceBase(CognitiveServiceBase):
    def _base(self) -> str:
        return f"{(self.get('url') or '').rstrip('/')}/face/v1.0"


class DetectFace(_FaceBase):
    """(ref ``DetectFace``)"""

    image_url_col = Param("image_url_col", "column of image URLs", default="url")
    return_face_id = ServiceParam("return_face_id", "include faceId", default=True)
    return_face_landmarks = ServiceParam("return_face_landmarks",
                                         "include landmarks", default=False)
    return_face_attributes = ServiceParam(
        "return_face_attributes", "comma-joined attributes (age, gender, "
        "headPose, smile, glasses, emotion, ...)", default=None)

    def input_bindings(self):
        return {"_url": "image_url_col"}

    def build_request(self, rp):
        if rp.get("_url") is None:
            return None
        q = [f"returnFaceId={str(bool(rp.get('return_face_id'))).lower()}",
             f"returnFaceLandmarks={str(bool(rp.get('return_face_landmarks'))).lower()}"]
        if rp.get("return_face_attributes"):
            q.append(f"returnFaceAttributes={rp['return_face_attributes']}")
        return self.json_request(rp, f"{self._base()}/detect?{'&'.join(q)}",
                                  {"url": str(rp["_url"])})


class FindSimilarFace(_FaceBase):
    """(ref ``FindSimilar``)"""

    face_id_col = Param("face_id_col", "query faceId column", default="faceId")
    face_ids = ServiceParam("face_ids", "candidate faceId list (or column)")
    max_candidates = ServiceParam("max_candidates", "max results", default=20)

    def input_bindings(self):
        return {"_face_id": "face_id_col"}

    def build_request(self, rp):
        if rp.get("_face_id") is None:
            return None
        body = {"faceId": str(rp["_face_id"]),
                "faceIds": list(rp.get("face_ids") or []),
                "maxNumOfCandidatesReturned": rp.get("max_candidates") or 20}
        return self.json_request(rp, f"{self._base()}/findsimilars", body)


class GroupFaces(_FaceBase):
    """(ref ``GroupFaces``)"""

    face_ids_col = Param("face_ids_col", "column of faceId lists", default="faceIds")

    def input_bindings(self):
        return {"_face_ids": "face_ids_col"}

    def build_request(self, rp):
        if rp.get("_face_ids") is None:
            return None
        return self.json_request(rp, f"{self._base()}/group",
                                  {"faceIds": list(rp["_face_ids"])})


class IdentifyFaces(_FaceBase):
    """(ref ``IdentifyFaces``)"""

    face_ids_col = Param("face_ids_col", "column of faceId lists", default="faceIds")
    person_group_id = ServiceParam("person_group_id", "person group to search")
    max_candidates = ServiceParam("max_candidates", "candidates per face", default=1)
    confidence_threshold = ServiceParam("confidence_threshold",
                                        "identification threshold", default=None)

    def input_bindings(self):
        return {"_face_ids": "face_ids_col"}

    def build_request(self, rp):
        if rp.get("_face_ids") is None:
            return None
        body = {"faceIds": list(rp["_face_ids"]),
                "personGroupId": rp.get("person_group_id"),
                "maxNumOfCandidatesReturned": rp.get("max_candidates") or 1}
        if rp.get("confidence_threshold") is not None:
            body["confidenceThreshold"] = float(rp["confidence_threshold"])
        return self.json_request(rp, f"{self._base()}/identify", body)


class VerifyFaces(_FaceBase):
    """(ref ``VerifyFaces``) — same-person check for two face ids."""

    face_id1_col = Param("face_id1_col", "first faceId column", default="faceId1")
    face_id2_col = Param("face_id2_col", "second faceId column", default="faceId2")

    def input_bindings(self):
        return {"_id1": "face_id1_col", "_id2": "face_id2_col"}

    def build_request(self, rp):
        if rp.get("_id1") is None or rp.get("_id2") is None:
            return None
        return self.json_request(rp, f"{self._base()}/verify",
                                  {"faceId1": str(rp["_id1"]),
                                   "faceId2": str(rp["_id2"])})
