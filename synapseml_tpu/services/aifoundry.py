"""AI Foundry chat service.

Reference: ``cognitive/.../services/aifoundry/AIFoundryChatCompletion.scala`` —
chat completions against an AI Foundry (serverless / models-as-a-service)
endpoint: flat ``/chat/completions`` route with a ``model`` body field and
bearer auth, vs the Azure OpenAI deployment-path route.
"""

from __future__ import annotations

import json

from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["AIFoundryChatCompletion"]


class AIFoundryChatCompletion(CognitiveServiceBase):
    messages_col = Param("messages_col", "chat messages column", default="messages")
    output_col = Param("output_col", "reply column", default="chat_completions")
    model = ServiceParam("model", "model name routed by the endpoint", default=None)
    temperature = ServiceParam("temperature", "sampling temperature", default=None)
    max_tokens = ServiceParam("max_tokens", "max generated tokens", default=None)
    api_version = Param("api_version", "API version query param", default=None)

    def input_bindings(self):
        return {"_messages": "messages_col"}

    def auth_headers(self, rp):
        key = rp.get("subscription_key")
        return {"Authorization": f"Bearer {key}"} if key else {}

    def build_request(self, rp):
        if rp.get("_messages") is None:
            return None
        body = {"messages": [dict(m) for m in rp["_messages"]]}
        for field in ("model", "temperature", "max_tokens"):
            if rp.get(field) is not None:
                body[field] = rp[field]
        url = f"{(self.get('url') or '').rstrip('/')}/chat/completions"
        if self.get("api_version"):
            url += f"?api-version={self.get('api_version')}"
        return self.json_request(rp, url, body)

    def parse_response(self, payload):
        try:
            return payload["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError):
            return payload
