"""Speech services (REST).

Reference: ``cognitive/.../services/speech/SpeechToTextSDK.scala:125-650``
wraps the native Speech client SDK over streamed audio; here the REST
short-audio endpoint covers the same transform surface (audio bytes column ->
transcription column) without a native dependency, plus TextToSpeech
(``TextToSpeech.scala``).
"""

from __future__ import annotations

import json

from ..core.params import Param, ServiceParam, TypeConverters
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase, HasAsyncReply

__all__ = ["SpeechToText", "TextToSpeech", "ConversationTranscriber"]


class SpeechToText(CognitiveServiceBase):
    """Audio bytes -> recognition JSON (DisplayText, offsets).

    ``url`` is the region endpoint, e.g.
    ``https://<region>.stt.speech.microsoft.com``."""

    audio_col = Param("audio_col", "column of audio bytes (WAV/OGG)",
                      default="audio")
    language = ServiceParam("language", "recognition language", default="en-US")
    format = ServiceParam("format", "simple | detailed", default="simple")
    profanity = ServiceParam("profanity", "masked | removed | raw", default=None)
    audio_format = Param("audio_format", "content type of the audio bytes",
                         default="audio/wav; codecs=audio/pcm; samplerate=16000")

    def input_bindings(self):
        return {"_audio": "audio_col"}

    def build_request(self, rp):
        if rp.get("_audio") is None:
            return None
        q = [f"language={rp.get('language') or 'en-US'}",
             f"format={rp.get('format') or 'simple'}"]
        if rp.get("profanity"):
            q.append(f"profanity={rp['profanity']}")
        url = (f"{(self.get('url') or '').rstrip('/')}/speech/recognition/"
               f"conversation/cognitiveservices/v1?{'&'.join(q)}")
        headers = {"Content-Type": self.get("audio_format"),
                   "Accept": "application/json", **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers,
                           entity=bytes(rp["_audio"]))


class TextToSpeech(CognitiveServiceBase):
    """Text -> synthesized audio bytes (SSML POST).

    ``url`` is the region TTS endpoint, e.g.
    ``https://<region>.tts.speech.microsoft.com``."""

    text_col = Param("text_col", "text column", default="text")
    voice = ServiceParam("voice", "voice name", default="en-US-JennyNeural")
    language = ServiceParam("language", "language", default="en-US")
    output_format = Param("output_format", "audio output format",
                          default="riff-16khz-16bit-mono-pcm")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp):
        if rp.get("_text") is None:
            return None
        def esc(s, attr=False):
            s = (str(s).replace("&", "&amp;").replace("<", "&lt;")
                 .replace(">", "&gt;"))
            return s.replace('"', "&quot;").replace("'", "&apos;") if attr else s

        lang = esc(rp.get("language") or "en-US", attr=True)
        voice = esc(rp.get("voice") or "en-US-JennyNeural", attr=True)
        text = esc(rp["_text"])
        ssml = (f"<speak version='1.0' xml:lang='{lang}'>"
                f"<voice xml:lang='{lang}' name='{voice}'>{text}</voice></speak>")
        url = f"{(self.get('url') or '').rstrip('/')}/cognitiveservices/v1"
        headers = {"Content-Type": "application/ssml+xml",
                   "X-Microsoft-OutputFormat": self.get("output_format"),
                   **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers, entity=ssml)

    def handle_response(self, resp):
        # binary audio body, not JSON
        if resp is None:
            return None, None
        if resp.error or resp.status_code // 100 != 2:
            return None, resp.error or f"HTTP {resp.status_code}: {resp.reason}"
        return resp.entity, None


class ConversationTranscriber(HasAsyncReply):
    """Long-audio transcription with per-utterance speaker diarization.

    Reference ``SpeechToTextSDK.scala:564`` ``ConversationTranscription`` —
    the native SDK's in-room/online transcriber; rebuilt on the
    batch-transcription REST flow, the service's supported non-SDK path for
    diarized long audio.

    Per row: create a transcription job for the row's audio URL (the batch
    API takes content URLs, not inline bytes), poll until it completes, fetch
    the result file, and land the diarized phrase list — one entry per
    utterance with ``speaker``, ``offset``, and text — in ``output_col``.

    ``url`` is the region management endpoint, e.g.
    ``https://<region>.api.cognitive.microsoft.com``.
    """

    audio_url_col = Param("audio_url_col", "column of audio content URLs",
                          default="audio_url")
    language = ServiceParam("language", "transcription locale", default="en-US")
    max_speakers = Param("max_speakers", "diarization: maximum speaker count",
                         default=2, converter=TypeConverters.to_int)
    display_name = Param("display_name", "job display name",
                         default="synapseml_tpu transcription")
    api_version = Param("api_version", "API version", default="v3.2")
    output_col = Param("output_col", "diarized phrases column",
                       default="transcription")

    def input_bindings(self):
        return {"_audio_url": "audio_url_col"}

    def build_request(self, rp):
        if rp.get("_audio_url") is None:
            return None
        body = {
            "displayName": self.get("display_name"),
            "locale": rp.get("language") or "en-US",
            "contentUrls": [str(rp["_audio_url"])],
            "properties": {
                "diarizationEnabled": True,
                "diarization": {"speakers": {"minCount": 1,
                                             "maxCount": self.get("max_speakers")}},
                "punctuationMode": "DictatedAndAutomatic",
                "profanityFilterMode": "Masked",
            },
        }
        url = (f"{(self.get('url') or '').rstrip('/')}/speechtotext/"
               f"{self.get('api_version')}/transcriptions")
        return self.json_request(rp, url, body)

    def is_done(self, payload) -> bool:
        status = str(payload.get("status", "")).lower() \
            if isinstance(payload, dict) else ""
        return status in ("succeeded", "failed")

    def poll_location(self, resp):
        # the create reply carries its own URL in "self"; poll that
        loc = super().poll_location(resp)
        if loc:
            return loc
        try:
            return resp.json().get("self")
        except Exception:
            return None

    def post_process_responses(self, requests, responses, client):
        """LRO poll (base), then fetch each finished job's result file."""
        polled = super().post_process_responses(requests, responses, client)
        out = list(polled)
        fetchable = {}
        for i, resp in enumerate(out):
            if resp is None or resp.status_code // 100 != 2:
                continue
            try:
                payload = resp.json()
            except Exception:
                continue
            if str(payload.get("status", "")).lower() != "succeeded":
                continue
            files_url = (payload.get("links") or {}).get("files")
            if files_url:
                fetchable[i] = files_url
        if not fetchable:
            return out
        idxs = list(fetchable)
        files_lists = client.send_all(
            [HTTPRequest(url=fetchable[i], method="GET",
                         headers=self.poll_headers(requests[i]))
             for i in idxs])
        content = {}
        for i, resp in zip(idxs, files_lists):
            try:
                values = resp.json().get("values", [])
            except Exception:
                continue
            urls = [v["links"]["contentUrl"] for v in values
                    if v.get("kind") == "Transcription"]
            if urls:
                content[i] = urls[0]
        if content:
            idxs = list(content)
            results = client.send_all(
                [HTTPRequest(url=content[i], method="GET",
                             headers=self.poll_headers(requests[i]))
                 for i in idxs])
            for i, resp in zip(idxs, results):
                out[i] = resp
        return out

    def handle_response(self, resp):
        parsed, err = super().handle_response(resp)
        if err is None and parsed is not None:
            payload = resp.json()
            if isinstance(payload, dict):
                status = str(payload.get("status", "")).lower()
                if status == "failed":
                    props = payload.get("properties") or {}
                    return None, ("transcription job failed: "
                                  f"{json.dumps(props.get('error', props))[:500]}")
                if status == "succeeded":
                    # job state never replaced by a result file: the files
                    # listing had no Transcription entry (or the fetch failed)
                    return None, "transcription succeeded but no result file"
        return parsed, err

    def parse_response(self, payload):
        try:
            phrases = payload["recognizedPhrases"]
        except (KeyError, TypeError):
            return payload
        out = []
        for p in phrases:
            best = p.get("nBest") or []  # silence segments can have no nBest
            out.append({"speaker": p.get("speaker"),
                        "offset": p.get("offset"),
                        "text": best[0].get("display", "") if best else ""})
        return out
