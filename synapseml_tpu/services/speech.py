"""Speech services (REST).

Reference: ``cognitive/.../services/speech/SpeechToTextSDK.scala:125-650``
wraps the native Speech client SDK over streamed audio; here the REST
short-audio endpoint covers the same transform surface (audio bytes column ->
transcription column) without a native dependency, plus TextToSpeech
(``TextToSpeech.scala``).
"""

from __future__ import annotations

from ..core.params import Param, ServiceParam
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["SpeechToText", "TextToSpeech"]


class SpeechToText(CognitiveServiceBase):
    """Audio bytes -> recognition JSON (DisplayText, offsets).

    ``url`` is the region endpoint, e.g.
    ``https://<region>.stt.speech.microsoft.com``."""

    audio_col = Param("audio_col", "column of audio bytes (WAV/OGG)",
                      default="audio")
    language = ServiceParam("language", "recognition language", default="en-US")
    format = ServiceParam("format", "simple | detailed", default="simple")
    profanity = ServiceParam("profanity", "masked | removed | raw", default=None)
    audio_format = Param("audio_format", "content type of the audio bytes",
                         default="audio/wav; codecs=audio/pcm; samplerate=16000")

    def input_bindings(self):
        return {"_audio": "audio_col"}

    def build_request(self, rp):
        if rp.get("_audio") is None:
            return None
        q = [f"language={rp.get('language') or 'en-US'}",
             f"format={rp.get('format') or 'simple'}"]
        if rp.get("profanity"):
            q.append(f"profanity={rp['profanity']}")
        url = (f"{(self.get('url') or '').rstrip('/')}/speech/recognition/"
               f"conversation/cognitiveservices/v1?{'&'.join(q)}")
        headers = {"Content-Type": self.get("audio_format"),
                   "Accept": "application/json", **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers,
                           entity=bytes(rp["_audio"]))


class TextToSpeech(CognitiveServiceBase):
    """Text -> synthesized audio bytes (SSML POST).

    ``url`` is the region TTS endpoint, e.g.
    ``https://<region>.tts.speech.microsoft.com``."""

    text_col = Param("text_col", "text column", default="text")
    voice = ServiceParam("voice", "voice name", default="en-US-JennyNeural")
    language = ServiceParam("language", "language", default="en-US")
    output_format = Param("output_format", "audio output format",
                          default="riff-16khz-16bit-mono-pcm")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp):
        if rp.get("_text") is None:
            return None
        def esc(s, attr=False):
            s = (str(s).replace("&", "&amp;").replace("<", "&lt;")
                 .replace(">", "&gt;"))
            return s.replace('"', "&quot;").replace("'", "&apos;") if attr else s

        lang = esc(rp.get("language") or "en-US", attr=True)
        voice = esc(rp.get("voice") or "en-US-JennyNeural", attr=True)
        text = esc(rp["_text"])
        ssml = (f"<speak version='1.0' xml:lang='{lang}'>"
                f"<voice xml:lang='{lang}' name='{voice}'>{text}</voice></speak>")
        url = f"{(self.get('url') or '').rstrip('/')}/cognitiveservices/v1"
        headers = {"Content-Type": "application/ssml+xml",
                   "X-Microsoft-OutputFormat": self.get("output_format"),
                   **self.auth_headers(rp)}
        return HTTPRequest(url=url, method="POST", headers=headers, entity=ssml)

    def handle_response(self, resp):
        # binary audio body, not JSON
        if resp is None:
            return None, None
        if resp.error or resp.status_code // 100 != 2:
            return None, resp.error or f"HTTP {resp.status_code}: {resp.reason}"
        return resp.entity, None
