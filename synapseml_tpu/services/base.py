"""CognitiveServiceBase (reference ``services/CognitiveServiceBase.scala:540-612``):
a Transformer = pack per-row params -> SimpleHTTPTransformer(inputFunc with
auth headers) -> unpack/parse -> drop temp cols.

ServiceParams (``HasServiceParams:34``): every request field is either a
literal applied to all rows — ``stage.set(x="v")`` — or bound to a column
with per-row values — ``stage.set(x=("col", "colname"))``.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, ServiceParam, TypeConverters
from ..core.pipeline import Transformer
from ..core.resilience import Deadline, resilience_measures
from ..io.http import (
    AsyncHTTPClient,
    HTTPRequest,
    HTTPResponse,
)

__all__ = ["CognitiveServiceBase", "HasAsyncReply"]


class CognitiveServiceBase(Transformer):
    """Subclasses define ``build_request(row_params) -> HTTPRequest`` and
    (optionally) ``parse_response(json) -> value``."""

    feature_name = "services"

    subscription_key = ServiceParam("subscription_key", "API key (or column)")
    url = Param("url", "service endpoint URL")
    output_col = Param("output_col", "parsed response column", default="out")
    error_col = Param("error_col", "per-row error column", default="errors")
    concurrency = Param("concurrency", "in-flight requests", default=4,
                        converter=TypeConverters.to_int)
    timeout_s = Param("timeout_s", "request timeout", default=60.0,
                      converter=TypeConverters.to_float)
    backoffs_ms = ComplexParam("backoffs_ms", "retry backoff schedule "
                               "(threaded to the HTTP client, as "
                               "HTTPTransformer does)",
                               default=(100, 500, 1000))
    retry_policy = ComplexParam("retry_policy", "core.resilience.RetryPolicy "
                                "(overrides backoffs_ms; carries jitter rng "
                                "and retry budget)", default=None)

    # ---- subclass hooks -------------------------------------------------
    def build_request(self, row_params: dict) -> HTTPRequest | None:
        raise NotImplementedError

    def parse_response(self, payload):
        return payload

    def auth_headers(self, row_params: dict) -> dict:
        key = row_params.get("subscription_key")
        return {"Ocp-Apim-Subscription-Key": key} if key else {}

    def service_param_names(self) -> list[str]:
        return [name for name, p in self.params().items()
                if isinstance(p, ServiceParam)]

    def input_bindings(self) -> dict:
        """pseudo row-param name -> Param holding an input COLUMN name.
        Declared bindings are validated against the DataFrame and injected
        per row into ``build_request``'s row_params (one shared mechanism
        instead of per-service plumbing)."""
        return {}

    # ---- engine ---------------------------------------------------------
    def _row_params(self, p: dict, n: int) -> list[dict]:
        names = self.service_param_names()
        per_param = {name: self.resolve_row_param(name, p, n) for name in names}
        rows = [{name: per_param[name][i] for name in names} for i in range(n)]
        for key, col_param in self.input_bindings().items():
            col = p[self.get(col_param)]
            for i, r in enumerate(rows):
                r[key] = col[i]
        return rows

    def json_request(self, row_params: dict, url: str, body: dict,
                     method: str = "POST") -> HTTPRequest:
        """Authenticated JSON request — the shared construction used by every
        JSON-bodied service."""
        headers = {"Content-Type": "application/json",
                   **self.auth_headers(row_params)}
        return HTTPRequest(url=url, method=method, headers=headers,
                           entity=json.dumps(body))

    def handle_response(self, resp: HTTPResponse | None) -> tuple:
        """-> (parsed value, error or None)"""
        if resp is None:
            return None, None
        if resp.error or resp.status_code // 100 != 2:
            return None, resp.error or f"HTTP {resp.status_code}: {resp.reason}"
        try:
            return self.parse_response(resp.json()), None
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return None, f"unparseable response: {e}"

    def _transform(self, df: DataFrame) -> DataFrame:
        for col_param in self.input_bindings().values():
            self.require_columns(df, self.get(col_param))
        client = AsyncHTTPClient(self.get("concurrency"), self.get("timeout_s"),
                                 self.get("backoffs_ms"),
                                 policy=self.get("retry_policy"))

        def per_part(p):
            n = len(next(iter(p.values()))) if p else 0
            rows = self._row_params(p, n)
            requests = [self.build_request(r) for r in rows]
            responses = client.send_all(requests)
            responses = self.post_process_responses(requests, responses, client)
            parsed = np.empty(n, dtype=object)
            errors = np.empty(n, dtype=object)
            for i, resp in enumerate(responses):
                parsed[i], errors[i] = self.handle_response(resp)
            q = dict(p)
            q[self.get("output_col")] = parsed
            q[self.get("error_col")] = errors
            return q

        return df.map_partitions(per_part)

    def post_process_responses(self, requests, responses, client):
        """Hook for async/LRO polling (overridden by HasAsyncReply)."""
        return responses


class HasAsyncReply(CognitiveServiceBase):
    """Long-running-operation support (reference ``HasAsyncReply`` /
    ``AnalyzeTextLongRunningOperations.scala``): a 202 reply carries an
    Operation-Location to poll until status is succeeded/failed."""

    polling_interval_s = Param("polling_interval_s", "poll sleep", default=0.25,
                               converter=TypeConverters.to_float)
    max_poll_attempts = Param("max_poll_attempts", "max polls per row", default=40,
                              converter=TypeConverters.to_int)
    lro_deadline_s = Param("lro_deadline_s", "total wall-clock budget for the "
                           "whole polling sweep (0 = attempts-bounded only); "
                           "expiry marks pending rows as timed out",
                           default=0.0, converter=TypeConverters.to_float)

    _AUTH_HEADERS = ("Ocp-Apim-Subscription-Key", "api-key", "Authorization")

    def poll_headers(self, request: HTTPRequest | None = None) -> dict:
        """Auth for poll GETs: reuse the originating request's resolved auth
        headers (covers column-bound per-row keys), else the literal key."""
        if request is not None:
            h = {k: v for k, v in request.headers.items()
                 if k in self._AUTH_HEADERS}
            if h:
                return h
        key = self.get("subscription_key")
        if isinstance(key, tuple):
            key = None
        return {"Ocp-Apim-Subscription-Key": key} if key else {}

    def is_done(self, payload) -> bool:
        status = str(payload.get("status", "")).lower() if isinstance(payload, dict) else ""
        return status in ("succeeded", "failed", "partiallycompleted")

    def poll_location(self, resp: HTTPResponse) -> str | None:
        """Where to poll a pending operation (override for services that use
        the plain Location header, e.g. multivariate anomaly)."""
        return (resp.headers.get("Operation-Location")
                or resp.headers.get("operation-location"))

    def post_process_responses(self, requests, responses, client):
        out = list(responses)
        # all pending operations poll together each sweep: wall-clock is
        # O(polls), not O(rows * polls)
        pending: dict[int, str] = {}
        for i, resp in enumerate(out):
            if resp is not None and resp.status_code in (201, 202):
                loc = self.poll_location(resp)
                if loc:
                    pending[i] = loc
        budget = self.get("lro_deadline_s")
        deadline = Deadline(budget) if budget and budget > 0 else None
        deadline_cut = False
        for _ in range(self.get("max_poll_attempts")):
            if not pending:
                break
            if deadline is not None and deadline.expired():
                deadline_cut = True
                break
            time.sleep(self.get("polling_interval_s"))
            idxs = list(pending)
            polled = client.send_all(
                [HTTPRequest(url=pending[i], method="GET",
                             headers=self.poll_headers(requests[i]))
                 for i in idxs], deadline=deadline)
            for i, resp in zip(idxs, polled):
                if resp is None or resp.status_code // 100 != 2:
                    if (resp is not None and resp.status_code == 0
                            and resp.reason == "deadline expired"):
                        deadline_cut = True  # cut off by the poll deadline
                    out[i] = resp
                    del pending[i]
                    continue
                try:
                    done = self.is_done(resp.json())
                except json.JSONDecodeError:
                    out[i] = resp
                    del pending[i]
                    continue
                if done:
                    out[i] = resp
                    del pending[i]
        if deadline_cut:
            resilience_measures("services").count("deadline_expired")
        for i in pending:
            out[i] = HTTPResponse(status_code=0, reason="LRO timeout",
                                  error="long-running operation timed out")
        return out
