"""OpenAI services (reference ``services/openai/``):
OpenAIChatCompletion:98, OpenAICompletion, OpenAIEmbedding:27, and
OpenAIPrompt (``OpenAIPrompt.scala:40-767`` — column template interpolation +
json/regex/delimiter output parsers) with OpenAIDefaults global params.
"""

from __future__ import annotations

import json
import re

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import GlobalParams, Param, ServiceParam, TypeConverters
from ..io.http import HTTPRequest
from .base import CognitiveServiceBase

__all__ = ["OpenAIChatCompletion", "OpenAICompletion", "OpenAIEmbedding",
           "OpenAIPrompt", "OpenAIResponses", "OpenAIDefaults"]


class OpenAIDefaults:
    """(ref ``OpenAIDefaults.scala`` over GlobalParams) — session-wide
    deployment/key/url defaults for every OpenAI stage."""

    @staticmethod
    def set_deployment_name(v: str) -> None:
        GlobalParams.set_default("_OpenAIBase", "deployment_name", v)

    @staticmethod
    def set_subscription_key(v: str) -> None:
        GlobalParams.set_default("_OpenAIBase", "subscription_key", v)

    @staticmethod
    def set_url(v: str) -> None:
        GlobalParams.set_default("_OpenAIBase", "url", v)

    @staticmethod
    def set_temperature(v: float) -> None:
        GlobalParams.set_default("_OpenAIBase", "temperature", v)

    @staticmethod
    def reset() -> None:
        GlobalParams.reset()


class _OpenAIBase(CognitiveServiceBase):
    deployment_name = ServiceParam("deployment_name", "model deployment name")
    temperature = ServiceParam("temperature", "sampling temperature", default=None)
    max_tokens = ServiceParam("max_tokens", "max generated tokens", default=None)
    api_version = Param("api_version", "API version query param",
                        default="2024-02-01")

    def auth_headers(self, row_params: dict) -> dict:
        key = row_params.get("subscription_key")
        return {"api-key": key, "Content-Type": "application/json"} if key \
            else {"Content-Type": "application/json"}

    def _endpoint(self, row_params: dict, path: str) -> str:
        base = (self.get("url") or "").rstrip("/")
        dep = row_params.get("deployment_name")
        return f"{base}/openai/deployments/{dep}/{path}?api-version={self.get('api_version')}"

    def _common_body(self, row_params: dict) -> dict:
        body = {}
        if row_params.get("temperature") is not None:
            body["temperature"] = float(row_params["temperature"])
        if row_params.get("max_tokens") is not None:
            body["max_tokens"] = int(row_params["max_tokens"])
        return body


class OpenAIChatCompletion(_OpenAIBase):
    """(ref ``OpenAIChatCompletion.scala:98``) — messages col holds a list of
    {role, content} dicts."""

    messages_col = Param("messages_col", "chat messages column", default="messages")
    output_col = Param("output_col", "reply column", default="chat_completions")

    def input_bindings(self):
        return {"_messages": "messages_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        msgs = rp.get("_messages")
        if msgs is None:
            return None
        msgs = [dict(m) for m in (msgs.tolist() if isinstance(msgs, np.ndarray) else msgs)]
        body = {"messages": msgs, **self._common_body(rp)}
        return HTTPRequest(url=self._endpoint(rp, "chat/completions"), method="POST",
                           headers=self.auth_headers(rp), entity=json.dumps(body))

    def parse_response(self, payload):
        return payload


class OpenAIResponses(_OpenAIBase):
    """(ref ``OpenAIResponses.scala``) — the /responses API: ``input`` is a
    string or a messages list; parses ``output[].content[].text``."""

    input_col = Param("input_col", "input column (string or messages list)",
                      default="input")
    output_col = Param("output_col", "response text column", default="responses")

    def input_bindings(self):
        return {"_input": "input_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        val = rp.get("_input")
        if val is None:
            return None
        if isinstance(val, np.ndarray):
            val = val.tolist()
        if isinstance(val, (list, tuple)):
            val = [dict(m) for m in val]
        else:
            val = str(val)
        body = {"input": val, **self._common_body(rp)}
        base = (self.get("url") or "").rstrip("/")
        url = f"{base}/openai/responses?api-version={self.get('api_version')}"
        return HTTPRequest(url=url, method="POST",
                           headers=self.auth_headers(rp), entity=json.dumps(body))

    def parse_response(self, payload):
        try:
            texts = [c.get("text", "") for item in payload.get("output", [])
                     for c in item.get("content", []) if c.get("type") == "output_text"]
            return "".join(texts) if texts else payload
        except AttributeError:
            return payload


class OpenAICompletion(_OpenAIBase):
    """(ref ``OpenAICompletion.scala``)"""

    prompt_col = Param("prompt_col", "prompt column", default="prompt")
    output_col = Param("output_col", "completion column", default="completions")

    def input_bindings(self):
        return {"_prompt": "prompt_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_prompt") is None:
            return None
        body = {"prompt": str(rp["_prompt"]), **self._common_body(rp)}
        return HTTPRequest(url=self._endpoint(rp, "completions"), method="POST",
                           headers=self.auth_headers(rp), entity=json.dumps(body))


class OpenAIEmbedding(_OpenAIBase):
    """(ref ``OpenAIEmbedding.scala:27``) — emits the embedding vector
    directly (not the raw payload)."""

    text_col = Param("text_col", "text column", default="text")
    output_col = Param("output_col", "embedding column", default="embedding")

    def input_bindings(self):
        return {"_text": "text_col"}

    def build_request(self, rp: dict) -> HTTPRequest | None:
        if rp.get("_text") is None:
            return None
        return HTTPRequest(url=self._endpoint(rp, "embeddings"), method="POST",
                           headers=self.auth_headers(rp),
                           entity=json.dumps({"input": str(rp["_text"])}))

    def parse_response(self, payload):
        data = payload.get("data") or []
        if data and "embedding" in data[0]:
            return np.asarray(data[0]["embedding"], np.float32)
        return None


# ---------------------------------------------------------------------------
# OpenAIPrompt
# ---------------------------------------------------------------------------

_TEMPLATE_RE = re.compile(r"\{(\w+)\}")


def parse_json_output(text: str, schema_hint=None):
    """Extract the first JSON object/array from the reply."""
    text = text.strip()
    for start, end in (("{", "}"), ("[", "]")):
        i = text.find(start)
        if i >= 0:
            j = text.rfind(end)
            if j > i:
                try:
                    return json.loads(text[i : j + 1])
                except json.JSONDecodeError:
                    continue
    return None


class OpenAIPrompt(_OpenAIBase):
    """(ref ``OpenAIPrompt.scala:40-767``) — prompt template interpolated from
    columns; post parsers: none | json | regex | csv (``:731-767``)."""

    prompt_template = Param("prompt_template",
                            "template with {column} placeholders")
    output_col = Param("output_col", "parsed output column", default="outParsedOutput")
    post_processing = Param("post_processing", "none | json | regex | csv",
                            default="none",
                            validator=lambda v: v in ("none", "json", "regex", "csv"))
    post_processing_options = Param("post_processing_options",
                                    "dict: regexGroup/regex or delimiter",
                                    default=None)
    system_prompt = Param("system_prompt", "optional system message", default=None)

    def _row_params(self, p, n):
        rows = super()._row_params(p, n)
        template = self.get("prompt_template")
        cols = _TEMPLATE_RE.findall(template)
        missing = [c for c in cols if c not in p]
        if missing:
            raise ValueError(f"OpenAIPrompt: template columns {missing} "
                             f"not in DataFrame")
        for i, r in enumerate(rows):
            # substitute ONLY known {column} placeholders so literal braces in
            # the prompt (e.g. JSON examples) pass through untouched
            r["_prompt"] = _TEMPLATE_RE.sub(
                lambda m: str(p[m.group(1)][i]) if m.group(1) in p else m.group(0),
                template)
        return rows

    def build_request(self, rp: dict) -> HTTPRequest | None:
        msgs = []
        if self.get("system_prompt"):
            msgs.append({"role": "system", "content": self.get("system_prompt")})
        msgs.append({"role": "user", "content": rp["_prompt"]})
        body = {"messages": msgs, **self._common_body(rp)}
        return HTTPRequest(url=self._endpoint(rp, "chat/completions"), method="POST",
                           headers=self.auth_headers(rp), entity=json.dumps(body))

    def parse_response(self, payload):
        try:
            text = payload["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError):
            return None
        mode = self.get("post_processing")
        opts = self.get("post_processing_options") or {}
        if mode == "none":
            return text
        if mode == "json":
            return parse_json_output(text)
        if mode == "regex":
            m = re.search(opts.get("regex", "(.*)"), text, re.DOTALL)
            if not m:
                return None
            try:
                return m.group(int(opts.get("regexGroup", 1)))
            except (IndexError, re.error):  # regexGroup beyond capture groups
                return None
        if mode == "csv":
            delim = opts.get("delimiter", ",")
            return [s.strip() for s in text.strip().split(delim)]
        return text
