"""Notebook corpus emission — the reference's ``docs/**/*.ipynb`` tier.

The reference ships its documentation as executable Jupyter notebooks
(``/root/reference/docs/Explore Algorithms/...``) validated by an nbtest
tier (``core/src/test/scala/.../nbtest/DatabricksUtilities.scala``). This
framework keeps the SOURCE of truth as plain ``# %%`` percent-cell Python
scripts (``docs/examples/``, ``docs/walkthroughs/`` — executed directly by
the test suite, diff-friendly in review) and EMITS the ``.ipynb`` corpus from
them, the same emitted-artifact pattern as :func:`..codegen.emit_wrappers`:
the committed notebooks are generated files, covered by a drift test.

Cell grammar (the jupytext "percent" convention):

* ``# %% [markdown]`` starts a markdown cell; following comment lines are
  de-commented into markdown source.
* ``# %%`` (with an optional trailing title, kept as a leading comment)
  starts a code cell.
* Anything before the first marker: a module docstring becomes the leading
  markdown cell; other preamble code joins the first code cell.
"""

from __future__ import annotations

import json
import os

__all__ = ["percent_to_notebook", "emit_notebooks", "notebook_code"]

_NB_METADATA = {
    "kernelspec": {"display_name": "Python 3", "language": "python",
                   "name": "python3"},
    "language_info": {"name": "python", "version": "3"},
}


def _markdown_cell(lines: list[str]) -> dict:
    src = []
    for ln in lines:
        s = ln.rstrip("\n")
        if s.startswith("# "):
            s = s[2:]
        elif s == "#":
            s = ""
        src.append(s)
    while src and not src[0].strip():
        src.pop(0)
    while src and not src[-1].strip():
        src.pop()
    return {"cell_type": "markdown", "metadata": {},
            "source": [s + "\n" for s in src[:-1]] + src[-1:]} if src else None


def _code_cell(lines: list[str]) -> dict:
    src = [ln.rstrip("\n") for ln in lines]
    while src and not src[0].strip():
        src.pop(0)
    while src and not src[-1].strip():
        src.pop()
    if not src:
        return None
    return {"cell_type": "code", "execution_count": None, "metadata": {},
            "outputs": [], "source": [s + "\n" for s in src[:-1]] + src[-1:]}


def _split_module_docstring(text: str):
    """Return (docstring, rest) if ``text`` opens with a module docstring
    BEFORE any ``# %%`` marker, else (None, text)."""
    import ast

    first_marker = None
    for i, ln in enumerate(text.splitlines()):
        if ln.strip().startswith("# %%"):
            first_marker = i + 1  # 1-based, like ast linenos
            break
    try:
        mod = ast.parse(text)
    except SyntaxError:
        return None, text
    if (mod.body and isinstance(mod.body[0], ast.Expr)
            and isinstance(mod.body[0].value, ast.Constant)
            and isinstance(mod.body[0].value.value, str)
            and (first_marker is None or mod.body[0].end_lineno < first_marker)):
        lines = text.splitlines(keepends=True)
        return (mod.body[0].value.value.strip(),
                "".join(lines[mod.body[0].end_lineno:]))
    return None, text


def percent_to_notebook(text: str) -> dict:
    """Convert ``# %%`` percent-cell script text to a nbformat-4 notebook."""
    doc, text = _split_module_docstring(text)
    lines = text.splitlines()
    cells = []
    if doc:
        cells.append({"cell_type": "markdown", "metadata": {},
                      "source": [s + "\n" for s in doc.splitlines()[:-1]]
                      + doc.splitlines()[-1:]})
    cur: list[str] = []
    kind = "code"

    def flush():
        cell = (_markdown_cell(cur) if kind == "markdown" else _code_cell(cur))
        if cell:
            cells.append(cell)
        cur.clear()

    for ln in lines:
        stripped = ln.strip()
        if stripped.startswith("# %%"):
            flush()
            rest = stripped[4:].strip()
            if rest.startswith("[markdown]"):
                kind = "markdown"
            else:
                kind = "code"
                if rest:  # keep the cell title as a leading comment
                    cur.append(f"# {rest}")
            continue
        cur.append(ln)
    flush()
    return {"nbformat": 4, "nbformat_minor": 5, "metadata": dict(_NB_METADATA),
            "cells": cells}


def notebook_code(nb: dict) -> str:
    """All code-cell source joined — the nbtest executor's input."""
    return "\n\n".join("".join(c["source"]) for c in nb["cells"]
                       if c["cell_type"] == "code")


def emit_notebooks(src_dirs, out_dir: str) -> list[str]:
    """Emit one ``.ipynb`` per percent-cell ``.py`` under ``src_dirs``.

    Returns the written paths. Deterministic output (sorted inputs, stable
    JSON) so a drift test can regenerate and diff.
    """
    os.makedirs(out_dir, exist_ok=True)
    written = []
    seen: dict[str, str] = {}
    expected = {name[:-3] + ".ipynb"
                for src_dir in src_dirs for name in os.listdir(src_dir)
                if name.endswith(".py") and not name.startswith("_")}
    for stale in sorted(set(os.listdir(out_dir)) - expected):
        if stale.endswith(".ipynb"):  # renamed/removed source: drop its notebook
            os.remove(os.path.join(out_dir, stale))
    for src_dir in src_dirs:
        for name in sorted(os.listdir(src_dir)):
            if not name.endswith(".py") or name.startswith("_"):
                continue
            if name in seen:
                raise ValueError(
                    f"notebook basename collision: {name} exists in both "
                    f"{seen[name]} and {src_dir} — one would silently "
                    f"overwrite the other in {out_dir}")
            seen[name] = src_dir
            with open(os.path.join(src_dir, name)) as f:
                nb = percent_to_notebook(f.read())
            out = os.path.join(out_dir, name[:-3] + ".ipynb")
            with open(out, "w") as f:
                json.dump(nb, f, indent=1, sort_keys=True)
                f.write("\n")
            written.append(out)
    return written


def main() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    docs = os.path.join(repo, "docs")
    out = emit_notebooks([os.path.join(docs, "examples"),
                          os.path.join(docs, "walkthroughs")],
                         os.path.join(docs, "notebooks"))
    print(f"wrote {len(out)} notebooks to docs/notebooks/")


if __name__ == "__main__":
    main()
