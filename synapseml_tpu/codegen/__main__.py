"""CLI: regenerate the compat wrappers and API docs.

Usage: python -m synapseml_tpu.codegen [--docs-dir DIR] [--compat-dir DIR]
"""
import argparse
import os

from . import emit_wrappers, write_docs

if __name__ == "__main__":
    parser = argparse.ArgumentParser(prog="python -m synapseml_tpu.codegen",
                                     description=__doc__)
    parser.add_argument("--docs-dir", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "docs", "api"), help="API-docs output directory (default: docs/api)")
    parser.add_argument("--compat-dir", default=None,
                        help="wrapper output directory (default: the in-tree "
                             "synapseml_tpu/compat package)")
    args = parser.parse_args()

    for p in emit_wrappers(args.compat_dir):
        print(p)
    for p in write_docs(args.docs_dir):
        print(p)
