"""Static website emission — the reference's docusaurus ``website/`` tier.

The reference ships a generated docs website (``/root/reference/website/``,
docusaurus over the ``docs/`` markdown + notebook corpus, with
``website/doctest.py`` executing its code blocks). Here the analog is a
dependency-free static site emitted from the SAME sources the test suite
already executes (docs-as-tests: ``tests/test_examples.py``,
``tests/test_walkthroughs.py``, ``tests/test_notebooks.py`` are the doctest
tier): every ``docs/**/*.md`` page plus an index page per section, rendered
with a small CommonMark-subset renderer (headers, fenced code, lists,
tables, links, emphasis) — no docusaurus/node in the image, and none needed
to browse: ``python -m http.server -d docs/site``.

Generated output (``docs/site/``) is committed and drift-tested like the
notebook corpus and the wrapper surface.
"""

from __future__ import annotations

import html
import os
import re

__all__ = ["markdown_to_html", "emit_site"]

_CSS = """
body { font-family: system-ui, sans-serif; margin: 0; display: flex;
       color: #1a1a1a; }
nav { width: 250px; min-height: 100vh; background: #f4f6f8; padding: 1rem;
      box-sizing: border-box; flex-shrink: 0; }
nav h2 { font-size: 0.85rem; text-transform: uppercase; color: #556; }
nav a { display: block; padding: 2px 0; color: #2a6df4;
        text-decoration: none; font-size: 0.92rem; }
main { padding: 2rem 3rem; max-width: 900px; box-sizing: border-box; }
code { background: #f0f2f4; padding: 1px 4px; border-radius: 3px;
       font-size: 0.9em; }
pre { background: #0f1419; color: #e6e1cf; padding: 1rem; overflow-x: auto;
      border-radius: 6px; }
pre code { background: none; color: inherit; padding: 0; }
table { border-collapse: collapse; margin: 1rem 0; }
th, td { border: 1px solid #ccd; padding: 4px 10px; font-size: 0.92rem; }
th { background: #eef1f4; }
h1, h2, h3 { line-height: 1.25; }
a { color: #2a6df4; }
"""


def _inline(text: str) -> str:
    """Inline markdown -> HTML (escape first; then code/links/emphasis)."""
    out = html.escape(text, quote=False)
    out = re.sub(r"`([^`]+)`", r"<code>\1</code>", out)
    out = re.sub(r"\[([^\]]+)\]\(([^)\s]+)\)", r'<a href="\2">\1</a>', out)
    out = re.sub(r"\*\*([^*]+)\*\*", r"<strong>\1</strong>", out)
    out = re.sub(r"(?<![\w*])\*([^*\n]+)\*(?![\w*])", r"<em>\1</em>", out)
    return out


def markdown_to_html(md: str) -> str:
    """CommonMark-subset renderer: headers, fenced code, unordered/ordered
    lists, pipe tables, blockquotes, paragraphs."""
    lines = md.splitlines()
    out: list[str] = []
    para: list[str] = []
    in_code = False
    code_buf: list[str] = []
    list_stack: list[str] = []

    def flush_para():
        if para:
            out.append(f"<p>{_inline(' '.join(para))}</p>")
            para.clear()

    def close_lists():
        while list_stack:
            out.append(f"</{list_stack.pop()}>")

    i = 0
    while i < len(lines):
        ln = lines[i]
        if in_code:
            if ln.strip().startswith("```"):
                out.append("<pre><code>"
                           + html.escape("\n".join(code_buf)) + "</code></pre>")
                code_buf.clear()
                in_code = False
            else:
                code_buf.append(ln)
            i += 1
            continue
        stripped = ln.strip()
        if stripped.startswith("```"):
            flush_para()
            close_lists()
            in_code = True
            i += 1
            continue
        m = re.match(r"(#{1,6})\s+(.*)", stripped)
        if m:
            flush_para()
            close_lists()
            lvl = len(m.group(1))
            out.append(f"<h{lvl}>{_inline(m.group(2))}</h{lvl}>")
            i += 1
            continue
        if stripped.startswith("|") and i + 1 < len(lines) \
                and re.match(r"^\s*\|[\s:|-]+\|\s*$", lines[i + 1]):
            flush_para()
            close_lists()
            header = [c.strip() for c in stripped.strip("|").split("|")]
            out.append("<table><tr>"
                       + "".join(f"<th>{_inline(c)}</th>" for c in header)
                       + "</tr>")
            i += 2
            while i < len(lines) and lines[i].strip().startswith("|"):
                cells = [c.strip() for c in lines[i].strip().strip("|").split("|")]
                out.append("<tr>" + "".join(f"<td>{_inline(c)}</td>"
                                            for c in cells) + "</tr>")
                i += 1
            out.append("</table>")
            continue
        m = re.match(r"^(\s*)([-*]|\d+\.)\s+(.*)", ln)
        if m:
            flush_para()
            tag = "ol" if m.group(2)[0].isdigit() else "ul"
            if not list_stack:
                out.append(f"<{tag}>")
                list_stack.append(tag)
            out.append(f"<li>{_inline(m.group(3))}</li>")
            i += 1
            # absorb hanging continuation lines of the same list item
            while i < len(lines) and lines[i].startswith("  ") \
                    and not re.match(r"^(\s*)([-*]|\d+\.)\s+", lines[i]):
                out[-1] = out[-1][:-5] + " " + _inline(lines[i].strip()) + "</li>"
                i += 1
            continue
        if stripped.startswith(">"):
            flush_para()
            close_lists()
            out.append(f"<blockquote>{_inline(stripped[1:].strip())}</blockquote>")
            i += 1
            continue
        if not stripped:
            flush_para()
            close_lists()
            i += 1
            continue
        para.append(stripped)
        i += 1
    if in_code:  # unterminated fence
        out.append("<pre><code>" + html.escape("\n".join(code_buf))
                   + "</code></pre>")
    flush_para()
    close_lists()
    return "\n".join(out)


def _page(title: str, nav_html: str, body: str) -> str:
    return (f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title><style>{_CSS}</style></head>"
            f"<body><nav>{nav_html}</nav><main>{body}</main></body></html>\n")


def emit_site(docs_dir: str | None = None, out_dir: str | None = None) -> list[str]:
    """Render every docs markdown page into ``docs/site/``; returns paths.

    Deterministic (sorted inputs) so a drift test can regenerate and diff.
    Stale pages from renamed sources are removed.
    """
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    docs_dir = docs_dir or os.path.join(repo, "docs")
    out_dir = out_dir or os.path.join(docs_dir, "site")
    os.makedirs(out_dir, exist_ok=True)

    sections = {"": ["GETTING_STARTED.md", "ARCHITECTURE.md", "AUTOML.md",
                     "BENCHMARKS.md", "CONTINUAL.md", "DATA.md", "FLEET.md",
                     "OBSERVABILITY.md", "RAI.md", "REGISTRY.md",
                     "RESILIENCE.md", "RETRIEVAL.md", "SCORING.md",
                     "SERVING.md", "SHARDING.md"],
                "api": sorted(f for f in os.listdir(os.path.join(docs_dir, "api"))
                              if f.endswith(".md"))}
    pages = []  # (out_name, title, src_path)
    for sec, names in sections.items():
        for name in names:
            src = os.path.join(docs_dir, sec, name) if sec else \
                os.path.join(docs_dir, name)
            if not os.path.exists(src):
                continue
            stem = name[:-3].lower()
            out_name = (f"{sec}_{stem}.html" if sec else f"{stem}.html")
            title = stem.replace("_", " ")
            pages.append((out_name, title, src))

    nav = ["<h2>synapseml_tpu</h2>", '<a href="index.html">Index</a>']
    for out_name, title, _ in pages:
        nav.append(f'<a href="{out_name}">{html.escape(title)}</a>')
    nav_html = "\n".join(nav)

    written = []
    expected = {"index.html"}
    for out_name, title, src in pages:
        with open(src) as f:
            body = markdown_to_html(f.read())
        path = os.path.join(out_dir, out_name)
        with open(path, "w") as f:
            f.write(_page(title, nav_html, body))
        written.append(path)
        expected.add(out_name)

    # index: narrative entry + the executable corpus listings
    nb_dir = os.path.join(docs_dir, "notebooks")
    notebooks = sorted(n for n in os.listdir(nb_dir) if n.endswith(".ipynb")) \
        if os.path.isdir(nb_dir) else []
    body = ["<h1>synapseml_tpu documentation</h1>",
            "<p>TPU-native rebuild of the SynapseML feature set: JAX/XLA "
            "compute, one device mesh for every parallelism, the same "
            "estimator/transformer surface.</p>",
            "<h2>Guides</h2><ul>"]
    body += [f'<li><a href="{o}">{html.escape(t)}</a></li>'
             for o, t, _ in pages if not o.startswith("api_")]
    body.append("</ul><h2>API reference</h2><ul>")
    body += [f'<li><a href="{o}">{html.escape(t)}</a></li>'
             for o, t, _ in pages if o.startswith("api_")]
    body.append("</ul><h2>Notebook corpus</h2><p>Executable notebooks "
                "(emitted from the percent-cell scripts, executed by the "
                "test suite):</p><ul>")
    body += [f"<li><code>docs/notebooks/{html.escape(n)}</code></li>"
             for n in notebooks]
    body.append("</ul>")
    index_path = os.path.join(out_dir, "index.html")
    with open(index_path, "w") as f:
        f.write(_page("synapseml_tpu docs", nav_html, "\n".join(body)))
    written.append(index_path)

    for stale in sorted(set(os.listdir(out_dir)) - expected):
        if stale.endswith(".html"):
            os.remove(os.path.join(out_dir, stale))
    return written


if __name__ == "__main__":
    out = emit_site()
    print(f"wrote {len(out)} pages to docs/site/")
