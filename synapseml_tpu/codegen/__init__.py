"""Codegen (reference ``core/.../codegen/Wrappable.scala`` + CodegenPlugin —
SURVEY.md §1 L7).

The reference reflects over Scala params to EMIT Python/R wrapper classes.
This framework is Python-first, so codegen shrinks to what remains useful
(SURVEY.md §7 step 9): reflection-driven artifacts FROM the param registry —
markdown API reference per module and a machine-readable stage manifest
(the piece wrapper generators and doc sites consume).
"""

from __future__ import annotations

import importlib
import inspect
import json
import os
import pkgutil

from ..core.params import ComplexParam, Param, ServiceParam
from ..core.pipeline import Estimator, Model, PipelineStage, Transformer

__all__ = ["discover_stages", "stage_manifest", "generate_markdown_docs",
           "write_docs"]

_ABSTRACT = {"PipelineStage", "Transformer", "Estimator", "Model"}


def discover_stages() -> dict[str, type]:
    """Every PipelineStage subclass in the package (the Wrappable walk —
    ref ``JarLoadingUtils`` reflection)."""
    import synapseml_tpu

    classes: dict[str, type] = {}
    for modinfo in pkgutil.walk_packages(synapseml_tpu.__path__,
                                         prefix="synapseml_tpu."):
        mod = importlib.import_module(modinfo.name)
        for name, obj in vars(mod).items():
            if (inspect.isclass(obj) and issubclass(obj, PipelineStage)
                    and obj.__module__.startswith("synapseml_tpu")
                    and not name.startswith("_")
                    and obj.__name__ not in _ABSTRACT):
                classes[f"{obj.__module__}.{name}"] = obj
    return classes


def _param_kind(p: Param) -> str:
    if isinstance(p, ServiceParam):
        return "service (value or ('col', name))"
    if isinstance(p, ComplexParam):
        return "complex (non-JSON)"
    return "simple"


def _stage_kind(cls: type) -> str:
    if issubclass(cls, Model):
        return "Model"
    if issubclass(cls, Estimator):
        return "Estimator"
    if issubclass(cls, Transformer):
        return "Transformer"
    return "Stage"


def stage_manifest() -> list[dict]:
    """Machine-readable stage descriptors (wrapper-generator input)."""
    out = []
    for full_name, cls in sorted(discover_stages().items()):
        out.append({
            "class": full_name,
            "name": cls.__name__,
            "module": cls.__module__,
            "kind": _stage_kind(cls),
            "feature": getattr(cls, "feature_name", None),
            "doc": inspect.getdoc(cls) or "",
            "params": [
                {"name": name, "doc": p.doc, "default": repr(p.default),
                 "kind": _param_kind(p)}
                for name, p in sorted(cls.params().items())
            ],
        })
    return out


def generate_markdown_docs() -> dict[str, str]:
    """module family -> markdown API reference."""
    by_family: dict[str, list[dict]] = {}
    for entry in stage_manifest():
        family = entry["module"].split(".")[1]
        by_family.setdefault(family, []).append(entry)
    docs = {}
    for family, entries in sorted(by_family.items()):
        lines = [f"# `synapseml_tpu.{family}`", ""]
        for e in entries:
            lines.append(f"## {e['name']} ({e['kind']})")
            lines.append("")
            if e["doc"]:
                lines.append(e["doc"])
                lines.append("")
            if e["params"]:
                lines.append("| param | kind | default | doc |")
                lines.append("|---|---|---|---|")
                for p in e["params"]:
                    doc = p["doc"].replace("|", "\\|")
                    lines.append(f"| `{p['name']}` | {p['kind']} | "
                                 f"`{p['default']}` | {doc} |")
                lines.append("")
        docs[family] = "\n".join(lines)
    return docs


def write_docs(output_dir: str) -> list[str]:
    """Emit docs/api/*.md + stages.json; returns written paths."""
    os.makedirs(output_dir, exist_ok=True)
    written = []
    for family, md in generate_markdown_docs().items():
        path = os.path.join(output_dir, f"{family}.md")
        with open(path, "w") as f:
            f.write(md)
        written.append(path)
    manifest_path = os.path.join(output_dir, "stages.json")
    with open(manifest_path, "w") as f:
        json.dump(stage_manifest(), f, indent=2)
    written.append(manifest_path)
    return written
