"""Codegen (reference ``core/.../codegen/Wrappable.scala`` + CodegenPlugin —
SURVEY.md §1 L7).

The reference reflects over Scala params to EMIT Python/R wrapper classes.
This framework is Python-first, so codegen shrinks to what remains useful
(SURVEY.md §7 step 9): reflection-driven artifacts FROM the param registry —
markdown API reference per module and a machine-readable stage manifest
(the piece wrapper generators and doc sites consume).
"""

from __future__ import annotations

import importlib
import inspect
import json
import os
import pkgutil
import shutil

from ..core.params import ComplexParam, Param, ServiceParam
from ..core.pipeline import Estimator, Model, PipelineStage, Transformer

__all__ = ["discover_stages", "stage_manifest", "generate_markdown_docs",
           "write_docs", "emit_wrappers", "facts"]

_ABSTRACT = {"PipelineStage", "Transformer", "Estimator", "Model"}


def discover_stages() -> dict[str, type]:
    """Every PipelineStage subclass in the package (the Wrappable walk —
    ref ``JarLoadingUtils`` reflection)."""
    import synapseml_tpu

    classes: dict[str, type] = {}
    for modinfo in pkgutil.walk_packages(synapseml_tpu.__path__,
                                         prefix="synapseml_tpu."):
        # never import __main__ scripts (side effects) or the generated
        # wrappers themselves
        if (modinfo.name.endswith("__main__")
                or modinfo.name.startswith("synapseml_tpu.compat")):
            continue
        mod = importlib.import_module(modinfo.name)
        for name, obj in vars(mod).items():
            if (inspect.isclass(obj) and issubclass(obj, PipelineStage)
                    and obj.__module__.startswith("synapseml_tpu")
                    and not name.startswith("_")
                    and obj.__name__ not in _ABSTRACT):
                classes[f"{obj.__module__}.{name}"] = obj
    return classes


def _param_kind(p: Param) -> str:
    if isinstance(p, ServiceParam):
        return "service (value or ('col', name))"
    if isinstance(p, ComplexParam):
        return "complex (non-JSON)"
    return "simple"


def _stage_kind(cls: type) -> str:
    if issubclass(cls, Model):
        return "Model"
    if issubclass(cls, Estimator):
        return "Estimator"
    if issubclass(cls, Transformer):
        return "Transformer"
    return "Stage"


def stage_manifest() -> list[dict]:
    """Machine-readable stage descriptors (wrapper-generator input)."""
    out = []
    for full_name, cls in sorted(discover_stages().items()):
        out.append({
            "class": full_name,
            "name": cls.__name__,
            "module": cls.__module__,
            "kind": _stage_kind(cls),
            "feature": getattr(cls, "feature_name", None),
            "doc": inspect.getdoc(cls) or "",
            "params": [
                {"name": name, "doc": p.doc, "default": repr(p.default),
                 "kind": _param_kind(p)}
                for name, p in sorted(cls.params().items())
            ],
        })
    return out


def generate_markdown_docs() -> dict[str, str]:
    """module family -> markdown API reference."""
    by_family: dict[str, list[dict]] = {}
    for entry in stage_manifest():
        family = entry["module"].split(".")[1]
        by_family.setdefault(family, []).append(entry)
    docs = {}
    for family, entries in sorted(by_family.items()):
        lines = [f"# `synapseml_tpu.{family}`", ""]
        for e in entries:
            lines.append(f"## {e['name']} ({e['kind']})")
            lines.append("")
            if e["doc"]:
                lines.append(e["doc"])
                lines.append("")
            if e["params"]:
                lines.append("| param | kind | default | doc |")
                lines.append("|---|---|---|---|")
                for p in e["params"]:
                    doc = p["doc"].replace("|", "\\|")
                    lines.append(f"| `{p['name']}` | {p['kind']} | "
                                 f"`{p['default']}` | {doc} |")
                lines.append("")
        docs[family] = "\n".join(lines)
    return docs


def facts() -> dict:
    """Self-reported numbers computed FROM the code, never hand-maintained.

    Reports (COVERAGE.md, README.md, docstrings) must quote these; the
    drift test (``tests/test_codegen.py``) greps the documents for numeric
    claims and fails when they disagree with this function — the same
    pattern that keeps the generated wrappers honest.
    """
    from ..onnx.convert import OP_REGISTRY

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    def _count(relpath: str, suffix: str) -> int:
        d = os.path.join(repo, relpath)
        try:
            return sum(1 for n in os.listdir(d) if n.endswith(suffix))
        except OSError:
            return 0

    from ..onnx.contrib import CONTRIB_OPS

    svc_dir = os.path.join(repo, "synapseml_tpu", "services")
    try:
        n_services = sum(1 for n in os.listdir(svc_dir)
                         if n.endswith(".py") and n != "__init__.py")
    except OSError:
        n_services = 0
    return {
        "onnx_ops": len(OP_REGISTRY),
        "onnx_contrib_ops": len(CONTRIB_OPS),
        "stage_classes": len(discover_stages()),
        "notebooks": _count("docs/notebooks", ".ipynb"),
        "walkthroughs": _count("docs/walkthroughs", ".py"),
        "examples": _count("docs/examples", ".py"),
        "service_modules": n_services,
    }


def write_docs(output_dir: str) -> list[str]:
    """Emit docs/api/*.md + stages.json + facts.json; returns written
    paths."""
    os.makedirs(output_dir, exist_ok=True)
    written = []
    for family, md in generate_markdown_docs().items():
        path = os.path.join(output_dir, f"{family}.md")
        with open(path, "w") as f:
            f.write(md)
        written.append(path)
    manifest_path = os.path.join(output_dir, "stages.json")
    with open(manifest_path, "w") as f:
        json.dump(stage_manifest(), f, indent=2)
    written.append(manifest_path)
    facts_path = os.path.join(output_dir, "facts.json")
    with open(facts_path, "w") as f:
        json.dump(facts(), f, indent=2)
    written.append(facts_path)
    return written


# ---------------------------------------------------------------------------
# wrapper emission (reference Wrappable.scala:56-389 pyGen: emit importable
# pyspark-style wrapper classes from the stage manifest)
# ---------------------------------------------------------------------------

# our package -> the reference's python namespace segment
_NAMESPACE_MAP = {
    "gbdt": "lightgbm",
    "image": "opencv",
    "models": "dl",
    "io": "io",
    # retrieval STAGES wrap beside the KNN family (they share the scorer
    # kernel); the package's full surface rides the retrieval passthrough
    # below — a same-named wrapper module would collide with it
    "retrieval": "nn",
}

# module-granular overrides where the reference splits one of our packages
# across namespaces (synapse.ml.cntk lives beside synapse.ml.dl)
_MODULE_NAMESPACE_MAP = {
    "models.cntk": "cntk",
}

# non-stage public surfaces that still get a compat namespace: generated
# passthrough modules re-exporting the package's __all__ (the registry's
# classes are not PipelineStages, so the param-reflection wrapper shape
# doesn't apply — but the compat coverage rule "every public symbol is
# importable from synapseml_tpu.compat.<ns>" does, and
# tests/test_codegen.py::test_registry_compat_coverage enforces it)
_PASSTHROUGH_NAMESPACES = {
    "continual": "synapseml_tpu.continual",
    "fleet": "synapseml_tpu.fleet",
    "rai": "synapseml_tpu.rai",
    "registry": "synapseml_tpu.registry",
    "retrieval": "synapseml_tpu.retrieval",
    "scoring": "synapseml_tpu.scoring",
}

_PASSTHROUGH_HEADER = '''"""Generated passthrough namespace — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers).
Re-exports the public surface of ``%s`` so the compat layer covers
non-stage subsystems too (compat coverage is drift-tested).
"""

'''

_WRAPPER_HEADER = '''"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase

'''


def _camel(name: str) -> str:
    parts = name.split("_")
    return "".join(p.capitalize() if p else "" for p in parts)


def emit_wrappers(out_dir: str | None = None) -> list[str]:
    """Write one wrapper module per reference namespace into
    ``synapseml_tpu/compat`` (or ``out_dir``); returns written paths."""
    if out_dir is None:
        out_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "compat")
    os.makedirs(out_dir, exist_ok=True)
    by_ns: dict[str, list] = {}
    for full_name, cls in sorted(discover_stages().items()):
        parts = cls.__module__.split(".")
        pkg = parts[1]
        ns = (_MODULE_NAMESPACE_MAP.get(".".join(parts[1:3]))
              or _NAMESPACE_MAP.get(pkg, pkg))
        by_ns.setdefault(ns, []).append((full_name, cls))

    # non-default out_dir must also carry the runtime base the generated
    # modules import (the in-tree package has it committed)
    base_src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "compat", "_base.py")
    base_dst = os.path.join(out_dir, "_base.py")
    if os.path.abspath(base_src) != os.path.abspath(base_dst):
        shutil.copyfile(base_src, base_dst)

    written = []
    all_modules = []
    for ns, entries in sorted(by_ns.items()):
        seen = set()
        lines = [_WRAPPER_HEADER]
        for full_name, cls in entries:
            if cls.__name__ in seen:  # same class re-exported via __init__
                continue
            seen.add(cls.__name__)
            doc = (inspect.getdoc(cls) or "").split("\n")[0].replace('"""', "'")
            lines.append(f"class {cls.__name__}(WrapperBase):")
            lines.append(f'    """{doc or cls.__name__} (wraps '
                         f'``{full_name}``)."""\n')
            lines.append(f"    _target = {full_name!r}\n")
            for pname in sorted(cls.params()):
                camel = _camel(pname)
                lines.append(f"    def set{camel}(self, value):")
                lines.append(f"        return self._set({pname!r}, value)\n")
                lines.append(f"    def get{camel}(self):")
                lines.append(f"        return self._get({pname!r})\n")
            lines.append("")
        path = os.path.join(out_dir, f"{ns}.py")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        written.append(path)
        all_modules.append(ns)

    for ns, target_mod in sorted(_PASSTHROUGH_NAMESPACES.items()):
        mod = importlib.import_module(target_mod)
        names = sorted(getattr(mod, "__all__"))
        lines = [_PASSTHROUGH_HEADER % target_mod,
                 f"from {target_mod} import (  # noqa: F401"]
        lines += [f"    {n}," for n in names]
        lines.append(")")
        lines.append("")
        lines.append("__all__ = [")
        lines += [f"    {n!r}," for n in names]
        lines.append("]")
        lines.append("")
        path = os.path.join(out_dir, f"{ns}.py")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        written.append(path)
        all_modules.append(ns)
    all_modules.sort()

    init_lines = ['"""Generated pyspark-style wrapper namespace — do not edit.',
                  "",
                  "``synapseml_tpu.compat.<ns>`` mirrors the reference's",
                  "``synapse.ml.<ns>`` Python modules (camelCase setters/getters,",
                  "chaining). Regenerate with ``python -m synapseml_tpu.codegen``.",
                  '"""', "",
                  "import importlib", ""]
    init_lines.append("_MODULES = %r" % (all_modules,))
    init_lines.append('''

_REGISTRY = None


def wrapper_for(stage_cls):
    """The generated wrapper class for a native stage class, or None."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = {}
        for ns in _MODULES:
            mod = importlib.import_module(f"{__name__}.{ns}")
            for name in dir(mod):
                obj = getattr(mod, name)
                if isinstance(obj, type) and getattr(obj, "_target", ""):
                    _REGISTRY[obj._target] = obj
    full = f"{stage_cls.__module__}.{stage_cls.__name__}"
    return _REGISTRY.get(full)
''')
    init_path = os.path.join(out_dir, "__init__.py")
    with open(init_path, "w") as f:
        f.write("\n".join(init_lines))
    written.append(init_path)
    return written

