"""Streaming adapters: corpus-scale explanation runs on the scoring plane.

``TabularSHAP(model).transform_source(source, sink)`` routes here
(``LocalExplainerBase.transform_source``): the run IS a PR-11 bulk-scoring
scan — reader→compute→writer bounded queues, exactly-once DONE-gated sink
parts, resume that skips completed shards, per-row quarantine — with the
explainer as the scored stage and the ``synapseml_rai_*`` series layered on
top of ``synapseml_scoring_*``. Because every explanation is keyed on
(seed, row content) (``explainers.row_rng``) and fused batches never leak
across rows, a killed run resumed mid-corpus produces byte-identical sink
parts — the scoring plane's kill/resume contract holds for explanations.

Progress rides a sink proxy: each shard COMMIT (the DONE marker) updates
``synapseml_rai_progress_pct`` from rows written vs the source's row
estimate, so a nightly explanation job is observable at the explanation
granularity without waiting for the final report.
"""

from __future__ import annotations

import time

from .metrics import rai_measures

__all__ = ["explain_source"]


class _ProgressSink:
    """Transparent ScoreSink proxy: counts rows as shards COMMIT and feeds
    the rai progress gauge; every other attribute delegates to the wrapped
    sink (same part layout, same resume semantics)."""

    def __init__(self, sink, explainer_name: str, total_rows):
        self._sink = sink
        self._name = explainer_name
        self._total = total_rows
        self._rows = 0

    def __getattr__(self, attr):
        return getattr(self._sink, attr)

    def begin_shard(self, *args, **kwargs):
        part = self._sink.begin_shard(*args, **kwargs)
        proxy = self
        orig_finish = part.finish

        def finish(meta=None):
            record = orig_finish(meta)
            proxy._rows += int(record.get("rows", 0))
            if proxy._total:
                rai_measures()["progress"].set(
                    min(100.0 * proxy._rows / max(proxy._total, 1), 100.0),
                    explainer=proxy._name)
            return record

        part.finish = finish
        return part


def explain_source(explainer, source, sink, **opts):
    """Explain every row of ``source`` into ``sink`` — the scoring plane's
    ``transform_source`` with the ``synapseml_rai_*`` series recorded
    around it. Returns the scoring plane's ``ScoringReport``."""
    from ..scoring.runner import transform_source

    name = type(explainer).__name__
    m = rai_measures()
    try:
        total = source.estimate_rows(read_fallback=False)
    except Exception:  # noqa: BLE001 — progress is best-effort
        total = None
    t0 = time.perf_counter()
    report = transform_source(explainer, source,
                              _ProgressSink(sink, name, total), **opts)
    wall = max(time.perf_counter() - t0, 1e-9)
    S = int(explainer.get("num_samples") or 0)
    m["explanations_per_sec"].set(report.rows_written / wall, explainer=name)
    m["perturbations_per_sec"].set(report.rows_written * S / wall,
                                   explainer=name)
    m["progress"].set(100.0 if report.complete else
                      min(100.0 * report.shards_done /
                          max(report.shards_assigned, 1), 100.0),
                      explainer=name)
    return report
