"""Fused perturbation engine — the rai plane's compute core.

A SHAP coalition, a LIME neighborhood and an ICE grid clone are all the same
workload: N perturbed forward passes of ONE model ("same program, different
data" — the HFTA observation, arXiv:2102.02344). The seed explainers score
them row-at-a-time through ``model.transform`` (a Python/DataFrame round
trip per explained row); this engine concatenates EVERY row's perturbations
into mega-batches, pads them to the process bucket ladder, and scores them
through the explained model's own score fn acquired via the shared
``core/batching.CompiledCache`` — the PR-7 fused-trial discipline applied to
perturbations. Compile count is bounded by the ladder (one executable per
rung per (model instance, feature shape, dtype)), proved by the
``CompiledCache.miss_count`` acceptance surface, never by the corpus size.

The score-fn protocol: a stage opts into fusion by exposing
``score_fn() -> callable`` returning a pure jax-traceable function
``fn(X: [B, ...]) -> [B, T]`` over the SAME feature layout its ``transform``
consumes, plus (for columnar stages, the ICE path) ``score_cols`` naming the
column order ``X`` is assembled in. Models without the protocol still fuse
at the batching level: all rows' perturbations go through ONE
``_score_samples`` call per ladder-capped chunk instead of one per row.

Everything here is deterministic given the explainer's (seed, row content)
rng — results never depend on which rows share a fused batch (padding is
sliced back off before any per-row solve), which is what makes streamed
explanation runs resumable byte-identically.
"""

from __future__ import annotations

import numpy as np

from ..core.batching import (
    default_bucketer,
    get_compiled_cache,
    instance_token,
    pad_rows,
)
from .metrics import rai_measures

__all__ = ["array_score_fn", "fused_array_scores", "fused_block_scores",
           "fused_columnar_scores", "FUSED_SCORE_FN_ID", "MAX_FUSED_ROWS"]

# the CompiledCache fn_id every fused explainer executable is acquired
# under: miss_count(FUSED_SCORE_FN_ID) is the explainer compile bound
FUSED_SCORE_FN_ID = "rai.fused_score"

# fused mega-batches are capped at the ladder top so peak sample memory is
# bounded by (cap x feature width), not by rows x perturbations
MAX_FUSED_ROWS = 1024


def array_score_fn(model):
    """The model's pure array score fn, or ``None`` when it doesn't expose
    the protocol (``score_fn()`` -> jax-traceable ``fn(X) -> [B, T]``)."""
    getter = getattr(model, "score_fn", None)
    if not callable(getter):
        return None
    try:
        fn = getter()
    except Exception:  # noqa: BLE001 — a broken protocol demotes to serial
        return None
    return fn if callable(fn) else None


def _ladder_scores(explainer, X: np.ndarray, fn) -> np.ndarray:
    """Score ``X`` [N, ...] through ``fn`` in bucket-ladder chunks; one
    executable per rung via the shared CompiledCache. Returns the
    target-selected [N, T] float64 scores (same selection rule as the
    serial ``_score_samples`` — parity depends on it)."""
    model = explainer.get("model")
    cache = get_compiled_cache()
    bucketer = default_bucketer()
    name = type(explainer).__name__
    n = X.shape[0]
    out = None
    valid = 0
    padded = 0
    for start, stop, bucket in bucketer.slices(n, max_rows=MAX_FUSED_ROWS):
        chunk = pad_rows(np.ascontiguousarray(X[start:stop]), bucket,
                         mode="edge")

        def build(fn=fn):
            import jax

            return jax.jit(fn)

        exe = cache.get(FUSED_SCORE_FN_ID, (bucket,) + tuple(X.shape[1:]),
                        build, instance=instance_token(model),
                        dtype=str(X.dtype))
        y = np.atleast_2d(np.asarray(exe(chunk), np.float64))
        if y.ndim == 1 or y.shape[0] != chunk.shape[0]:
            y = y.reshape(chunk.shape[0], -1)
        if out is None:
            out = np.empty((n, y.shape[1]), np.float64)
        out[start:stop] = y[: stop - start]
        valid += stop - start
        padded += bucket
    m = rai_measures()
    m["perturbations"].inc(n, explainer=name)
    if padded:
        m["occupancy"].set(valid / padded, explainer=name)
    return out[:, explainer._target_index(out.shape[1])]


def _chunked_transform_scores(explainer, samples, builder) -> np.ndarray:
    """The no-protocol fallback: ONE ``_score_samples`` call per
    ladder-capped chunk (fused across rows, bounded memory) instead of one
    per explained row."""
    n = len(samples)
    name = type(explainer).__name__
    blocks = []
    for start in range(0, n, MAX_FUSED_ROWS):
        chunk = samples[start:start + MAX_FUSED_ROWS]
        blocks.append(explainer._score_samples(builder(chunk)))
    rai_measures()["perturbations"].inc(n, explainer=name)
    return np.concatenate(blocks, axis=0) if blocks else \
        np.empty((0, 1), np.float64)


def fused_array_scores(explainer, X: np.ndarray,
                       builder=None) -> np.ndarray:
    """[N, ...] perturbation samples -> [N, T] scores, fused.

    Uses the model's score-fn protocol when available (ladder-bucketed
    CompiledCache executables); otherwise falls back to ladder-capped
    chunks through ``builder`` + ``_score_samples`` (``builder`` defaults
    to a single-column DataFrame over the explainer's ``input_col``)."""
    fn = array_score_fn(explainer.get("model"))
    if fn is not None:
        return _ladder_scores(explainer, X, fn)
    if builder is None:
        from ..core.dataframe import DataFrame

        col = explainer.get("input_col")
        builder = lambda chunk: DataFrame.from_dict({col: chunk})  # noqa: E731
    return _chunked_transform_scores(explainer, X, builder)


def fused_block_scores(explainer, blocks: list, builder) -> list:
    """Per-row sample blocks -> per-row score arrays, scored together.

    ``blocks`` holds one samples payload per explained row (an ndarray
    [S, ...] or a list, e.g. text variants). Blocks with a common payload
    shape are concatenated into one mega-batch — ndarrays ride the
    score-fn/ladder path via :func:`fused_array_scores`, ragged or
    non-array payloads ride the chunked-transform fallback — then split
    back per row, so results are identical to scoring each row alone."""
    groups: dict = {}                     # signature -> [block indices]
    for i, b in enumerate(blocks):
        sig = (("nd",) + tuple(np.asarray(b).shape[1:])
               if isinstance(b, np.ndarray) else ("raw",))
        groups.setdefault(sig, []).append(i)
    out: list = [None] * len(blocks)
    for sig, idxs in groups.items():
        counts = [len(blocks[i]) for i in idxs]
        if sig[0] == "nd":
            cat = np.concatenate([blocks[i] for i in idxs], axis=0)
            scores = fused_array_scores(explainer, cat, builder)
        else:
            cat = []
            for i in idxs:
                cat.extend(blocks[i])
            scores = _chunked_transform_scores(explainer, cat, builder)
        offset = 0
        for i, c in zip(idxs, counts):
            out[i] = scores[offset:offset + c]
            offset += c
    return out


def fused_columnar_scores(explainer, cols: dict) -> np.ndarray | None:
    """The ICE path: assemble the model's declared ``score_cols`` from a
    columnar dict and score through the ladder. ``None`` when the model
    doesn't declare a columnar score layout (caller falls back serial)."""
    model = explainer.get("model")
    names = getattr(model, "score_cols", None)
    if not names or array_score_fn(model) is None:
        return None
    try:
        X = np.stack([np.asarray(cols[c], np.float32) for c in names],
                     axis=1)
    except (KeyError, ValueError, TypeError):
        return None
    return fused_array_scores(explainer, X)
