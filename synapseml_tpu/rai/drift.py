"""Per-segment distribution drift of logged traffic vs a reference window.

The audit plane's drift question is "which slice of production traffic no
longer looks like what the model was trained on?" — answered per SEGMENT
(a caller-defined partition of requests: geography, client tier, cohort)
so the retrain trigger can name the drifted slice instead of a corpus-wide
average that washes real drift out.

Measures: PSI (population stability index — the industry drift staple; >0.25
is the conventional "significant shift" line) and Jensen-Shannon divergence,
both over per-feature histograms binned at the REFERENCE window's deciles
(quantile bins make the measures scale-free and robust to outliers). All
vectorized: one ``searchsorted`` per feature over the whole window +
``np.add.at`` scatter per segment — no per-row Python on the hot path.
"""

from __future__ import annotations

import numpy as np

__all__ = ["psi", "js_divergence", "reference_bins", "segment_drift"]

_EPS = 1e-6


def reference_bins(reference: np.ndarray, bins: int = 10) -> list[np.ndarray]:
    """Per-feature interior bin edges at the reference quantiles.

    ``reference`` is [n_ref, M]; returns M edge arrays (deduplicated, so a
    constant feature yields zero edges = one bin)."""
    X = np.asarray(reference, np.float64)
    if X.ndim == 1:
        X = X[:, None]
    qs = np.linspace(0.0, 1.0, max(int(bins), 2) + 1)[1:-1]
    return [np.unique(np.quantile(X[:, j], qs)) for j in range(X.shape[1])]


def _fractions(X: np.ndarray, edges: list[np.ndarray]) -> np.ndarray:
    """[M, max_bins] bin-fraction table of ``X`` under ``edges``."""
    M = len(edges)
    width = max((len(e) + 1 for e in edges), default=1)
    out = np.zeros((M, width), np.float64)
    n = X.shape[0]
    for j, e in enumerate(edges):
        idx = np.searchsorted(e, X[:, j], side="right")
        counts = np.bincount(idx, minlength=len(e) + 1)
        out[j, : len(e) + 1] = counts / max(n, 1)
    return out


def psi(p: np.ndarray, q: np.ndarray) -> float:
    """Population stability index between two fraction vectors/tables."""
    p = np.asarray(p, np.float64) + _EPS
    q = np.asarray(q, np.float64) + _EPS
    p = p / p.sum(axis=-1, keepdims=True)
    q = q / q.sum(axis=-1, keepdims=True)
    return float(np.sum((p - q) * np.log(p / q), axis=-1).max())


def js_divergence(p: np.ndarray, q: np.ndarray) -> float:
    """Jensen-Shannon divergence (base e) between fraction vectors/tables;
    returns the max over leading rows like :func:`psi`."""
    p = np.asarray(p, np.float64) + _EPS
    q = np.asarray(q, np.float64) + _EPS
    p = p / p.sum(axis=-1, keepdims=True)
    q = q / q.sum(axis=-1, keepdims=True)
    m = 0.5 * (p + q)
    kl_pm = np.sum(p * np.log(p / m), axis=-1)
    kl_qm = np.sum(q * np.log(q / m), axis=-1)
    return float((0.5 * (kl_pm + kl_qm)).max())


def segment_drift(reference: np.ndarray, X: np.ndarray,
                  segments, bins: int = 10,
                  metric: str = "psi") -> dict[str, dict]:
    """Drift of each traffic segment vs the reference window.

    ``reference`` [n_ref, M] is the training/healthy window; ``X`` [n, M]
    the audited traffic; ``segments`` a length-n sequence of segment keys.
    Returns ``{segment: {"drift": <max over features>, "per_feature": [...],
    "rows": n_seg}}`` under the chosen metric (``psi`` | ``js``)."""
    ref = np.asarray(reference, np.float64)
    if ref.ndim == 1:
        ref = ref[:, None]
    W = np.asarray(X, np.float64)
    if W.ndim == 1:
        W = W[:, None]
    if W.shape[1] != ref.shape[1]:
        raise ValueError(f"window has {W.shape[1]} features, reference "
                         f"has {ref.shape[1]}")
    measure = {"psi": psi, "js": js_divergence}[metric]
    edges = reference_bins(ref, bins)
    ref_frac = _fractions(ref, edges)
    keys = np.asarray([str(s) for s in segments], dtype=object)
    out: dict[str, dict] = {}
    for seg in sorted(set(keys.tolist())):
        rows = W[keys == seg]
        frac = _fractions(rows, edges)
        per_feature = [measure(frac[j], ref_frac[j])
                       for j in range(ref.shape[1])]
        out[seg] = {"drift": float(max(per_feature)),
                    "per_feature": [float(v) for v in per_feature],
                    "rows": int(rows.shape[0])}
    return out
