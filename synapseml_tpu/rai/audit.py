"""Nightly audit jobs over logged production traffic.

An :class:`AuditJob` closes the responsible-AI loop the way the continual
plane closed the training loop: it reads the SAME DONE-committed
``RequestLogger`` shards the retrainer consumes, runs the audit battery —
per-segment distribution drift vs a reference window (:mod:`.drift`),
``FeatureBalanceMeasure`` label-parity gaps across segments, isolation-forest
anomaly rates, and (optionally) fused exemplar explanations of the most
drifted slice — and publishes the result as a REGISTRY ARTIFACT: a
content-addressed, signed version of ``<model>-audit`` whose tree carries
the manifest (model version, traffic window, metric tables) under
``audit/``.

The artifact is not just a report. ``run_once`` feeds the per-segment
numbers into the ``synapseml_rai_segment_drift`` gauge and annotates the
gauge with the artifact ref (``continual.annotate_drift_gauge``), so when
``ContinualLoop.should_run`` fires on that gauge the retrain record's
trigger reason names the exact audit that justified it — "the model
drifted on segment X, evidence: <model>-audit:v7" instead of a bare
number.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
import time
from typing import Callable, Sequence

import numpy as np

from ..continual.logger import _DONE_SUFFIX, _PART_PREFIX
from ..continual.loop import _tolerant_rows, annotate_drift_gauge
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Transformer
from .drift import segment_drift
from .metrics import DRIFT_GAUGE, rai_measures

__all__ = ["AuditSpec", "AuditJob", "AuditReport",
           "default_feature_fn", "default_segment_fn"]


def default_feature_fn(record: dict) -> Sequence[float]:
    """Logged record → feature vector: the request body's ``x`` (the same
    convention as ``continual.default_row_fn``)."""
    return record["body"]["x"]


def default_segment_fn(record: dict) -> str:
    """Logged record → segment key: the request path (one segment per
    served route). Real deployments pass a cohort/geo/tier extractor."""
    return str(record.get("path", "/"))


class AuditReport(Transformer):
    """The stage INSIDE a published audit artifact.

    Publishing requires a stage; the report stage carries the audit summary
    as params so ``registry.resolve(...)`` round-trips it like any model,
    while the full metric tables ride the artifact tree under ``audit/``.
    Its transform is identity — an audit artifact scores nothing."""

    feature_name = "rai"

    model_name = Param("model_name", "audited model name", default="")
    model_version = Param("model_version", "audited model version at audit "
                          "time", default="")
    window = ComplexParam("window", "traffic window summary (parts, rows, "
                          "ts range)", default=None)
    summary = ComplexParam("summary", "flat audit metric summary",
                           default=None)

    def _transform(self, df: DataFrame) -> DataFrame:
        return df


@dataclasses.dataclass
class AuditSpec:
    """One audit's declarative config.

    ``reference`` is the healthy/training feature window [n_ref, M] the
    traffic is compared against; ``segment_fn``/``feature_fn``/``label_fn``
    map a logged request record to its segment key, feature vector, and
    (optional) binary label for the balance measures."""

    model: str
    reference: np.ndarray
    feature_fn: Callable[[dict], Sequence[float]] = default_feature_fn
    segment_fn: Callable[[dict], str] = default_segment_fn
    label_fn: Callable[[dict], object] | None = None
    drift_gauge: str = DRIFT_GAUGE
    drift_metric: str = "psi"
    drift_bins: int = 10
    alias: str = "prod"
    artifact: str | None = None        # default: f"{model}-audit"
    anomaly_trees: int = 0             # 0 disables the isolation-forest pass
    anomaly_seed: int = 0
    explainer: object | None = None    # optional LocalExplainerBase
    explain_rows: int = 8              # exemplars from the worst segment

    @property
    def artifact_name(self) -> str:
        return self.artifact or f"{self.model}-audit"


class AuditJob:
    """Run the audit battery over a ``RequestLogger`` directory and publish
    the result as a registry artifact; see the module docstring for the
    flywheel contract."""

    def __init__(self, spec: AuditSpec, registry, log_dir: str):
        self.spec = spec
        self.registry = registry
        self.log_dir = log_dir

    # -- traffic window ------------------------------------------------------
    def _committed_parts(self) -> list[str]:
        try:
            names = sorted(os.listdir(self.log_dir))
        except OSError:
            return []
        return [n for n in names
                if n.startswith(_PART_PREFIX) and n.endswith(".jsonl")
                and os.path.exists(os.path.join(self.log_dir,
                                                n + _DONE_SUFFIX))]

    def _collect_window(self, parts: list[str]):
        """(features [n, M], segments, labels|None, ts range, quarantined)."""
        feats: list[np.ndarray] = []
        segs: list[str] = []
        labels: list = []
        ts_lo = ts_hi = None
        quarantined = 0
        for name in parts:
            for record in _tolerant_rows(os.path.join(self.log_dir, name)):
                if record is None:
                    quarantined += 1
                    continue
                try:
                    x = np.asarray(self.spec.feature_fn(record), np.float64)
                    seg = str(self.spec.segment_fn(record))
                except Exception:  # noqa: BLE001 — poisoned row, not job
                    quarantined += 1
                    continue
                if x.ndim != 1 or not np.all(np.isfinite(x)):
                    quarantined += 1
                    continue
                feats.append(x)
                segs.append(seg)
                if self.spec.label_fn is not None:
                    try:
                        labels.append(self.spec.label_fn(record))
                    except Exception:  # noqa: BLE001
                        labels.append(None)
                ts = record.get("ts")
                if isinstance(ts, (int, float)):
                    ts_lo = ts if ts_lo is None else min(ts_lo, ts)
                    ts_hi = ts if ts_hi is None else max(ts_hi, ts)
        X = (np.stack(feats) if feats
             else np.zeros((0, np.asarray(self.spec.reference).shape[-1])))
        y = labels if self.spec.label_fn is not None else None
        return X, segs, y, (ts_lo, ts_hi), quarantined

    # -- audit passes --------------------------------------------------------
    def _balance_table(self, segments: list[str], labels) -> list[dict]:
        """Per-(segmentA, segmentB) label-parity gaps via the exploratory
        plane's ``FeatureBalanceMeasure`` (sensitive col = segment)."""
        from ..exploratory.balance import FeatureBalanceMeasure

        pairs = [(s, l) for s, l in zip(segments, labels)
                 if l is not None]
        if not pairs:
            return []
        df = DataFrame.from_dict({
            "segment": [s for s, _ in pairs],
            "label": [int(bool(l)) for _, l in pairs],
        })
        out = FeatureBalanceMeasure(sensitive_cols=["segment"],
                                    label_col="label").transform(df)
        cols = {c: out.collect_column(c) for c in out.columns}
        n = len(cols.get("ClassA", []))
        return [{k: (v[i].item() if hasattr(v[i], "item") else v[i])
                 for k, v in cols.items()} for i in range(n)]

    def _anomaly_rates(self, X: np.ndarray,
                       segments: list[str]) -> dict | None:
        """Isolation forest fit on the REFERENCE window, scored on the
        traffic: per-segment mean anomaly score + overall anomalous rate."""
        if self.spec.anomaly_trees <= 0 or not len(X):
            return None
        from ..isolationforest.iforest import IsolationForest

        ref = np.asarray(self.spec.reference, np.float64)
        fit_df = DataFrame.from_dict({"features": [r for r in ref]})
        model = IsolationForest(
            num_estimators=self.spec.anomaly_trees,
            random_seed=self.spec.anomaly_seed).fit(fit_df)
        scores = model._scores(np.asarray(X, np.float64))
        thr = float(model.get("threshold"))
        keys = np.asarray(segments, dtype=object)
        per_segment = {
            seg: float(scores[keys == seg].mean())
            for seg in sorted(set(segments))
        }
        return {"rate": float((scores >= thr).mean()),
                "mean_score": float(scores.mean()),
                "per_segment": per_segment}

    def _exemplars(self, X: np.ndarray, segments: list[str],
                   worst: str | None) -> list | None:
        """Fused explanations of up to ``explain_rows`` rows from the most
        drifted segment — the artifact shows WHICH features drive the
        drifted slice's predictions, not just that the slice drifted."""
        exp = self.spec.explainer
        if exp is None or worst is None or not len(X):
            return None
        try:
            keys = np.asarray(segments, dtype=object)
            rows = X[keys == worst][: max(self.spec.explain_rows, 1)]
            col = exp.get("input_col")
            df = DataFrame.from_dict(
                {col: [np.asarray(r, np.float32) for r in rows]})
            out = exp.transform(df)
            return [np.asarray(e, np.float64).tolist()
                    for e in out.collect_column(exp.get("output_col"))]
        except Exception:  # noqa: BLE001 — exemplars are best-effort
            return None

    # -- run -----------------------------------------------------------------
    def run_once(self) -> dict:
        """One audit: collect the committed window, run the battery, publish
        the artifact, feed the drift gauges, annotate the trigger."""
        spec = self.spec
        m = rai_measures()
        t0 = time.perf_counter()
        parts = self._committed_parts()
        X, segments, labels, (ts_lo, ts_hi), quarantined = \
            self._collect_window(parts)
        if not len(X):
            m["audit_runs"].inc(1, model=spec.model, status="empty")
            return {"status": "empty", "rows": 0, "parts": parts,
                    "quarantined": quarantined}

        drift = segment_drift(spec.reference, X, segments,
                              bins=spec.drift_bins, metric=spec.drift_metric)
        worst = max(drift, key=lambda s: drift[s]["drift"])
        balance = (self._balance_table(segments, labels)
                   if labels is not None else [])
        anomaly = self._anomaly_rates(X, segments)
        exemplars = self._exemplars(X, segments, worst)

        try:
            model_version = (self.registry.resolve_ref(spec.model, spec.alias)
                             if spec.alias else "")
        except (KeyError, RuntimeError):
            model_version = ""
        window = {"parts": parts, "rows": int(len(X)),
                  "quarantined": int(quarantined),
                  "ts_first": ts_lo, "ts_last": ts_hi}
        metrics = {
            "rows": float(len(X)),
            "segments": float(len(drift)),
            "max_segment_drift": drift[worst]["drift"],
            "quarantined": float(quarantined),
        }
        if anomaly is not None:
            metrics["anomaly_rate"] = anomaly["rate"]
        if balance:
            metrics["max_abs_dp_gap"] = max(abs(r.get("dp", 0.0))
                                            for r in balance)
        summary = dict(metrics, worst_segment=worst,
                       drift_metric=spec.drift_metric)

        report = AuditReport(model_name=spec.model,
                             model_version=model_version,
                             window=window, summary=summary)
        tree = tempfile.mkdtemp(prefix="rai-audit-")
        try:
            audit_dir = os.path.join(tree, "audit")
            os.makedirs(audit_dir)
            manifest = {"model": spec.model, "model_version": model_version,
                        "alias": spec.alias, "window": window,
                        "drift_gauge": spec.drift_gauge,
                        "drift_metric": spec.drift_metric,
                        "drift_bins": spec.drift_bins, "metrics": metrics,
                        "worst_segment": worst}
            with open(os.path.join(audit_dir, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
            with open(os.path.join(audit_dir, "segment_drift.json"),
                      "w") as f:
                json.dump(drift, f, indent=1, sort_keys=True)
            if balance:
                with open(os.path.join(audit_dir, "balance.jsonl"),
                          "w") as f:
                    for row in balance:
                        f.write(json.dumps(row) + "\n")
            if anomaly is not None:
                with open(os.path.join(audit_dir, "anomaly.json"), "w") as f:
                    json.dump(anomaly, f, indent=1, sort_keys=True)
            if exemplars is not None:
                with open(os.path.join(audit_dir, "explanations.json"),
                          "w") as f:
                    json.dump({"segment": worst,
                               "attributions": exemplars}, f)
            published = self.registry.publish(
                spec.artifact_name, report, metrics=metrics,
                extra={"kind": "rai_audit", "model": spec.model,
                       "model_version": model_version},
                extra_tree=tree)
        finally:
            shutil.rmtree(tree, ignore_errors=True)

        for seg, d in drift.items():
            m["segment_drift"].set(d["drift"], model=spec.model, segment=seg)
        artifact_ref = f"{published.name}:{published.version}"
        if spec.drift_gauge:
            annotate_drift_gauge(spec.drift_gauge, artifact_ref)
        m["audit_runs"].inc(1, model=spec.model, status="ok")
        m["audit_ms"].observe((time.perf_counter() - t0) * 1e3,
                              model=spec.model)
        return {"status": "ok", "artifact": artifact_ref,
                "rows": int(len(X)), "parts": parts,
                "quarantined": int(quarantined),
                "worst_segment": worst, "drift": drift, "metrics": metrics}
