"""The ``synapseml_rai_*`` metric series (PR-2 observability plane).

One :class:`~synapseml_tpu.core.observability.HandleCache` per plane is the
repo-wide discipline (``_SCORING_METRICS``, ``_LOOP_METRICS``, ...); the rai
plane's series cover the three workloads it owns:

- fused explanation (``explanations``/``perturbations`` counters + the
  per-run rate gauges and the fused-batch ``occupancy`` gauge — valid rows
  over padded rows, the ladder's wasted-compute fraction);
- streamed explanation runs (``progress`` — mirrors the scoring plane's
  per-shard progress at the explanation granularity);
- audit jobs (``audit_runs`` counter by outcome, ``audit_ms`` wall
  histogram, and the ``segment_drift`` gauge that
  ``ContinualLoop.drift_gauge`` watches — one labeled series per
  (model, segment), max over segments drives the retrain trigger).

See docs/RAI.md for the full series table.
"""

from __future__ import annotations

from ..core import observability as obs

__all__ = ["rai_measures", "DRIFT_GAUGE"]

# the default gauge name AuditJob publishes per-segment drift under; pass it
# as ``ContinualSpec.drift_gauge`` to close the audit -> retrain loop
DRIFT_GAUGE = "synapseml_rai_segment_drift"

_RAI_METRICS = obs.HandleCache(lambda reg: {
    "explanations": reg.counter(
        "synapseml_rai_explanations_total",
        "rows explained (one explanation vector per row per target)",
        ("explainer",)),
    "perturbations": reg.counter(
        "synapseml_rai_perturbations_total",
        "perturbed samples scored through the explained model",
        ("explainer",)),
    "explanations_per_sec": reg.gauge(
        "synapseml_rai_explanations_per_sec",
        "explanation throughput of the last streamed run", ("explainer",)),
    "perturbations_per_sec": reg.gauge(
        "synapseml_rai_perturbations_per_sec",
        "perturbation scoring throughput of the last streamed run",
        ("explainer",)),
    "occupancy": reg.gauge(
        "synapseml_rai_fused_occupancy",
        "valid rows / padded rows across fused score batches (1.0 = no "
        "ladder padding waste)", ("explainer",)),
    "progress": reg.gauge(
        "synapseml_rai_progress_pct",
        "streamed explanation run progress (rows written / estimated rows)",
        ("explainer",)),
    "audit_runs": reg.counter(
        "synapseml_rai_audit_runs_total",
        "audit job iterations by outcome", ("model", "status")),
    "audit_ms": reg.histogram(
        "synapseml_rai_audit_ms",
        "wall time of one full audit job iteration", ("model",)),
    "segment_drift": reg.gauge(
        DRIFT_GAUGE,
        "per-segment drift (PSI) of logged traffic vs the reference window",
        ("model", "segment")),
})


def rai_measures() -> dict:
    """The rai plane's metric handles (registry-swap-safe memo)."""
    return _RAI_METRICS.get()
