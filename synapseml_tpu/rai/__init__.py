"""Responsible-AI audit plane: fused corpus-scale explainers, streamed
data-balance/drift audits, and flywheel-triggering audit artifacts.

The plane composes existing seams instead of inventing new ones:

* **fused explanation** (:mod:`.fused`) — the perturbation batches every
  local explainer generates (SHAP coalitions, LIME neighborhoods, ICE
  grids) score through ONE ladder-bucketed executable per rung of the
  shared ``core.batching.CompiledCache`` instead of a Python loop per row:
  a model opts in by exposing ``score_fn()`` (a pure jax array fn), and the
  compile bill for an entire corpus-scale run is bounded by the bucket
  ladder, provable from the cache's miss counters;
* **streamed runs** (:mod:`.stream`) — ``explainer.transform_source(source,
  sink)`` IS a scoring-plane bulk scan (exactly-once DONE-gated sink parts,
  resume, quarantine); content-keyed per-row rngs
  (``explainers.row_rng``) make a killed-and-resumed run byte-identical;
* **audits** (:mod:`.audit`, :mod:`.drift`) — :class:`AuditJob` replays the
  continual plane's DONE-committed request log through per-segment drift
  (PSI/JS vs a reference window), ``FeatureBalanceMeasure`` parity gaps,
  isolation-forest anomaly rates, and exemplar explanations, publishing the
  result as a content-addressed registry artifact;
* **flywheel** — the audit feeds ``synapseml_rai_segment_drift`` and
  annotates the gauge with its artifact ref, so a ``ContinualLoop`` watching
  that gauge retrains WITH the evidence in its trigger reason;
* **observe** (:mod:`.metrics`) — the ``synapseml_rai_*`` series.

Submodules import lazily (PEP 562) so ``explainers/`` can consult the
fused engine without dragging the registry/continual planes into every
explainer import.
"""

from __future__ import annotations

import importlib

__all__ = [
    "array_score_fn", "fused_array_scores", "fused_block_scores",
    "fused_columnar_scores", "FUSED_SCORE_FN_ID", "MAX_FUSED_ROWS",
    "explain_source",
    "psi", "js_divergence", "reference_bins", "segment_drift",
    "AuditSpec", "AuditJob", "AuditReport",
    "default_feature_fn", "default_segment_fn",
    "rai_measures", "DRIFT_GAUGE",
]

_LOCATIONS = {
    "array_score_fn": "fused", "fused_array_scores": "fused",
    "fused_block_scores": "fused", "fused_columnar_scores": "fused",
    "FUSED_SCORE_FN_ID": "fused", "MAX_FUSED_ROWS": "fused",
    "explain_source": "stream",
    "psi": "drift", "js_divergence": "drift", "reference_bins": "drift",
    "segment_drift": "drift",
    "AuditSpec": "audit", "AuditJob": "audit", "AuditReport": "audit",
    "default_feature_fn": "audit", "default_segment_fn": "audit",
    "rai_measures": "metrics", "DRIFT_GAUGE": "metrics",
}


def __getattr__(name: str):
    submodule = _LOCATIONS.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(f".{submodule}", __name__), name)
    globals()[name] = value  # cache: one import, stable identity
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
