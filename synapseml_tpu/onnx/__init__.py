"""ONNX inference on TPU — model import, conversion to XLA, batch transform.

Reference module: ``deep-learning/src/main/scala/.../onnx/`` (ONNXModel,
ONNXHub, ImageFeaturizer — SURVEY.md §2.3). The ONNX Runtime JNI session is
replaced by a protobuf decode (:mod:`proto`, no onnx package needed) + an
ONNX->JAX conversion (:mod:`convert`) whose output XLA compiles straight into
TPU executables.
"""

from .convert import ConvertedModel, convert_graph
from .featurizer import ImageFeaturizer
from .hub import ONNXHub
from .model import ONNXModel, slice_model_at_outputs
from .proto import (
    AttributeProto,
    GraphProto,
    ModelProto,
    NodeProto,
    OperatorSetId,
    TensorProto,
    ValueInfoProto,
    encode_model,
    numpy_to_tensor,
    parse_model,
    tensor_to_numpy,
)

__all__ = [
    "ONNXModel", "ONNXHub", "ImageFeaturizer", "ConvertedModel", "convert_graph",
    "slice_model_at_outputs", "ModelProto", "GraphProto", "NodeProto",
    "TensorProto", "AttributeProto", "ValueInfoProto", "OperatorSetId",
    "parse_model", "encode_model", "numpy_to_tensor", "tensor_to_numpy",
]
