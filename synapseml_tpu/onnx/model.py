"""ONNXModel — distributed batch inference transformer, XLA-resident.

Reference: ``deep-learning/.../onnx/ONNXModel.scala:145-423`` (transform:211,
transformInner:230-256, softmax/argmax post-cols :258-301, model slicing via
``ONNXUtils.sliceModelAtOutputs:267-352``) and ``ONNXRuntime.scala:25-107``.

TPU-native shape of the same pipeline (SURVEY.md §3.3 "TPU rebuild" note):
  * model bytes -> :class:`~synapseml_tpu.onnx.convert.ConvertedModel` once
    (broadcast analog: the converted fn is shared across partitions);
  * per-partition OrtSession -> ONE jitted XLA executable, cached per input
    shape signature;
  * FixedMiniBatch(10) + dynamic batches -> fixed-size padded microbatches so
    every batch hits the SAME compiled program (static shapes, no recompiles);
  * softMaxDict / argMaxDict post-processing fused into the same jit.
"""

from __future__ import annotations

import numpy as np

from ..core import batching as cb
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer
from .convert import ConvertedModel
from .proto import GraphProto, ModelProto, ValueInfoProto, parse_model

__all__ = ["ONNXModel", "slice_model_at_outputs"]


def slice_model_at_outputs(model_bytes: bytes, output_names: list[str]) -> bytes:
    """Cut the graph at (possibly intermediate) values — the reference's
    protobuf surgery (``ONNXUtils.sliceModelAtOutputs:267-352``): keep only
    nodes/initializers reachable backwards from ``output_names``."""
    m = parse_model(model_bytes)
    g = m.graph
    produced_by = {}
    for n in g.node:
        for o in n.output:
            produced_by[o] = n
    needed_values: set[str] = set()
    needed_nodes: list = []
    seen_nodes: set[int] = set()
    stack = list(output_names)
    while stack:
        v = stack.pop()
        if v in needed_values:
            continue
        needed_values.add(v)
        n = produced_by.get(v)
        if n is not None and id(n) not in seen_nodes:
            seen_nodes.add(id(n))
            needed_nodes.append(n)
            stack.extend([i for i in n.input if i])
    ordered = [n for n in g.node if id(n) in seen_nodes]
    known = {vi.name: vi for vi in list(g.output) + list(g.value_info) + list(g.input)}
    new_outputs = [known.get(name, ValueInfoProto(name=name)) for name in output_names]
    init_names = {t.name for t in g.initializer}
    new_graph = GraphProto(
        node=ordered,
        name=g.name + "_sliced",
        initializer=[t for t in g.initializer if t.name in needed_values],
        input=[vi for vi in g.input
               if vi.name in needed_values and vi.name not in init_names],
        output=new_outputs,
        value_info=g.value_info,
    )
    return ModelProto(ir_version=m.ir_version, producer_name=m.producer_name,
                      graph=new_graph, opset_import=m.opset_import).encode()


class ONNXModel(Transformer):
    """(ref ``ONNXModel.scala:145``)"""

    feature_name = "onnx"

    model_payload = ComplexParam("model_payload", "ONNX model protobuf bytes")
    feed_dict = ComplexParam("feed_dict", "model input name -> DataFrame column",
                             default=None)
    fetch_dict = ComplexParam("fetch_dict", "output column -> model output name",
                              default=None)
    mini_batch_size = Param("mini_batch_size", "rows per padded device batch",
                            default=64, converter=TypeConverters.to_int)
    softmax_dict = ComplexParam("softmax_dict", "input col -> softmax output col",
                                default=None)
    argmax_dict = ComplexParam("argmax_dict", "input col -> argmax output col",
                               default=None)

    def __init__(self, model_bytes: bytes | None = None, **kw):
        super().__init__(**kw)
        if model_bytes is not None:
            self.set(model_payload=model_bytes)

    # NOTE: stage deserialization constructs via cls.__new__ (serialization
    # .load_stage:168), bypassing __init__ — runtime caches therefore live
    # behind lazy accessors, never as __init__-assigned attributes. Jitted
    # programs live in the process-wide CompiledCache keyed by this stage's
    # instance_token, not in a private per-stage dict.

    # -------- model management --------
    def set_model_location(self, path: str) -> "ONNXModel":
        with open(path, "rb") as f:
            return self.set(model_payload=f.read())

    def slice_at_outputs(self, output_names: list[str]) -> "ONNXModel":
        """Re-target the model at intermediate outputs (headless featurization,
        ref ``ONNXModel.setSliceAtOutputs`` / ImageFeaturizer ``extraPorts``)."""
        self.set(model_payload=slice_model_at_outputs(self.get("model_payload"),
                                                      list(output_names)))
        self.__dict__.pop("_cache_converted", None)
        cb.invalidate_token(self)  # orphan the old graph's executables
        return self

    @property
    def converted(self) -> ConvertedModel:
        if self.__dict__.get("_cache_converted") is None:
            payload = self.get("model_payload")
            if payload is None:
                raise ValueError("ONNXModel: model_payload not set")
            self.__dict__["_cache_converted"] = ConvertedModel(parse_model(payload))
        return self.__dict__["_cache_converted"]

    @property
    def model_input_names(self) -> list[str]:
        return self.converted.input_names

    @property
    def model_output_names(self) -> list[str]:
        return self.converted.output_names

    # -------- transform --------
    def _resolved_feeds(self) -> dict:
        feeds = self.get("feed_dict")
        if feeds:
            return dict(feeds)
        names = self.model_input_names
        if len(names) == 1:
            return {names[0]: "features"}
        raise ValueError(f"feed_dict required for multi-input model {names}")

    def _resolved_fetches(self) -> dict:
        fetches = self.get("fetch_dict")
        if fetches:
            return dict(fetches)
        return {f"out_{n}" if n in ("", None) else n: n
                for n in self.model_output_names}

    def _jitted(self, feeds: dict, fetches: dict, bucket: int, dtypes: tuple):
        """One jitted program per ladder bucket: model + post softmax/argmax
        cols fused. Acquired through the process-wide CompiledCache so a
        variable request stream compiles at most ladder-many executables."""
        soft = dict(self.get("softmax_dict") or {})
        arg = dict(self.get("argmax_dict") or {})

        def build():
            import jax
            import jax.numpy as jnp

            conv = self.converted
            out_col_of = {v: k for k, v in fetches.items()}

            def fn(*arrays):
                outs = conv(**dict(zip(sorted(feeds), arrays)))
                cols = {out_col_of[name]: val for name, val in outs.items()
                        if name in out_col_of}
                for src, dst in soft.items():
                    cols[dst] = jax.nn.softmax(cols[src], axis=-1)
                for src, dst in arg.items():
                    cols[dst] = jnp.argmax(cols[src], axis=-1).astype(jnp.int32)
                return cols

            return jax.jit(fn)

        key = (tuple(sorted(feeds.items())), tuple(sorted(fetches.items())),
               tuple(sorted(soft.items())), tuple(sorted(arg.items())))
        return cb.get_compiled_cache().get(
            "onnx_model", (bucket,) + key, build,
            instance=cb.instance_token(self), dtype=dtypes)

    def _transform(self, df: DataFrame) -> DataFrame:
        feeds = self._resolved_feeds()
        fetches = self._resolved_fetches()
        self.require_columns(df, *feeds.values())
        B = self.get("mini_batch_size")
        bucketer = cb.default_bucketer()

        soft = dict(self.get("softmax_dict") or {})
        arg = dict(self.get("argmax_dict") or {})
        out_cols = list(fetches) + list(soft.values()) + list(arg.values())

        def per_part(p):
            n = len(next(iter(p.values()))) if p else 0
            if n == 0:
                return None  # placeholders filled from a non-empty partition
            cols_in = {name: np.asarray(np.stack(list(p[col])))
                       if p[col].dtype == object else np.asarray(p[col])
                       for name, col in feeds.items()}
            dtypes = tuple(str(cols_in[k].dtype) for k in sorted(feeds))
            results: dict[str, list] = {}
            for start, stop, bucket in bucketer.slices(n, B):
                # pad to the chunk's ladder bucket -> same compiled program
                # for every request size that maps to this rung (edge-repeat
                # padding, the original fixed-B strategy)
                batch = {k: cb.pad_rows(v[start:stop], bucket, mode="edge")
                         for k, v in cols_in.items()}
                jitted = self._jitted(feeds, fetches, bucket, dtypes)
                out = jitted(*[batch[k] for k in sorted(feeds)])
                for col, val in out.items():
                    arr = cb.unpad_rows(val, stop - start)
                    results.setdefault(col, []).append(arr)
            q = dict(p)
            for col in out_cols:  # deterministic order (jit sorts dict keys)
                chunks = results.get(col, [])
                q[col] = np.concatenate(chunks, axis=0) if chunks else np.empty(0)
            return q

        processed = [per_part(p) for p in df.partitions]
        # empty partitions: placeholder columns with the dtype/trailing shape
        # of a non-empty partition's outputs (schema + dtype stability)
        template = next((q for q in processed if q is not None), None)
        out_parts = []
        for p, q in zip(df.partitions, processed):
            if q is not None:
                out_parts.append(q)
                continue
            q = dict(p)
            for col in out_cols:
                if template is not None:
                    ref = template[col]
                    q[col] = np.empty((0,) + ref.shape[1:], dtype=ref.dtype)
                else:
                    q[col] = np.empty(0)
            out_parts.append(q)
        return DataFrame(out_parts)
