"""Minimal ONNX protobuf wire codec (no `onnx` package dependency).

The reference hands model bytes to ONNX Runtime JNI (``onnx/ONNXModel.scala``)
and does graph surgery over the protobuf for slicing
(``ONNXUtils.sliceModelAtOutputs:267-352``). Here the model bytes are decoded
into plain dataclasses (the subset of onnx.proto the converter needs) with a
hand-rolled varint/length-delimited reader, and re-encoded with the matching
writer (used by graph slicing and by tests constructing models).

Schema: the public, frozen onnx.proto field numbers (onnx/onnx.proto in the
ONNX repo). Only fields the converter consumes are modeled; unknown fields are
skipped on read (forward compatible) and omitted on write.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Iterator

import numpy as np

__all__ = ["ModelProto", "GraphProto", "NodeProto", "TensorProto",
           "AttributeProto", "ValueInfoProto", "OperatorSetId",
           "tensor_to_numpy", "numpy_to_tensor", "parse_model", "encode_model"]

# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------

_WIRE_VARINT, _WIRE_I64, _WIRE_LEN, _WIRE_I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(buf: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message buffer."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            v, pos = _read_varint(buf, pos)
        elif wire == _WIRE_I64:
            v = buf[pos : pos + 8]
            pos += 8
        elif wire == _WIRE_LEN:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos : pos + ln]
            pos += ln
        elif wire == _WIRE_I32:
            v = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire} at {pos}")
        yield field, wire, v


def _write_varint(out: bytearray, v: int) -> None:
    if v < 0:
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _tag(out: bytearray, field: int, wire: int) -> None:
    _write_varint(out, (field << 3) | wire)


def _w_varint_field(out: bytearray, field: int, v: int) -> None:
    _tag(out, field, _WIRE_VARINT)
    _write_varint(out, v)


def _w_bytes_field(out: bytearray, field: int, data: bytes) -> None:
    _tag(out, field, _WIRE_LEN)
    _write_varint(out, len(data))
    out.extend(data)


def _w_str_field(out: bytearray, field: int, s: str) -> None:
    _w_bytes_field(out, field, s.encode("utf-8"))


def _unpack_packed(buf: bytes, fmt: str, size: int) -> list:
    return [struct.unpack_from(f"<{fmt}", buf, i)[0] for i in range(0, len(buf), size)]


def _unpack_packed_varints(buf: bytes) -> list[int]:
    out, pos = [], 0
    while pos < len(buf):
        v, pos = _read_varint(buf, pos)
        out.append(_signed(v))
    return out


# ---------------------------------------------------------------------------
# messages (onnx.proto field numbers)
# ---------------------------------------------------------------------------

# TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL = 1, 2, 3, 4, 5, 6, 7, 8, 9
FLOAT16, DOUBLE, UINT32, UINT64 = 10, 11, 12, 13
BFLOAT16 = 16

_DTYPE_TO_NP = {
    FLOAT: np.float32, UINT8: np.uint8, INT8: np.int8, UINT16: np.uint16,
    INT16: np.int16, INT32: np.int32, INT64: np.int64, BOOL: np.bool_,
    FLOAT16: np.float16, DOUBLE: np.float64, UINT32: np.uint32, UINT64: np.uint64,
}
try:  # bfloat16 is a numpy extension type shipped with jax
    import ml_dtypes as _ml_dtypes

    _DTYPE_TO_NP[BFLOAT16] = _ml_dtypes.bfloat16
except ImportError:  # pragma: no cover
    pass
_NP_TO_DTYPE = {np.dtype(v): k for k, v in _DTYPE_TO_NP.items()}


@dataclasses.dataclass
class TensorProto:
    dims: list = dataclasses.field(default_factory=list)          # field 1
    data_type: int = FLOAT                                        # field 2
    float_data: list = dataclasses.field(default_factory=list)    # field 4
    int32_data: list = dataclasses.field(default_factory=list)    # field 5
    int64_data: list = dataclasses.field(default_factory=list)    # field 7
    name: str = ""                                                # field 8
    raw_data: bytes = b""                                         # field 9
    double_data: list = dataclasses.field(default_factory=list)   # field 10

    @staticmethod
    def parse(buf: bytes) -> "TensorProto":
        t = TensorProto()
        for field, wire, v in _fields(buf):
            if field == 1:
                if wire == _WIRE_LEN:
                    t.dims.extend(_unpack_packed_varints(v))
                else:
                    t.dims.append(_signed(v))
            elif field == 2:
                t.data_type = v
            elif field == 4:
                t.float_data.extend(_unpack_packed(v, "f", 4) if wire == _WIRE_LEN
                                    else [struct.unpack("<f", v)[0]])
            elif field == 5:
                t.int32_data.extend(_unpack_packed_varints(v) if wire == _WIRE_LEN
                                    else [_signed(v)])
            elif field == 7:
                t.int64_data.extend(_unpack_packed_varints(v) if wire == _WIRE_LEN
                                    else [_signed(v)])
            elif field == 8:
                t.name = v.decode("utf-8")
            elif field == 9:
                t.raw_data = bytes(v)
            elif field == 10:
                t.double_data.extend(_unpack_packed(v, "d", 8) if wire == _WIRE_LEN
                                     else [struct.unpack("<d", v)[0]])
        return t

    def encode(self) -> bytes:
        out = bytearray()
        for d in self.dims:
            _w_varint_field(out, 1, d)
        _w_varint_field(out, 2, self.data_type)
        for f in self.float_data:
            _tag(out, 4, _WIRE_I32)
            out.extend(struct.pack("<f", f))
        for i in self.int32_data:
            _w_varint_field(out, 5, i)
        for i in self.int64_data:
            _w_varint_field(out, 7, i)
        if self.name:
            _w_str_field(out, 8, self.name)
        if self.raw_data:
            _w_bytes_field(out, 9, self.raw_data)
        for d in self.double_data:
            _tag(out, 10, _WIRE_I64)
            out.extend(struct.pack("<d", d))
        return bytes(out)


def tensor_to_numpy(t: TensorProto) -> np.ndarray:
    np_dtype = _DTYPE_TO_NP.get(t.data_type)
    if np_dtype is None:
        raise ValueError(f"unsupported tensor data_type {t.data_type} ({t.name})")
    shape = tuple(t.dims)
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=np_dtype)
    elif t.float_data:
        arr = np.asarray(t.float_data, dtype=np_dtype)
    elif t.int64_data:
        arr = np.asarray(t.int64_data, dtype=np_dtype)
    elif t.int32_data:
        if t.data_type in (FLOAT16, BFLOAT16):
            # ONNX stores fp16/bf16 in int32_data as uint16 bit patterns
            arr = np.asarray(t.int32_data, dtype=np.uint16).view(np_dtype)
        else:
            arr = np.asarray(t.int32_data, dtype=np_dtype)
    elif t.double_data:
        arr = np.asarray(t.double_data, dtype=np_dtype)
    else:
        arr = np.zeros(int(np.prod(shape)) if shape else 1, dtype=np_dtype)
    return arr.reshape(shape)


def numpy_to_tensor(arr: np.ndarray, name: str = "") -> TensorProto:
    arr = np.asarray(arr)
    dt = _NP_TO_DTYPE.get(arr.dtype)
    if dt is None:
        raise ValueError(f"unsupported numpy dtype {arr.dtype}")
    return TensorProto(dims=list(arr.shape), data_type=dt, name=name,
                       raw_data=arr.tobytes())


# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR, ATTR_GRAPH = 1, 2, 3, 4, 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


@dataclasses.dataclass
class AttributeProto:
    name: str = ""                                                # 1
    f: float = 0.0                                                # 2
    i: int = 0                                                    # 3
    s: bytes = b""                                                # 4
    t: TensorProto | None = None                                  # 5
    g: "GraphProto | None" = None                                 # 6
    floats: list = dataclasses.field(default_factory=list)        # 7
    ints: list = dataclasses.field(default_factory=list)          # 8
    strings: list = dataclasses.field(default_factory=list)       # 9
    type: int = 0                                                 # 20

    @property
    def value(self):
        if self.type == ATTR_FLOAT:
            return self.f
        if self.type == ATTR_INT:
            return self.i
        if self.type == ATTR_STRING:
            return self.s.decode("utf-8", "replace")
        if self.type == ATTR_TENSOR:
            return tensor_to_numpy(self.t)
        if self.type == ATTR_FLOATS:
            return list(self.floats)
        if self.type == ATTR_INTS:
            return list(self.ints)
        if self.type == ATTR_STRINGS:
            return [s.decode("utf-8", "replace") for s in self.strings]
        if self.type == ATTR_GRAPH:
            return self.g
        return None

    @staticmethod
    def parse(buf: bytes) -> "AttributeProto":
        a = AttributeProto()
        for field, wire, v in _fields(buf):
            if field == 1:
                a.name = v.decode("utf-8")
            elif field == 2:
                a.f = struct.unpack("<f", v)[0]
            elif field == 3:
                a.i = _signed(v)
            elif field == 4:
                a.s = bytes(v)
            elif field == 5:
                a.t = TensorProto.parse(v)
            elif field == 6:
                a.g = GraphProto.parse(v)
            elif field == 7:
                a.floats.extend(_unpack_packed(v, "f", 4) if wire == _WIRE_LEN
                                else [struct.unpack("<f", v)[0]])
            elif field == 8:
                a.ints.extend(_unpack_packed_varints(v) if wire == _WIRE_LEN
                              else [_signed(v)])
            elif field == 9:
                a.strings.append(bytes(v))
            elif field == 20:
                a.type = v
        return a

    def encode(self) -> bytes:
        out = bytearray()
        _w_str_field(out, 1, self.name)
        if self.type == ATTR_FLOAT:
            _tag(out, 2, _WIRE_I32)
            out.extend(struct.pack("<f", self.f))
        elif self.type == ATTR_INT:
            _w_varint_field(out, 3, self.i)
        elif self.type == ATTR_STRING:
            _w_bytes_field(out, 4, self.s)
        elif self.type == ATTR_TENSOR:
            _w_bytes_field(out, 5, self.t.encode())
        elif self.type == ATTR_GRAPH:
            _w_bytes_field(out, 6, self.g.encode())
        elif self.type == ATTR_FLOATS:
            for f in self.floats:
                _tag(out, 7, _WIRE_I32)
                out.extend(struct.pack("<f", f))
        elif self.type == ATTR_INTS:
            for i in self.ints:
                _w_varint_field(out, 8, i)
        elif self.type == ATTR_STRINGS:
            for s in self.strings:
                _w_bytes_field(out, 9, s)
        _w_varint_field(out, 20, self.type)
        return bytes(out)

    # convenience constructors
    @staticmethod
    def make(name: str, value) -> "AttributeProto":
        a = AttributeProto(name=name)
        if isinstance(value, bool):
            a.type, a.i = ATTR_INT, int(value)
        elif isinstance(value, int):
            a.type, a.i = ATTR_INT, value
        elif isinstance(value, float):
            a.type, a.f = ATTR_FLOAT, value
        elif isinstance(value, str):
            a.type, a.s = ATTR_STRING, value.encode("utf-8")
        elif isinstance(value, np.ndarray):
            a.type, a.t = ATTR_TENSOR, numpy_to_tensor(value)
        elif isinstance(value, GraphProto):
            a.type, a.g = ATTR_GRAPH, value
        elif isinstance(value, (list, tuple)):
            if all(isinstance(x, int) for x in value):
                a.type, a.ints = ATTR_INTS, list(value)
            elif all(isinstance(x, (int, float)) for x in value):
                a.type, a.floats = ATTR_FLOATS, [float(x) for x in value]
            elif all(isinstance(x, str) for x in value):
                a.type, a.strings = ATTR_STRINGS, [x.encode() for x in value]
            else:
                raise ValueError(f"unsupported attribute list {value!r}")
        else:
            raise ValueError(f"unsupported attribute value {value!r}")
        return a


@dataclasses.dataclass
class NodeProto:
    input: list = dataclasses.field(default_factory=list)         # 1
    output: list = dataclasses.field(default_factory=list)        # 2
    name: str = ""                                                # 3
    op_type: str = ""                                             # 4
    attribute: list = dataclasses.field(default_factory=list)     # 5
    domain: str = ""                                              # 7

    def attrs(self) -> dict:
        return {a.name: a.value for a in self.attribute}

    @staticmethod
    def parse(buf: bytes) -> "NodeProto":
        n = NodeProto()
        for field, _, v in _fields(buf):
            if field == 1:
                n.input.append(v.decode("utf-8"))
            elif field == 2:
                n.output.append(v.decode("utf-8"))
            elif field == 3:
                n.name = v.decode("utf-8")
            elif field == 4:
                n.op_type = v.decode("utf-8")
            elif field == 5:
                n.attribute.append(AttributeProto.parse(v))
            elif field == 7:
                n.domain = v.decode("utf-8")
        return n

    def encode(self) -> bytes:
        out = bytearray()
        for s in self.input:
            _w_str_field(out, 1, s)
        for s in self.output:
            _w_str_field(out, 2, s)
        if self.name:
            _w_str_field(out, 3, self.name)
        _w_str_field(out, 4, self.op_type)
        for a in self.attribute:
            _w_bytes_field(out, 5, a.encode())
        if self.domain:
            _w_str_field(out, 7, self.domain)
        return bytes(out)


@dataclasses.dataclass
class ValueInfoProto:
    """name (1) + TypeProto (2) -> tensor_type (1) -> elem_type (1), shape (2)."""

    name: str = ""
    elem_type: int = FLOAT
    dims: list = dataclasses.field(default_factory=list)  # ints or str dim_params

    @staticmethod
    def parse(buf: bytes) -> "ValueInfoProto":
        vi = ValueInfoProto()
        for field, _, v in _fields(buf):
            if field == 1:
                vi.name = v.decode("utf-8")
            elif field == 2:  # TypeProto
                for f2, _, v2 in _fields(v):
                    if f2 == 1:  # tensor_type
                        for f3, _, v3 in _fields(v2):
                            if f3 == 1:
                                vi.elem_type = v3
                            elif f3 == 2:  # TensorShapeProto
                                for f4, _, v4 in _fields(v3):
                                    if f4 == 1:  # Dimension
                                        dim = None
                                        for f5, _, v5 in _fields(v4):
                                            if f5 == 1:
                                                dim = _signed(v5)
                                            elif f5 == 2:
                                                dim = v5.decode("utf-8")
                                        vi.dims.append(dim)
        return vi

    def encode(self) -> bytes:
        shape = bytearray()
        for d in self.dims:
            dim = bytearray()
            if isinstance(d, str):
                _w_str_field(dim, 2, d)
            elif d is not None:
                _w_varint_field(dim, 1, d)
            _w_bytes_field(shape, 1, bytes(dim))
        tt = bytearray()
        _w_varint_field(tt, 1, self.elem_type)
        _w_bytes_field(tt, 2, bytes(shape))
        tp = bytearray()
        _w_bytes_field(tp, 1, bytes(tt))
        out = bytearray()
        _w_str_field(out, 1, self.name)
        _w_bytes_field(out, 2, bytes(tp))
        return bytes(out)


@dataclasses.dataclass
class GraphProto:
    node: list = dataclasses.field(default_factory=list)          # 1
    name: str = ""                                                # 2
    initializer: list = dataclasses.field(default_factory=list)   # 5
    input: list = dataclasses.field(default_factory=list)         # 11
    output: list = dataclasses.field(default_factory=list)        # 12
    value_info: list = dataclasses.field(default_factory=list)    # 13

    @staticmethod
    def parse(buf: bytes) -> "GraphProto":
        g = GraphProto()
        for field, _, v in _fields(buf):
            if field == 1:
                g.node.append(NodeProto.parse(v))
            elif field == 2:
                g.name = v.decode("utf-8")
            elif field == 5:
                g.initializer.append(TensorProto.parse(v))
            elif field == 11:
                g.input.append(ValueInfoProto.parse(v))
            elif field == 12:
                g.output.append(ValueInfoProto.parse(v))
            elif field == 13:
                g.value_info.append(ValueInfoProto.parse(v))
        return g

    def encode(self) -> bytes:
        out = bytearray()
        for n in self.node:
            _w_bytes_field(out, 1, n.encode())
        if self.name:
            _w_str_field(out, 2, self.name)
        for t in self.initializer:
            _w_bytes_field(out, 5, t.encode())
        for vi in self.input:
            _w_bytes_field(out, 11, vi.encode())
        for vi in self.output:
            _w_bytes_field(out, 12, vi.encode())
        for vi in self.value_info:
            _w_bytes_field(out, 13, vi.encode())
        return bytes(out)


@dataclasses.dataclass
class OperatorSetId:
    domain: str = ""   # 1
    version: int = 0   # 2

    @staticmethod
    def parse(buf: bytes) -> "OperatorSetId":
        o = OperatorSetId()
        for field, _, v in _fields(buf):
            if field == 1:
                o.domain = v.decode("utf-8")
            elif field == 2:
                o.version = _signed(v)
        return o

    def encode(self) -> bytes:
        out = bytearray()
        if self.domain:
            _w_str_field(out, 1, self.domain)
        _w_varint_field(out, 2, self.version)
        return bytes(out)


@dataclasses.dataclass
class ModelProto:
    ir_version: int = 8                                           # 1
    producer_name: str = ""                                       # 2
    graph: GraphProto = dataclasses.field(default_factory=GraphProto)  # 7
    opset_import: list = dataclasses.field(default_factory=list)  # 8

    @staticmethod
    def parse(buf: bytes) -> "ModelProto":
        m = ModelProto()
        for field, _, v in _fields(buf):
            if field == 1:
                m.ir_version = _signed(v)
            elif field == 2:
                m.producer_name = v.decode("utf-8")
            elif field == 7:
                m.graph = GraphProto.parse(v)
            elif field == 8:
                m.opset_import.append(OperatorSetId.parse(v))
        return m

    def encode(self) -> bytes:
        out = bytearray()
        _w_varint_field(out, 1, self.ir_version)
        if self.producer_name:
            _w_str_field(out, 2, self.producer_name)
        _w_bytes_field(out, 7, self.graph.encode())
        for o in self.opset_import or [OperatorSetId(version=17)]:
            _w_bytes_field(out, 8, o.encode())
        return bytes(out)


def parse_model(data: bytes) -> ModelProto:
    return ModelProto.parse(data)


def encode_model(model: ModelProto) -> bytes:
    return model.encode()
