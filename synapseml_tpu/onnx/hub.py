"""ONNXHub — model-zoo client (reference ``onnx/ONNXHub.scala:72-255``).

The reference fetches a manifest JSON + SHA-checked model files from the
github onnx/models zoo into an HDFS-compatible cache. Here the hub is
cache-first (models + ``manifest.json`` under ``hub_dir``); when a
``base_url`` is configured (constructor arg or $SYNAPSEML_TPU_HUB_URL) a
cache miss fetches ``{base_url}/manifest.json`` and the model file, verifies
the manifest SHA-256, and caches — the reference's remote-zoo path
(``ONNXHub.getModel``). Without a base_url (this image has zero egress) a
miss raises with the expected cache path.
"""

from __future__ import annotations

import hashlib
import json
import os

__all__ = ["ONNXHub"]


class ONNXHub:
    def __init__(self, hub_dir: str | None = None, base_url: str | None = None,
                 timeout_s: float = 120.0):
        self.hub_dir = hub_dir or os.environ.get(
            "SYNAPSEML_TPU_HUB",
            os.path.join(os.path.expanduser("~"), ".cache", "synapseml_tpu", "onnx"))
        self.base_url = (base_url or os.environ.get("SYNAPSEML_TPU_HUB_URL")
                         or "").rstrip("/")
        self.timeout_s = timeout_s

    # -------- remote fetch (manifest-driven, SHA-checked) --------
    def _fetch(self, rel: str) -> bytes:
        import urllib.request

        url = f"{self.base_url}/{rel.lstrip('/')}"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
            return r.read()

    def refresh_manifest(self) -> list[dict]:
        """Download the zoo manifest (``ONNXHub.scala`` getModelManifest)."""
        if not self.base_url:
            raise RuntimeError("no hub base_url configured (constructor arg or "
                               "$SYNAPSEML_TPU_HUB_URL)")
        manifest = json.loads(self._fetch("manifest.json"))
        os.makedirs(self.hub_dir, exist_ok=True)
        with open(self._manifest_path(), "w") as f:
            json.dump(manifest, f, indent=2)
        return manifest

    def _safe_cache_path(self, rel: str) -> str:
        """Join a manifest-supplied relative path into hub_dir, rejecting
        absolute paths and traversal — the manifest is REMOTE UNTRUSTED data."""
        if os.path.isabs(rel):
            raise ValueError(f"manifest model_path must be relative: {rel!r}")
        path = os.path.realpath(os.path.join(self.hub_dir, rel))
        root = os.path.realpath(self.hub_dir)
        if not (path == root or path.startswith(root + os.sep)):
            raise ValueError(f"manifest model_path escapes the cache dir: {rel!r}")
        return path

    def download(self, name: str) -> tuple[str, bytes]:
        """Fetch one model by manifest entry, verify sha256, cache atomically,
        return (path, bytes) (``ONNXHub.scala`` downloadModel with checksum)."""
        if self.base_url:
            try:
                self.get_model_info(name)
            except KeyError:
                # stale/empty local manifest: refresh before giving up
                self.refresh_manifest()
        info = self.get_model_info(name)
        rel = info.get("model_path") or f"{name}.onnx"
        data = self._fetch(rel)
        expect = info.get("model_sha256")
        if expect:
            got = hashlib.sha256(data).hexdigest()
            if got != expect:
                raise ValueError(f"downloaded {name!r} sha256 mismatch: "
                                 f"{got} != {expect}")
        path = self._safe_cache_path(rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".part"
        with open(tmp, "wb") as f:  # atomic: no truncated cache entries
            f.write(data)
        os.replace(tmp, path)
        return path, data

    # -------- manifest --------
    def _manifest_path(self) -> str:
        return os.path.join(self.hub_dir, "manifest.json")

    def list_models(self) -> list[dict]:
        path = self._manifest_path()
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return json.load(f)

    def get_model_info(self, name: str) -> dict:
        matches = [m for m in self.list_models()
                   if m.get("model", "").lower() == name.lower()
                   or m.get("model_path", "") == name]
        if not matches:
            raise KeyError(f"model {name!r} not in hub manifest "
                           f"({self._manifest_path()}); available: "
                           f"{[m.get('model') for m in self.list_models()]}")
        # newest opset wins (reference picks max opset version)
        return max(matches, key=lambda m: m.get("opset_version", 0))

    # -------- models --------
    def model_path(self, name: str) -> str:
        try:
            info = self.get_model_info(name)
            rel = info.get("model_path") or f"{name}.onnx"
        except KeyError:
            rel = f"{name}.onnx"
        return os.path.join(self.hub_dir, rel)

    def load(self, name: str, verify_sha: bool = True) -> bytes:
        path = self.model_path(name)
        if not os.path.exists(path) and self.base_url:
            _, data = self.download(name)  # just verified in memory
            return data
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"ONNX model {name!r} not cached at {path}. This environment "
                f"has no network egress: place the .onnx file there (and "
                f"optionally a manifest.json entry) to use the hub, or set a "
                f"base_url.")
        with open(path, "rb") as f:
            data = f.read()
        if verify_sha:
            try:
                expect = self.get_model_info(name).get("model_sha256")
            except KeyError:
                expect = None
            if expect and hashlib.sha256(data).hexdigest() != expect:
                if self.base_url:
                    # corrupt/interrupted cache entry: re-download once
                    _, data = self.download(name)
                    return data
                raise ValueError(f"sha256 mismatch for {name}: "
                                 f"{hashlib.sha256(data).hexdigest()} != {expect}")
        return data

    def save(self, name: str, data: bytes, extra_info: dict | None = None) -> str:
        """Register a model into the local hub (test/setup convenience)."""
        os.makedirs(self.hub_dir, exist_ok=True)
        rel = f"{name}.onnx"
        with open(os.path.join(self.hub_dir, rel), "wb") as f:
            f.write(data)
        manifest = self.list_models()
        manifest = [m for m in manifest if m.get("model") != name]
        entry = {"model": name, "model_path": rel,
                 "model_sha256": hashlib.sha256(data).hexdigest(),
                 "opset_version": 17}
        entry.update(extra_info or {})
        manifest.append(entry)
        with open(self._manifest_path(), "w") as f:
            json.dump(manifest, f, indent=2)
        return os.path.join(self.hub_dir, rel)
