"""ONNXHub — model-zoo client (reference ``onnx/ONNXHub.scala:72-255``).

The reference fetches a manifest JSON + SHA-checked model files from the
github onnx/models zoo into an HDFS-compatible cache. This environment has no
egress, so the hub is cache-first: models and a ``manifest.json`` live under
``hub_dir`` (``~/.cache/synapseml_tpu/onnx`` by default, or $SYNAPSEML_TPU_HUB);
a missing model raises with the expected path instead of downloading.
"""

from __future__ import annotations

import hashlib
import json
import os

__all__ = ["ONNXHub"]


class ONNXHub:
    def __init__(self, hub_dir: str | None = None):
        self.hub_dir = hub_dir or os.environ.get(
            "SYNAPSEML_TPU_HUB",
            os.path.join(os.path.expanduser("~"), ".cache", "synapseml_tpu", "onnx"))

    # -------- manifest --------
    def _manifest_path(self) -> str:
        return os.path.join(self.hub_dir, "manifest.json")

    def list_models(self) -> list[dict]:
        path = self._manifest_path()
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return json.load(f)

    def get_model_info(self, name: str) -> dict:
        matches = [m for m in self.list_models()
                   if m.get("model", "").lower() == name.lower()
                   or m.get("model_path", "") == name]
        if not matches:
            raise KeyError(f"model {name!r} not in hub manifest "
                           f"({self._manifest_path()}); available: "
                           f"{[m.get('model') for m in self.list_models()]}")
        # newest opset wins (reference picks max opset version)
        return max(matches, key=lambda m: m.get("opset_version", 0))

    # -------- models --------
    def model_path(self, name: str) -> str:
        try:
            info = self.get_model_info(name)
            rel = info.get("model_path") or f"{name}.onnx"
        except KeyError:
            rel = f"{name}.onnx"
        return os.path.join(self.hub_dir, rel)

    def load(self, name: str, verify_sha: bool = True) -> bytes:
        path = self.model_path(name)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"ONNX model {name!r} not cached at {path}. This environment "
                f"has no network egress: place the .onnx file there (and "
                f"optionally a manifest.json entry) to use the hub.")
        with open(path, "rb") as f:
            data = f.read()
        if verify_sha:
            try:
                expect = self.get_model_info(name).get("model_sha256")
            except KeyError:
                expect = None
            if expect:
                got = hashlib.sha256(data).hexdigest()
                if got != expect:
                    raise ValueError(f"sha256 mismatch for {name}: {got} != {expect}")
        return data

    def save(self, name: str, data: bytes, extra_info: dict | None = None) -> str:
        """Register a model into the local hub (test/setup convenience)."""
        os.makedirs(self.hub_dir, exist_ok=True)
        rel = f"{name}.onnx"
        with open(os.path.join(self.hub_dir, rel), "wb") as f:
            f.write(data)
        manifest = self.list_models()
        manifest = [m for m in manifest if m.get("model") != name]
        entry = {"model": name, "model_path": rel,
                 "model_sha256": hashlib.sha256(data).hexdigest(),
                 "opset_version": 17}
        entry.update(extra_info or {})
        manifest.append(entry)
        with open(self._manifest_path(), "w") as f:
            json.dump(manifest, f, indent=2)
        return os.path.join(self.hub_dir, rel)
