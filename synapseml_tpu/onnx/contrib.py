"""``com.microsoft`` contrib ops — the ORT transformer-fusion opset.

The reference's ONNXModel runs on ONNX Runtime, whose graph optimizer
rewrites transformer models into contrib ops (``ONNXRuntime.scala:25``;
ORT's ``FusionAttention``/``FusionSkipLayerNormalization`` passes emit
``com.microsoft`` nodes). Models saved AFTER that optimization — the form
many deployed BERT/GPT ONNX artifacts ship in — therefore need these ops for
migration, not just the stock opset.

Registered into :data:`~synapseml_tpu.onnx.convert.OP_REGISTRY` by name
(contrib names don't collide with the standard opset; the converter keys by
``op_type``). Each lowering is plain jnp — XLA re-fuses what ORT fused by
hand, and the attention math hits the MXU as three dots per head group.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .convert import OP_REGISTRY, op

_BEFORE_CONTRIB = frozenset(OP_REGISTRY)

_SQRT_2_OVER_PI = 0.7978845608028654


def _tanh_gelu(x):
    return 0.5 * x * (1.0 + jnp.tanh(_SQRT_2_OVER_PI
                                     * (x + 0.044715 * x * x * x)))


@op("FastGelu")
def _fast_gelu(ins, attrs):
    x = ins[0]
    if len(ins) > 1 and ins[1] is not None:
        x = x + ins[1]
    return _tanh_gelu(x)


@op("BiasGelu")
def _bias_gelu(ins, attrs):
    return jax.nn.gelu(ins[0] + ins[1], approximate=False)


@op("QuickGelu")
def _quick_gelu(ins, attrs):
    alpha = attrs.get("alpha", 1.702)
    return ins[0] * jax.nn.sigmoid(alpha * ins[0])


def _layer_norm(h, gamma, beta, eps):
    hf = h.astype(jnp.float32)
    mean = jnp.mean(hf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(hf - mean), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    out = (hf - mean) * inv * gamma.astype(jnp.float32)
    if beta is not None:
        out = out + beta.astype(jnp.float32)
    return out.astype(h.dtype), mean, inv


@op("SkipLayerNormalization")
def _skip_layer_norm(ins, attrs):
    """input + skip (+ bias) -> layernorm. Outputs (out, mean, inv_std_var,
    input_skip_bias_sum) — callers binding fewer outputs just take a prefix."""
    x, skip, gamma = ins[0], ins[1], ins[2]
    beta = ins[3] if len(ins) > 3 else None
    bias = ins[4] if len(ins) > 4 else None
    h = x + skip
    if bias is not None:
        h = h + bias
    out, mean, inv = _layer_norm(h, gamma, beta, attrs.get("epsilon", 1e-12))
    return out, mean, inv, h


@op("EmbedLayerNormalization")
def _embed_layer_norm(ins, attrs):
    """(input_ids, segment_ids, word_emb, pos_emb, seg_emb, gamma, beta,
    [mask], [position_ids]) -> (output, mask_index, [embedding_sum])."""
    input_ids = jnp.asarray(ins[0]).astype(jnp.int32)
    seg_ids = ins[1]
    word_emb, pos_emb = ins[2], ins[3]
    seg_emb = ins[4] if len(ins) > 4 else None
    gamma, beta = ins[5], ins[6] if len(ins) > 6 else None
    mask = ins[7] if len(ins) > 7 else None
    pos_ids = ins[8] if len(ins) > 8 else None
    B, S = input_ids.shape
    emb = jnp.take(jnp.asarray(word_emb), input_ids, axis=0)
    if pos_ids is None:
        pos = jnp.asarray(pos_emb)[:S][None, :, :]
    else:
        pos = jnp.take(jnp.asarray(pos_emb),
                       jnp.asarray(pos_ids).astype(jnp.int32), axis=0)
    emb = emb + pos
    if seg_emb is not None and seg_ids is not None:
        emb = emb + jnp.take(jnp.asarray(seg_emb),
                             jnp.asarray(seg_ids).astype(jnp.int32), axis=0)
    out, _, _ = _layer_norm(emb, jnp.asarray(gamma),
                            None if beta is None else jnp.asarray(beta),
                            attrs.get("epsilon", 1e-12))
    if mask is not None:
        mask_index = jnp.sum(jnp.asarray(mask).astype(jnp.int32), axis=1)
    else:
        mask_index = jnp.full((B,), S, jnp.int32)
    return out, mask_index, emb


@op("FusedMatMul")
def _fused_matmul(ins, attrs):
    a, b = ins[0], ins[1]
    if attrs.get("transBatchA") or attrs.get("transBatchB"):
        raise NotImplementedError(
            "FusedMatMul transBatchA/transBatchB (batch-dim transposition) "
            "is not lowered")
    if attrs.get("transA"):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transB"):
        b = jnp.swapaxes(b, -1, -2)
    out = jnp.matmul(a, b)
    alpha = attrs.get("alpha", 1.0)
    return out if alpha == 1.0 else out * jnp.asarray(alpha, out.dtype)


@op("Attention")
def _attention(ins, attrs):
    """ORT fused self-attention: (input [B,S,Hin], weights [Hin,3*H],
    bias [3*H], [mask], [past], [attention_bias]) -> [B,S,H].

    Supported mask forms: None, raw 2D [B, S] key mask (1 = attend), or 1D
    [B] right-side key lengths. ``unidirectional=1`` adds the causal mask
    (the GPT fusion form). ``past``/``present`` KV-cache states are not
    lowered — batch scoring re-runs the full sequence (the reference's
    ONNXModel usage); a clear error guards the gap.
    """
    x, w, b = ins[0], ins[1], ins[2]
    mask = ins[3] if len(ins) > 3 else None
    past = ins[4] if len(ins) > 4 else None
    attn_bias = ins[5] if len(ins) > 5 else None
    if past is not None:
        raise NotImplementedError(
            "com.microsoft Attention with a `past` KV state is a decode-loop "
            "form; batch scoring re-runs the full sequence without it")
    if attrs.get("do_rotary"):
        raise NotImplementedError(
            "com.microsoft Attention with do_rotary=1 (the GPT-NeoX fusion "
            "form) is not lowered")
    n_heads = int(attrs["num_heads"])
    if attrs.get("qkv_hidden_sizes"):
        sizes = [int(s) for s in attrs["qkv_hidden_sizes"]]
        if len(set(sizes)) != 1:
            raise NotImplementedError(
                f"Attention with unequal qkv_hidden_sizes {sizes}")
    B, S, _ = x.shape
    qkv = jnp.matmul(x, jnp.asarray(w)) + jnp.asarray(b)     # [B, S, 3H]
    H3 = qkv.shape[-1]
    H = H3 // 3
    d = H // n_heads
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):  # [B, S, H] -> [B, n, S, d]
        return jnp.transpose(t.reshape(B, S, n_heads, d), (0, 2, 1, 3))

    q, k, v = heads(q), heads(k), heads(v)
    scale = attrs.get("scale") or 1.0 / np.sqrt(d)
    scores = jnp.einsum("bnqd,bnkd->bnqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    neg = jnp.asarray(-1e30, jnp.float32)
    if mask is not None:
        m = jnp.asarray(mask)
        if m.ndim == 1:                      # [B] key lengths
            key_ok = jnp.arange(S)[None, :] < m[:, None].astype(jnp.int32)
        elif m.ndim == 2:                    # [B, S] raw key mask
            key_ok = m.astype(bool)
        else:
            raise NotImplementedError(
                f"Attention mask_index of rank {m.ndim} (supported: 1D "
                f"lengths, 2D raw key mask)")
        scores = jnp.where(key_ok[:, None, None, :], scores, neg)
    if attn_bias is not None:
        scores = scores + jnp.asarray(attn_bias).astype(jnp.float32)
    if attrs.get("unidirectional"):
        causal = jnp.arange(S)[None, :] <= jnp.arange(S)[:, None]
        scores = jnp.where(causal[None, None], scores, neg)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bnqk,bnkd->bnqd", probs, v,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    return jnp.transpose(out, (0, 2, 1, 3)).reshape(B, S, H)


# Gelu exists in the standard opset registry; com.microsoft Gelu is the same
# exact-erf form, so the shared entry in convert.py covers both domains.
assert "Gelu" in OP_REGISTRY

# registration-time truth for codegen.facts(): exactly the ops this module
# added to the shared registry
CONTRIB_OPS = frozenset(OP_REGISTRY) - _BEFORE_CONTRIB
