"""ONNX graph -> jittable JAX function.

The TPU replacement for ONNX Runtime's CUDA execution provider
(reference ``onnx/ONNXRuntime.scala:25-107``): instead of a per-partition
OrtSession, the graph converts ONCE into a pure JAX callable which XLA
compiles (and fuses) for the device. Weights become closure constants so XLA
can constant-fold/bake them into the executable, mirroring a session's
"model resident in device memory".

The 165-op registry is proven through REAL torch.onnx exports, one per model
family: convnets (ResNet-50, ``tests/test_onnx_resnet.py``), transformer
encoders with einsum attention and dynamic shapes (``tests/test_onnx_bert.py``),
causal decoders with Trilu masks, GatherElements and shape-guard If nodes
(``tests/test_onnx_gpt.py``), modern-vision ops — Resize, GroupNorm-as-
InstanceNorm, Hardswish, TopK (``tests/test_onnx_mixed.py``) — and recurrent
LSTM/GRU lowered to ``lax.scan`` (``tests/test_onnx_rnn.py``). Host-side
int64 shape math stays numpy end-to-end so dynamic-shape chains never stage
tracers. Unsupported ops raise with the op name at conversion time, not run
time.
"""

from __future__ import annotations

import itertools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .proto import GraphProto, ModelProto, parse_model, tensor_to_numpy

__all__ = ["convert_graph", "ConvertedModel", "OP_REGISTRY"]

OP_REGISTRY: dict[str, Callable] = {}


def op(name):
    def deco(fn):
        OP_REGISTRY[name] = fn
        return fn
    return deco


def _pair(v, default):
    if v is None:
        return (default, default)
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def _conv_pads(attrs, spatial_rank):
    """ONNX pads = [x1_begin, x2_begin, ..., x1_end, x2_end, ...]."""
    pads = attrs.get("pads")
    auto = attrs.get("auto_pad", "NOTSET")
    if auto and auto not in ("NOTSET",):
        return auto  # SAME_UPPER / SAME_LOWER / VALID handled by lax
    if pads is None:
        return [(0, 0)] * spatial_rank
    half = len(pads) // 2
    return list(zip(pads[:half], pads[half:]))


# ---------------- math / activation ----------------

@op("Add")
def _add(ins, attrs):
    return ins[0] + ins[1]


@op("Sub")
def _sub(ins, attrs):
    return ins[0] - ins[1]


@op("Mul")
def _mul(ins, attrs):
    return ins[0] * ins[1]


@op("Div")
def _div(ins, attrs):
    a, b = ins[0], ins[1]
    a_int = jnp.issubdtype(getattr(a, "dtype", None) or np.asarray(a).dtype,
                           np.integer)
    b_int = jnp.issubdtype(getattr(b, "dtype", None) or np.asarray(b).dtype,
                           np.integer)
    if a_int and b_int:
        # ONNX integer Div truncates toward zero (C semantics) — torch's
        # chunk/split exports rely on it for Slice bounds; Python floor
        # division (or float division) would shift every boundary
        q = a // b
        r = a - q * b
        return q + ((r != 0) & ((a < 0) != (b < 0)))
    return a / b


@op("Pow")
def _pow(ins, attrs):
    return ins[0] ** ins[1]


@op("Neg")
def _neg(ins, attrs):
    return -ins[0]


@op("Abs")
def _abs(ins, attrs):
    return jnp.abs(ins[0])


@op("Sqrt")
def _sqrt(ins, attrs):
    return jnp.sqrt(ins[0])


@op("Exp")
def _exp(ins, attrs):
    return jnp.exp(ins[0])


@op("Log")
def _log(ins, attrs):
    return jnp.log(ins[0])


@op("Erf")
def _erf(ins, attrs):
    return jax.scipy.special.erf(ins[0])


@op("Relu")
def _relu(ins, attrs):
    return jax.nn.relu(ins[0])


@op("LeakyRelu")
def _leaky(ins, attrs):
    return jax.nn.leaky_relu(ins[0], attrs.get("alpha", 0.01))


@op("Sigmoid")
def _sigmoid(ins, attrs):
    return jax.nn.sigmoid(ins[0])


@op("Tanh")
def _tanh(ins, attrs):
    return jnp.tanh(ins[0])


@op("Gelu")
def _gelu(ins, attrs):
    return jax.nn.gelu(ins[0], approximate=attrs.get("approximate", "none") == "tanh")


@op("Softmax")
def _softmax(ins, attrs):
    return jax.nn.softmax(ins[0], axis=attrs.get("axis", -1))


@op("LogSoftmax")
def _log_softmax(ins, attrs):
    return jax.nn.log_softmax(ins[0], axis=attrs.get("axis", -1))


@op("Clip")
def _clip(ins, attrs):
    lo = ins[1] if len(ins) > 1 and ins[1] is not None else attrs.get("min")
    hi = ins[2] if len(ins) > 2 and ins[2] is not None else attrs.get("max")
    return jnp.clip(ins[0], lo, hi)


@op("Sin")
def _sin(ins, attrs):
    return jnp.sin(ins[0])


@op("Cos")
def _cos(ins, attrs):
    return jnp.cos(ins[0])


@op("HardSwish")
def _hardswish(ins, attrs):
    return jax.nn.hard_swish(ins[0])


@op("HardSigmoid")
def _hardsigmoid(ins, attrs):
    alpha = attrs.get("alpha", 0.2)
    beta = attrs.get("beta", 0.5)
    return jnp.clip(alpha * ins[0] + beta, 0.0, 1.0)


@op("Where")
def _where(ins, attrs):
    present = [x for x in ins if x is not None]
    if all(isinstance(x, (np.ndarray, np.generic)) for x in present):
        # shape-math select (torch's expand exports Where(shape==-1, ...)):
        # stay host numpy — under jit a jnp.where would stage to a tracer
        # and break static-shape consumers like Expand/Reshape
        return np.where(ins[0], ins[1], ins[2])
    return jnp.where(ins[0], ins[1], ins[2])


@op("Equal")
def _equal(ins, attrs):
    return ins[0] == ins[1]


@op("Greater")
def _greater(ins, attrs):
    return ins[0] > ins[1]


@op("Less")
def _less(ins, attrs):
    return ins[0] < ins[1]


# ---------------- linear algebra ----------------

@op("MatMul")
def _matmul(ins, attrs):
    return jnp.matmul(ins[0], ins[1])


@op("Einsum")
def _einsum(ins, attrs):
    # torch exports einsum attention (bthd,bshd->bhts) as one Einsum node;
    # XLA maps it straight onto MXU dot_generals
    eq = attrs["equation"]
    if isinstance(eq, bytes):
        eq = eq.decode("utf-8")
    return jnp.einsum(eq, *ins)


@op("Gemm")
def _gemm(ins, attrs):
    a, b = ins[0], ins[1]
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    y = attrs.get("alpha", 1.0) * (a @ b)
    if len(ins) > 2 and ins[2] is not None:
        y = y + attrs.get("beta", 1.0) * ins[2]
    return y


@op("Conv")
def _conv(ins, attrs):
    x, w = ins[0], ins[1]
    rank = x.ndim - 2
    strides = attrs.get("strides") or [1] * rank
    dilations = attrs.get("dilations") or [1] * rank
    groups = attrs.get("group", 1)
    pads = _conv_pads(attrs, rank)
    if isinstance(pads, str):
        pads = {"SAME_UPPER": "SAME", "SAME_LOWER": "SAME_LOWER", "VALID": "VALID"}[pads]
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW") if rank == 2 else None)
    if len(ins) > 2 and ins[2] is not None:
        out = out + ins[2].reshape((1, -1) + (1,) * rank)
    return out


@op("ConvTranspose")
def _conv_transpose(ins, attrs):
    """Transposed (fractionally-strided) convolution — the UNet/segmentation
    upsampling op. Lowered as ``conv_general_dilated`` with lhs_dilation =
    stride over the spatially-flipped, channel-swapped kernel (the gradient
    identity), which XLA maps straight onto the MXU. ONNX weight layout is
    ``[C_in, C_out/groups, *k]``; output spatial size follows the spec:
    (in-1)*s + ((k-1)*d + 1) - pad_begin - pad_end + output_padding."""
    x, w = ins[0], ins[1]
    rank = x.ndim - 2
    strides = [int(s) for s in (attrs.get("strides") or [1] * rank)]
    dilations = [int(d) for d in (attrs.get("dilations") or [1] * rank)]
    out_pad = [int(p) for p in (attrs.get("output_padding") or [0] * rank)]
    groups = int(attrs.get("group", 1))
    if attrs.get("output_shape"):
        raise NotImplementedError("ConvTranspose with explicit output_shape")
    if attrs.get("auto_pad", "NOTSET") not in ("NOTSET", ""):
        raise NotImplementedError("ConvTranspose auto_pad")
    pads = attrs.get("pads") or [0] * (2 * rank)
    k = [int(w.shape[2 + i]) for i in range(rank)]
    # flip spatial dims; swap [C_in, C_out/g, ...] -> [C_out/g * g?, ...]:
    # per group, the transposed kernel is [C_out/g, C_in/g, *k] OIHW
    wf = jnp.flip(w, axis=tuple(range(2, 2 + rank)))
    if groups == 1:
        wt = jnp.swapaxes(wf, 0, 1)                       # [C_out, C_in, *k]
    else:
        cin, cog = w.shape[0], w.shape[1]
        wt = wf.reshape((groups, cin // groups, cog) + tuple(k))
        wt = jnp.swapaxes(wt, 1, 2)                       # [g, C_out/g, C_in/g, *k]
        wt = wt.reshape((groups * cog, cin // groups) + tuple(k))
    padding = [((k[i] - 1) * dilations[i] - pads[i],
                (k[i] - 1) * dilations[i] - pads[rank + i] + out_pad[i])
               for i in range(rank)]
    dn = ("NCHW", "OIHW", "NCHW") if rank == 2 else None
    out = jax.lax.conv_general_dilated(
        x, wt, window_strides=[1] * rank, padding=padding,
        lhs_dilation=strides, rhs_dilation=dilations,
        feature_group_count=groups, dimension_numbers=dn)
    if len(ins) > 2 and ins[2] is not None:
        out = out + ins[2].reshape((1, -1) + (1,) * rank)
    return out


@op("BatchNormalization")
def _batchnorm(ins, attrs):
    x, scale, bias, mean, var = ins[:5]
    eps = attrs.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = jax.lax.rsqrt(var.reshape(shape) + eps)
    return (x - mean.reshape(shape)) * inv * scale.reshape(shape) + bias.reshape(shape)


@op("LayerNormalization")
def _layernorm(ins, attrs):
    x = ins[0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-5)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if len(ins) > 1 and ins[1] is not None:
        y = y * ins[1]
    if len(ins) > 2 and ins[2] is not None:
        y = y + ins[2]
    return y


# ---------------- pooling ----------------

def _same_explicit_pads(in_sizes, kernel, strides, lower: bool):
    out = []
    for i, k, s in zip(in_sizes, kernel, strides):
        o = -(-i // s)
        total = max((o - 1) * s + k - i, 0)
        a, b = total // 2, total - total // 2
        out.append((b, a) if lower else (a, b))
    return out


def _pool(x, attrs, reducer, init, is_avg=False):
    rank = x.ndim - 2
    kernel = attrs["kernel_shape"]
    strides = attrs.get("strides") or [1] * rank
    pads = _conv_pads(attrs, rank)
    if pads == "VALID":
        padding = "VALID"
    elif isinstance(pads, str):
        # SAME_UPPER / SAME_LOWER differ in which side takes the odd pad;
        # reduce_window's 'SAME' is upper, so compute explicit pads instead
        padding = [(0, 0), (0, 0)] + _same_explicit_pads(
            x.shape[2:], kernel, strides, lower=pads == "SAME_LOWER")
    else:
        padding = [(0, 0), (0, 0)] + list(pads)
    window = (1, 1) + tuple(kernel)
    stride = (1, 1) + tuple(strides)
    if isinstance(padding, str):
        out = jax.lax.reduce_window(x, init, reducer, window, stride, padding)
        if is_avg:
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride, padding)
            out = out / counts
        return out
    out = jax.lax.reduce_window(x, init, reducer, window, stride, padding)
    if is_avg:
        if attrs.get("count_include_pad", 0):
            out = out / float(np.prod(kernel))
        else:
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride, padding)
            out = out / counts
    return out


@op("MaxPool")
def _maxpool(ins, attrs):
    return _pool(ins[0], attrs, jax.lax.max, -jnp.inf)


@op("AveragePool")
def _avgpool(ins, attrs):
    return _pool(ins[0], attrs, jax.lax.add, 0.0, is_avg=True)


@op("GlobalAveragePool")
def _gap(ins, attrs):
    x = ins[0]
    return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("GlobalMaxPool")
def _gmp(ins, attrs):
    x = ins[0]
    return jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("InstanceNormalization")
def _instance_norm(ins, attrs):
    # also the lowering torch emits for GroupNorm (reshape -> IN -> reshape)
    x, scale, bias = ins[0], ins[1], ins[2]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) / jnp.sqrt(var + eps) * scale.reshape(shape) \
        + bias.reshape(shape)


def _resize_coords(out_len: int, in_len: int, scale: float, ct: str):
    i = np.arange(out_len, dtype=np.float64)
    if ct == "asymmetric":
        return i / scale
    if ct in ("half_pixel", "pytorch_half_pixel"):
        x = (i + 0.5) / scale - 0.5
        if ct == "pytorch_half_pixel" and out_len == 1:
            x = np.zeros_like(x)
        return x
    if ct == "align_corners":
        return i * ((in_len - 1) / max(out_len - 1, 1))
    raise NotImplementedError(f"Resize coordinate mode {ct!r}")


@op("Resize")
def _resize(ins, attrs):
    """Nearest / linear resize (torch F.interpolate exports). Coordinates
    are computed host-side per ONNX's coordinate_transformation_mode, so
    the op lowers to static gathers + lerps XLA can fuse."""
    x = ins[0]
    if len(ins) == 2:  # opset-10 form: Resize(X, scales)
        scales, sizes = np.asarray(ins[1]), None
    else:              # opset-11+ form: Resize(X, roi, scales, sizes)
        scales = (np.asarray(ins[2]) if len(ins) > 2 and ins[2] is not None
                  and np.asarray(ins[2]).size else None)
        sizes = (np.asarray(ins[3]) if len(ins) > 3 and ins[3] is not None
                 and np.asarray(ins[3]).size else None)
    if scales is None and sizes is None:
        raise NotImplementedError("Resize needs scales or sizes")
    if attrs.get("antialias", 0):
        raise NotImplementedError("Resize antialias=1 is not supported")
    mode = attrs.get("mode", "nearest")
    ct = attrs.get("coordinate_transformation_mode", "half_pixel")
    nearest_mode = attrs.get("nearest_mode", "round_prefer_floor")
    if sizes is not None:
        out_shape = [int(s) for s in sizes]
        scale_list = [o / i for o, i in zip(out_shape, x.shape)]
    else:
        scale_list = [float(s) for s in scales]
        out_shape = [int(np.floor(i * s)) for i, s in zip(x.shape, scale_list)]
    out = x
    for ax, (o, n, sc) in enumerate(zip(out_shape, x.shape, scale_list)):
        if o == n:
            continue
        coords = _resize_coords(o, n, sc, ct)
        if mode == "nearest":
            if nearest_mode == "floor":
                idx = np.floor(coords)
            elif nearest_mode == "ceil":
                idx = np.ceil(coords)
            elif nearest_mode == "round_prefer_ceil":
                idx = np.floor(coords + 0.5)
            else:  # round_prefer_floor
                idx = np.ceil(coords - 0.5)
            out = jnp.take(out, np.clip(idx, 0, n - 1).astype(np.int32),
                           axis=ax)
        elif mode == "linear":
            lo = np.clip(np.floor(coords), 0, n - 1).astype(np.int32)
            hi = np.clip(lo + 1, 0, n - 1).astype(np.int32)
            w = np.clip(coords - lo, 0.0, 1.0).astype(np.float32)
            w = w.reshape([o if a == ax else 1 for a in range(out.ndim)])
            out = (jnp.take(out, lo, axis=ax) * (1.0 - w)
                   + jnp.take(out, hi, axis=ax) * w)
        else:
            raise NotImplementedError(f"Resize mode {mode!r}")
    return out


# ---------------- recurrent (lax.scan lowering) ----------------

def _seq_mask(seq_lens, T: int, B: int):
    """[T, B, 1] validity mask from ONNX sequence_lens (None = all valid)."""
    if seq_lens is None:
        return None
    t = jnp.arange(T)[:, None]
    return (t < jnp.asarray(seq_lens)[None, :]).astype(jnp.float32)[..., None]


def _lstm_direction(x, w, r, b, h0, c0, seq_lens, reverse: bool):
    """One LSTM direction. ONNX gate order i,o,f,c; default activations
    sigmoid/tanh/tanh. x: [T,B,I]; w: [4H,I]; r: [4H,H]; b: [8H]."""
    T, B, _ = x.shape
    H = r.shape[1]
    wb, rb = (b[: 4 * H], b[4 * H:]) if b is not None else (0.0, 0.0)
    xw = jnp.einsum("tbi,gi->tbg", x, w) + wb + rb  # input proj, both biases
    mask = _seq_mask(seq_lens, T, B)

    def step(carry, inp):
        h, c = carry
        gates = inp[0] + h @ r.T
        i, o, f, g = (gates[:, k * H:(k + 1) * H] for k in range(4))
        i, o, f = jax.nn.sigmoid(i), jax.nn.sigmoid(o), jax.nn.sigmoid(f)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        if inp[1] is not None:
            m = inp[1]
            h_new = m * h_new + (1 - m) * h  # frozen past seq end
            c_new = m * c_new + (1 - m) * c
            y = m * h_new                    # ONNX: padded steps output 0
        else:
            y = h_new
        return (h_new, c_new), y

    if mask is None:
        (h, c), ys = jax.lax.scan(lambda cr, xt: step(cr, (xt, None)),
                                  (h0, c0), xw, reverse=reverse)
    else:
        (h, c), ys = jax.lax.scan(step, (h0, c0), (xw, mask),
                                  reverse=reverse)
    return ys, h, c


@op("LSTM")
def _lstm(ins, attrs):
    x = ins[0]                       # [T, B, I]
    W, R = ins[1], ins[2]            # [D, 4H, I], [D, 4H, H]
    B_ = ins[3] if len(ins) > 3 else None
    seq_lens = ins[4] if len(ins) > 4 else None
    H = R.shape[2]
    T, Bsz, _ = x.shape
    n_dir = W.shape[0]
    h0 = ins[5] if len(ins) > 5 and ins[5] is not None else jnp.zeros((n_dir, Bsz, H), x.dtype)
    c0 = ins[6] if len(ins) > 6 and ins[6] is not None else jnp.zeros((n_dir, Bsz, H), x.dtype)
    if attrs.get("activations"):
        raise NotImplementedError("LSTM custom activations")
    if attrs.get("layout", 0):
        raise NotImplementedError("LSTM layout=1 (batch-first)")
    if attrs.get("clip") is not None:
        raise NotImplementedError("LSTM cell clipping")
    if len(ins) > 7 and ins[7] is not None:
        raise NotImplementedError("LSTM peephole connections (input P)")
    ys, hs, cs = [], [], []
    for d in range(n_dir):
        y, h, c = _lstm_direction(
            x, jnp.asarray(W[d]), jnp.asarray(R[d]),
            jnp.asarray(B_[d]) if B_ is not None else None,
            jnp.asarray(h0[d]), jnp.asarray(c0[d]), seq_lens,
            reverse=(d == 1 or attrs.get("direction") == "reverse"))
        ys.append(y)
        hs.append(h)
        cs.append(c)
    return (jnp.stack(ys, axis=1),   # Y: [T, D, B, H]
            jnp.stack(hs, axis=0),   # Y_h: [D, B, H]
            jnp.stack(cs, axis=0))   # Y_c: [D, B, H]


def _gru_direction(x, w, r, b, h0, seq_lens, linear_before_reset, reverse):
    """One GRU direction. ONNX gate order z,r,h. x: [T,B,I]; w: [3H,I];
    r: [3H,H]; b: [6H] (Wb zrh + Rb zrh)."""
    T, B, _ = x.shape
    H = r.shape[1]
    wb = b[: 3 * H] if b is not None else jnp.zeros((3 * H,), x.dtype)
    rb = b[3 * H:] if b is not None else jnp.zeros((3 * H,), x.dtype)
    xw = jnp.einsum("tbi,gi->tbg", x, w) + wb
    mask = _seq_mask(seq_lens, T, B)

    def step(h, inp):
        xt, m = inp
        hr = h @ r.T
        z = jax.nn.sigmoid(xt[:, :H] + hr[:, :H] + rb[:H])
        rt = jax.nn.sigmoid(xt[:, H:2 * H] + hr[:, H:2 * H] + rb[H:2 * H])
        if linear_before_reset:
            hh = jnp.tanh(xt[:, 2 * H:] + rt * (hr[:, 2 * H:] + rb[2 * H:]))
        else:
            hh = jnp.tanh(xt[:, 2 * H:] + (rt * h) @ r.T[:, 2 * H:]
                          + rb[2 * H:])
        h_new = (1 - z) * hh + z * h
        if m is not None:
            h_new = m * h_new + (1 - m) * h
            y = m * h_new
        else:
            y = h_new
        return h_new, y

    if mask is None:
        h, ys = jax.lax.scan(lambda hh, xt: step(hh, (xt, None)), h0, xw,
                             reverse=reverse)
    else:
        h, ys = jax.lax.scan(step, h0, (xw, mask), reverse=reverse)
    return ys, h


@op("GRU")
def _gru(ins, attrs):
    x = ins[0]
    W, R = ins[1], ins[2]
    B_ = ins[3] if len(ins) > 3 else None
    seq_lens = ins[4] if len(ins) > 4 else None
    H = R.shape[2]
    T, Bsz, _ = x.shape
    n_dir = W.shape[0]
    h0 = (ins[5] if len(ins) > 5 and ins[5] is not None
          else jnp.zeros((n_dir, Bsz, H), x.dtype))
    if attrs.get("activations"):
        raise NotImplementedError("GRU custom activations")
    if attrs.get("layout", 0):
        raise NotImplementedError("GRU layout=1 (batch-first)")
    if attrs.get("clip") is not None:
        raise NotImplementedError("GRU cell clipping")
    lbr = attrs.get("linear_before_reset", 0)
    ys, hs = [], []
    for d in range(n_dir):
        y, h = _gru_direction(
            x, jnp.asarray(W[d]), jnp.asarray(R[d]),
            jnp.asarray(B_[d]) if B_ is not None else None,
            jnp.asarray(h0[d]), seq_lens, lbr,
            reverse=(d == 1 or attrs.get("direction") == "reverse"))
        ys.append(y)
        hs.append(h)
    return jnp.stack(ys, axis=1), jnp.stack(hs, axis=0)


# ---------------- shape / structure ----------------

@op("Reshape")
def _reshape(ins, attrs):
    x, shape = ins[0], ins[1]
    shape = [int(s) for s in np.asarray(shape)]
    # ONNX semantics: 0 = copy input dim; -1 = infer
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    if _host_i64(ins[:1]):
        # shape-math flowing AS data (torch expand/reshape chains): stay
        # host so downstream Expand/Reshape see static ints, not tracers
        return np.reshape(x, shape)
    return jnp.reshape(x, shape)


@op("Flatten")
def _flatten(ins, attrs):
    x = ins[0]
    ax = attrs.get("axis", 1)
    if ax < 0:
        ax += x.ndim
    lead = int(np.prod(x.shape[:ax])) if ax > 0 else 1
    return jnp.reshape(x, (lead, -1))


@op("Transpose")
def _transpose(ins, attrs):
    perm = attrs.get("perm")
    return jnp.transpose(ins[0], perm)



def _host_i64(ins) -> bool:
    """True when every present input is a host numpy array of 64-bit ints —
    the shape/index-constant chains (Concat/Cast/Squeeze/Unsqueeze of Slice
    ends etc.). Computing those with numpy preserves INT64 sentinels that
    jnp would wrap to int32 under disabled x64."""
    present = [x for x in ins if x is not None]
    return bool(present) and all(
        isinstance(x, np.ndarray) and x.dtype in (np.int64, np.uint64)
        for x in present)

@op("Concat")
def _concat(ins, attrs):
    xs = [x for x in ins if x is not None]
    if _host_i64(ins):
        return np.concatenate(xs, axis=attrs["axis"])
    return jnp.concatenate(xs, axis=attrs["axis"])


@op("Split")
def _split(ins, attrs):
    x = ins[0]
    axis = attrs.get("axis", 0)
    if len(ins) > 1 and ins[1] is not None:
        sizes = np.cumsum(np.asarray(ins[1]))[:-1]
        return tuple(jnp.split(x, sizes, axis=axis))
    n = attrs.get("num_outputs") or len(attrs.get("split", [])) or 2
    split = attrs.get("split")
    if split:
        return tuple(jnp.split(x, np.cumsum(split)[:-1], axis=axis))
    return tuple(jnp.split(x, n, axis=axis))


@op("Squeeze")
def _squeeze(ins, attrs):
    axes = (tuple(int(a) for a in np.asarray(ins[1]))
            if len(ins) > 1 and ins[1] is not None else attrs.get("axes"))
    xp = np if _host_i64(ins[:1]) else jnp
    return xp.squeeze(ins[0], axis=tuple(axes) if axes else None)


@op("Unsqueeze")
def _unsqueeze(ins, attrs):
    axes = (tuple(int(a) for a in np.asarray(ins[1]))
            if len(ins) > 1 and ins[1] is not None else tuple(attrs.get("axes")))
    x = ins[0]
    xp = np if _host_i64(ins[:1]) else jnp
    for a in sorted(axes):
        x = xp.expand_dims(x, a)
    return x


@op("Slice")
def _slice(ins, attrs):
    x = ins[0]
    if len(ins) > 1:  # opset >= 10: starts/ends/axes/steps as inputs
        starts = [int(v) for v in np.asarray(ins[1])]
        ends = [int(v) for v in np.asarray(ins[2])]
        axes = ([int(v) for v in np.asarray(ins[3])] if len(ins) > 3 and ins[3] is not None
                else list(range(len(starts))))
        steps = ([int(v) for v in np.asarray(ins[4])] if len(ins) > 4 and ins[4] is not None
                 else [1] * len(starts))
    else:
        starts, ends = attrs["starts"], attrs["ends"]
        axes = attrs.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    idx = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        # normalize "to end" sentinels explicitly: exporters emit anything from
        # INT32_MAX to INT64_MAX (positive step) / INT64_MIN (negative step)
        dim = x.shape[a]
        if st > 0:
            e = None if e >= dim else e
        else:
            e = None if e <= -dim - 1 else e
        idx[a] = slice(s, e, st)
    return x[tuple(idx)]


@op("Not")
def _not(ins, attrs):
    return jnp.logical_not(ins[0])


@op("Trilu")
def _trilu(ins, attrs):
    # causal masks: torch.tril/triu export (GPT-style decoders)
    k = int(np.asarray(ins[1])) if len(ins) > 1 and ins[1] is not None else 0
    return jnp.triu(ins[0], k) if attrs.get("upper", 1) else jnp.tril(ins[0], k)


@op("GatherElements")
def _gather_elements(ins, attrs):
    # torch.gather: per-element indexed pick along an axis; ONNX permits
    # negative indices (wrap from the end), which jnp's OOB clamping would
    # otherwise silently send to index 0
    axis = attrs.get("axis", 0)
    idx = jnp.asarray(ins[1]).astype(jnp.int32)
    idx = jnp.where(idx < 0, idx + ins[0].shape[axis], idx)
    return jnp.take_along_axis(ins[0], idx, axis=axis)


@op("Gather")
def _gather(ins, attrs):
    if _host_i64([ins[0]]):
        # shape-math chain (Shape -> Gather -> Range/Reshape): stay host
        # numpy so consumers see static ints, not traced scalars (asarray:
        # np.take with a 0-d index yields a np scalar, which would fail the
        # downstream _host_i64 ndarray check)
        return np.asarray(np.take(ins[0], np.asarray(ins[1]).astype(np.int64),
                                  axis=attrs.get("axis", 0)))
    return jnp.take(ins[0], jnp.asarray(ins[1]).astype(jnp.int32),
                    axis=attrs.get("axis", 0))


@op("Expand")
def _expand(ins, attrs):
    shape = [int(s) for s in np.asarray(ins[1])]
    return jnp.broadcast_to(ins[0], np.broadcast_shapes(ins[0].shape, tuple(shape)))


@op("Pad")
def _pad(ins, attrs):
    x = ins[0]
    pads = (np.asarray(ins[1]).astype(int) if len(ins) > 1 and ins[1] is not None
            else np.asarray(attrs["pads"], int))
    value = float(np.asarray(ins[2])) if len(ins) > 2 and ins[2] is not None else \
        attrs.get("value", 0.0)
    half = len(pads) // 2
    cfg = list(zip(pads[:half], pads[half:]))
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    return jnp.pad(x, cfg, mode={"reflect": "reflect", "edge": "edge"}[mode])


@op("Cast")
def _cast(ins, attrs):
    from . import proto as P

    to = attrs["to"]
    np_dtype = {P.FLOAT: jnp.float32, P.INT64: jnp.int64, P.INT32: jnp.int32,
                P.DOUBLE: jnp.float64, P.BOOL: jnp.bool_, P.FLOAT16: jnp.float16,
                P.BFLOAT16: jnp.bfloat16, P.UINT8: jnp.uint8, P.INT8: jnp.int8}[to]
    if isinstance(ins[0], np.ndarray) and to == P.INT64:
        return ins[0].astype(np.int64)  # keep host int64 (sentinel-safe)
    return ins[0].astype(np_dtype)


@op("Shape")
def _shape(ins, attrs):
    return np.asarray(ins[0].shape, np.int64)  # static under jit


@op("ConstantOfShape")
def _constant_of_shape(ins, attrs):
    shape = [int(s) for s in np.asarray(ins[0])]
    val = attrs.get("value")
    v = np.asarray(val).ravel()[0] if val is not None else 0.0
    dt = np.asarray(val).dtype if val is not None else np.float32
    if np.issubdtype(dt, np.integer) and np.dtype(dt).itemsize == 8:
        # int64 fills are shape/index constants (torch expand chains compare
        # them to -1): stay host, like int64 initializers/Constants — under
        # jit, jnp.full would stage to a tracer and poison shape consumers
        return np.full(shape, v, dtype=dt)
    return jnp.full(shape, v, dtype=dt)


@op("Range")
def _range(ins, attrs):
    start, limit, delta = (int(np.asarray(v)) for v in ins[:3])
    return jnp.arange(start, limit, delta)


@op("Identity")
def _identity(ins, attrs):
    return ins[0]


@op("Dropout")
def _dropout(ins, attrs):
    return ins[0]  # inference mode


@op("Constant")
def _constant(ins, attrs):
    # ALWAYS host numpy: under jit, jnp.asarray stages even a literal into
    # a tracer, poisoning static consumers (Reshape/Expand/Resize scales,
    # int64 index sentinels). Device ops promote host literals on demand.
    for key in ("value", "value_float", "value_int", "value_floats", "value_ints"):
        if key in attrs and attrs[key] is not None:
            return np.asarray(attrs[key])
    raise ValueError("Constant node without value attribute")


# ---------------- reductions ----------------

def _reduce(fn, ins, attrs):
    axes = (tuple(int(a) for a in np.asarray(ins[1]))
            if len(ins) > 1 and ins[1] is not None else attrs.get("axes"))
    # opset-18 axes-as-input: an EMPTY (or entirely omitted) axes tensor with
    # noop_with_empty_axes=1 means identity, not reduce-all
    if (axes is None or len(tuple(axes)) == 0) \
            and attrs.get("noop_with_empty_axes"):
        return ins[0]
    keep = bool(attrs.get("keepdims", 1))
    return fn(ins[0], axis=tuple(axes) if axes else None, keepdims=keep)


@op("ReduceMean")
def _reduce_mean(ins, attrs):
    return _reduce(jnp.mean, ins, attrs)


@op("ReduceSum")
def _reduce_sum(ins, attrs):
    return _reduce(jnp.sum, ins, attrs)


@op("ReduceMax")
def _reduce_max(ins, attrs):
    return _reduce(jnp.max, ins, attrs)


@op("ReduceMin")
def _reduce_min(ins, attrs):
    return _reduce(jnp.min, ins, attrs)


@op("TopK")
def _topk(ins, attrs):
    x = ins[0]
    k = int(np.asarray(ins[1]).ravel()[0])
    axis = attrs.get("axis", -1)
    if axis < 0:
        axis += x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    if attrs.get("largest", 1):
        vals, idx = jax.lax.top_k(moved, k)
    else:
        # smallest-k via ascending argsort — a negation trick would break
        # unsigned dtypes (wraparound) and signed INT_MIN (its own negation)
        idx = jnp.argsort(moved, axis=-1)[..., :k]
        vals = jnp.take_along_axis(moved, idx, axis=-1)
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx.astype(jnp.int32), -1, axis))


@op("ArgMax")
def _argmax(ins, attrs):
    if attrs.get("select_last_index"):
        raise NotImplementedError("ArgMax select_last_index=1")
    out = jnp.argmax(ins[0], axis=attrs.get("axis", 0))
    if attrs.get("keepdims", 1):
        out = jnp.expand_dims(out, attrs.get("axis", 0))
    return out


@op("ReduceProd")
def _reduce_prod(ins, attrs):
    return _reduce(jnp.prod, ins, attrs)


@op("Tile")
def _tile(ins, attrs):
    return jnp.tile(ins[0], tuple(int(r) for r in np.asarray(ins[1])))


# ---------------- elementwise / logic / layout tail ----------------
# (the long tail of ORT's opset behind the reference ONNXModel. NonZero,
# Compress and Unique have dynamically-shaped outputs that XLA's static-shape
# model cannot express — they run in eager (non-jit) execution only, where
# their inputs are concrete; under jit they raise with a clear message.)

def _variadic(fn):
    def handler(ins, attrs):
        out = ins[0]
        for x in ins[1:]:
            out = fn(out, x)
        return out
    return handler


OP_REGISTRY["Min"] = _variadic(jnp.minimum)
OP_REGISTRY["Max"] = _variadic(jnp.maximum)
OP_REGISTRY["Sum"] = _variadic(jnp.add)
OP_REGISTRY["And"] = _variadic(jnp.logical_and)
OP_REGISTRY["Or"] = _variadic(jnp.logical_or)
OP_REGISTRY["Xor"] = _variadic(jnp.logical_xor)


@op("Mean")
def _mean_variadic(ins, attrs):
    return _variadic(jnp.add)(ins, attrs) / len(ins)


for _name, _fn in {
    "Floor": jnp.floor, "Ceil": jnp.ceil, "Round": jnp.round,  # jnp.round IS half-to-even, per spec
    "Sign": jnp.sign, "Reciprocal": lambda x: 1.0 / x,
    "Softplus": jax.nn.softplus,
    "Softsign": lambda x: x / (1 + jnp.abs(x)),
    "Mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "IsNaN": jnp.isnan,
}.items():
    OP_REGISTRY[_name] = (lambda f: lambda ins, attrs: f(ins[0]))(_fn)


@op("Mod")
def _mod(ins, attrs):
    if attrs.get("fmod"):
        return jnp.fmod(ins[0], ins[1])
    return jnp.mod(ins[0], ins[1])  # sign follows divisor, the ONNX int default


@op("PRelu")
def _prelu(ins, attrs):
    x, slope = ins[0], ins[1]
    return jnp.where(x < 0, slope * x, x)


@op("Elu")
def _elu(ins, attrs):
    a = attrs.get("alpha", 1.0)
    x = ins[0]
    return jnp.where(x < 0, a * (jnp.exp(x) - 1.0), x)


@op("Selu")
def _selu(ins, attrs):
    a = attrs.get("alpha", 1.67326319217681884765625)
    g = attrs.get("gamma", 1.05070102214813232421875)
    x = ins[0]
    return g * jnp.where(x < 0, a * (jnp.exp(x) - 1.0), x)


@op("Celu")
def _celu(ins, attrs):
    a = attrs.get("alpha", 1.0)
    x = ins[0]
    return jnp.maximum(x, 0) + jnp.minimum(0, a * (jnp.exp(x / a) - 1.0))


@op("ThresholdedRelu")
def _thresholded_relu(ins, attrs):
    a = attrs.get("alpha", 1.0)
    return jnp.where(ins[0] > a, ins[0], 0.0)


@op("Shrink")
def _shrink(ins, attrs):
    lambd = attrs.get("lambd", 0.5)
    bias = attrs.get("bias", 0.0)
    x = ins[0]
    return jnp.where(x < -lambd, x + bias, jnp.where(x > lambd, x - bias, 0.0))


@op("IsInf")
def _isinf(ins, attrs):
    x = ins[0]
    pos = bool(attrs.get("detect_positive", 1))
    neg = bool(attrs.get("detect_negative", 1))
    return ((jnp.isposinf(x) & pos) | (jnp.isneginf(x) & neg))


@op("GreaterOrEqual")
def _greater_equal(ins, attrs):
    return ins[0] >= ins[1]


@op("LessOrEqual")
def _less_equal(ins, attrs):
    return ins[0] <= ins[1]


@op("BitShift")
def _bit_shift(ins, attrs):
    if attrs.get("direction") == "LEFT":
        return jnp.left_shift(ins[0], ins[1])
    return jnp.right_shift(ins[0], ins[1])


@op("CumSum")
def _cumsum(ins, attrs):
    x = ins[0]
    axis = int(np.asarray(ins[1]).ravel()[0])
    if attrs.get("reverse"):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive"):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        out = jnp.pad(out, pad)
        out = jax.lax.slice_in_dim(out, 0, x.shape[axis], axis=axis)
    if attrs.get("reverse"):
        out = jnp.flip(out, axis)
    return out


@op("OneHot")
def _one_hot(ins, attrs):
    indices, depth, values = ins[0], int(np.asarray(ins[1]).ravel()[0]), ins[2]
    axis = attrs.get("axis", -1)
    idx = jnp.asarray(indices)
    idx = jnp.where(idx < 0, idx + depth, idx)           # negative wrap, per spec
    # select via boolean mask, not float blending — off/on keep their exact
    # dtype (int64 on-values above 2^24 would corrupt through float32)
    oh = jax.nn.one_hot(idx, depth, axis=axis, dtype=jnp.bool_)
    vals = jnp.asarray(values)
    return jnp.where(oh, vals[1], vals[0])


@op("ArgMin")
def _argmin(ins, attrs):
    if attrs.get("select_last_index"):
        raise NotImplementedError("ArgMin select_last_index=1")
    out = jnp.argmin(ins[0], axis=attrs.get("axis", 0))
    if attrs.get("keepdims", 1):
        out = jnp.expand_dims(out, attrs.get("axis", 0))
    return out


@op("ReduceL1")
def _reduce_l1(ins, attrs):
    return _reduce(lambda x, axis, keepdims: jnp.sum(jnp.abs(x), axis=axis,
                                                     keepdims=keepdims),
                   ins, attrs)


@op("ReduceL2")
def _reduce_l2(ins, attrs):
    return _reduce(lambda x, axis, keepdims: jnp.sqrt(
        jnp.sum(x * x, axis=axis, keepdims=keepdims)), ins, attrs)


@op("ReduceSumSquare")
def _reduce_sum_square(ins, attrs):
    return _reduce(lambda x, axis, keepdims: jnp.sum(x * x, axis=axis,
                                                     keepdims=keepdims),
                   ins, attrs)


@op("ReduceLogSum")
def _reduce_log_sum(ins, attrs):
    return _reduce(lambda x, axis, keepdims: jnp.log(
        jnp.sum(x, axis=axis, keepdims=keepdims)), ins, attrs)


@op("ReduceLogSumExp")
def _reduce_log_sum_exp(ins, attrs):
    import jax.scipy.special as jsp

    return _reduce(lambda x, axis, keepdims: jsp.logsumexp(
        x, axis=axis, keepdims=keepdims), ins, attrs)


@op("DepthToSpace")
def _depth_to_space(ins, attrs):
    x = ins[0]
    b = int(attrs["blocksize"])
    N, C, H, W = x.shape
    if attrs.get("mode", "DCR") == "CRD":
        t = x.reshape(N, C // (b * b), b, b, H, W)
        t = jnp.transpose(t, (0, 1, 4, 2, 5, 3))
    else:                                                # DCR (default)
        t = x.reshape(N, b, b, C // (b * b), H, W)
        t = jnp.transpose(t, (0, 3, 4, 1, 5, 2))
    return t.reshape(N, C // (b * b), H * b, W * b)


@op("SpaceToDepth")
def _space_to_depth(ins, attrs):
    x = ins[0]
    b = int(attrs["blocksize"])
    N, C, H, W = x.shape
    t = x.reshape(N, C, H // b, b, W // b, b)
    t = jnp.transpose(t, (0, 3, 5, 1, 2, 4))
    return t.reshape(N, C * b * b, H // b, W // b)


@op("ReverseSequence")
def _reverse_sequence(ins, attrs):
    x, seq_lens = jnp.asarray(ins[0]), jnp.asarray(ins[1])
    batch_axis = attrs.get("batch_axis", 1)
    time_axis = attrs.get("time_axis", 0)
    T = x.shape[time_axis]
    t_idx = jnp.arange(T)
    # per-batch: first len[b] entries reversed, the rest untouched
    rev = jnp.where(t_idx[None, :] < seq_lens[:, None],
                    seq_lens[:, None] - 1 - t_idx[None, :],
                    t_idx[None, :])                      # [B, T]
    xb = jnp.moveaxis(x, (batch_axis, time_axis), (0, 1))
    out = jax.vmap(lambda row, idx: jnp.take(row, idx, axis=0))(xb, rev)
    return jnp.moveaxis(out, (0, 1), (batch_axis, time_axis))


@op("EyeLike")
def _eye_like(ins, attrs):
    from .proto import _DTYPE_TO_NP

    x = ins[0]
    # x.dtype works on tracers too; np.asarray would concretize under jit
    dtype = _DTYPE_TO_NP[attrs["dtype"]] if "dtype" in attrs else x.dtype
    return jnp.eye(x.shape[0], x.shape[1], k=attrs.get("k", 0), dtype=dtype)


@op("Size")
def _size(ins, attrs):
    return np.asarray(int(np.prod(np.shape(ins[0]))), np.int64)


# ---------------- quantization family ----------------
# The reference's ONNXModel runs QDQ-quantized exports through ONNX Runtime
# (ONNXRuntime.scala:25). TPU-native lowering: the integer matmul stays
# integer (int32 accumulation — exact per spec), rounding is
# round-half-to-even (jnp.round), saturation to the zero-point dtype.

def _per_axis(scale, zp, ndim, axis):
    """Broadcast per-axis scale/zero_point to the tensor rank."""
    scale = jnp.asarray(scale, jnp.float32)
    if zp is not None:
        zp = jnp.asarray(zp)
    if scale.ndim == 1 and scale.size > 1:
        shape = [1] * ndim
        shape[axis] = scale.size
        scale = scale.reshape(shape)
        if zp is not None and zp.ndim == 1:
            zp = zp.reshape(shape)
    return scale, zp


def _saturate(x, dtype):
    info = jnp.iinfo(dtype)
    return jnp.clip(x, info.min, info.max).astype(dtype)


@op("QuantizeLinear")
def _quantize_linear(ins, attrs):
    x, scale = ins[0], ins[1]
    zp = ins[2] if len(ins) > 2 and ins[2] is not None else None
    dtype = zp.dtype if zp is not None else jnp.uint8
    scale, zp_b = _per_axis(scale, zp, x.ndim, attrs.get("axis", 1))
    q = jnp.round(x.astype(jnp.float32) / scale)
    if zp_b is not None:
        q = q + zp_b.astype(jnp.float32)
    return _saturate(q, dtype)


@op("DequantizeLinear")
def _dequantize_linear(ins, attrs):
    x, scale = ins[0], ins[1]
    zp = ins[2] if len(ins) > 2 and ins[2] is not None else None
    scale, zp_b = _per_axis(scale, zp, x.ndim, attrs.get("axis", 1))
    xf = x.astype(jnp.float32)
    if zp_b is not None:
        xf = xf - zp_b.astype(jnp.float32)
    return xf * scale


@op("DynamicQuantizeLinear")
def _dynamic_quantize_linear(ins, attrs):
    x = ins[0].astype(jnp.float32)
    # spec: range must include 0 so the zero point is representable
    lo = jnp.minimum(jnp.min(x), 0.0)
    hi = jnp.maximum(jnp.max(x), 0.0)
    scale = (hi - lo) / 255.0
    scale = jnp.where(scale == 0, 1.0, scale)  # all-zero input
    zp = _saturate(jnp.round(-lo / scale), jnp.uint8)
    y = _saturate(jnp.round(x / scale) + zp.astype(jnp.float32), jnp.uint8)
    return y, scale, zp


def _int_matmul(a, a_zp, b, b_zp):
    """(a - a_zp) @ (b - b_zp) in int32 — exact integer accumulation.

    Per-row ``a_zp`` (shape [M]) applies along a's second-to-last axis;
    per-column ``b_zp`` (shape [N]) broadcasts along b's last axis as-is.
    """
    a32, b32 = a.astype(jnp.int32), b.astype(jnp.int32)
    if a_zp is not None:
        z = a_zp.astype(jnp.int32)
        a32 = a32 - (z[..., :, None] if z.ndim >= 1 and z.size > 1 else z)
    if b_zp is not None:
        b32 = b32 - b_zp.astype(jnp.int32)
    return jnp.matmul(a32, b32, preferred_element_type=jnp.int32)


@op("MatMulInteger")
def _matmul_integer(ins, attrs):
    a, b = ins[0], ins[1]
    a_zp = ins[2] if len(ins) > 2 and ins[2] is not None else None
    b_zp = ins[3] if len(ins) > 3 and ins[3] is not None else None
    return _int_matmul(a, a_zp, b, b_zp)


@op("QLinearMatMul")
def _qlinear_matmul(ins, attrs):
    # accumulation is exact int32; the requantize multiply happens in f32,
    # so outputs can differ from ORT's by one quantization step once
    # |acc| > 2^24 (K ~ 1024 at full-range int8 inputs) — same bound as
    # QLinearConv, inherent to f32-only TPU arithmetic
    a, a_scale, a_zp, b, b_scale, b_zp, y_scale, y_zp = ins[:8]
    acc = _int_matmul(a, a_zp, b, b_zp).astype(jnp.float32)
    a_s = jnp.asarray(a_scale, jnp.float32)
    if a_s.ndim >= 1 and a_s.size > 1:          # per-row: align to M axis
        a_s = a_s[..., :, None]
    mult = a_s * jnp.asarray(b_scale, jnp.float32) \
        / jnp.asarray(y_scale, jnp.float32)
    y = jnp.round(acc * mult) + y_zp.astype(jnp.float32)
    return _saturate(y, y_zp.dtype)


@op("QLinearConv")
def _qlinear_conv(ins, attrs):
    x, x_scale, x_zp, w, w_scale, w_zp, y_scale, y_zp = ins[:8]
    bias = ins[8] if len(ins) > 8 else None
    # integer-valued float conv: products |x-zp|*|w-zp| <= 2^14 summed over
    # the receptive field stay exact in f32 up to 2^24 — exact for any
    # realistic kernel volume (3x3x64*16k = 2^23)
    xf = x.astype(jnp.float32) - x_zp.astype(jnp.float32)
    w_scale_arr = jnp.asarray(w_scale, jnp.float32)
    wzf = w_zp.astype(jnp.float32)
    if wzf.ndim == 1 and wzf.size > 1:          # per-output-channel zp
        wzf = wzf.reshape((-1,) + (1,) * (w.ndim - 1))
    wf = w.astype(jnp.float32) - wzf
    acc = OP_REGISTRY["Conv"]([xf, wf], attrs)   # [N, M, *spatial]
    if bias is not None:                          # int32, scale = x_scale*w_scale
        acc = acc + bias.astype(jnp.float32).reshape(
            (1, -1) + (1,) * (acc.ndim - 2))
    mult = jnp.asarray(x_scale, jnp.float32) * w_scale_arr \
        / jnp.asarray(y_scale, jnp.float32)
    if mult.ndim == 1 and mult.size > 1:          # per-output-channel scale
        mult = mult.reshape((1, -1) + (1,) * (acc.ndim - 2))
    y = jnp.round(acc * mult) + y_zp.astype(jnp.float32)
    return _saturate(y, y_zp.dtype)


# ---------------- advanced indexing / detection ----------------

@op("GatherND")
def _gather_nd(ins, attrs):
    # jit-safe: indices may be runtime tensors (NMS/TopK outputs), never
    # force them to host numpy
    x, indices = jnp.asarray(ins[0]), jnp.asarray(ins[1]).astype(jnp.int32)
    b = attrs.get("batch_dims", 0)

    def gather(data, idx):
        return data[tuple(jnp.moveaxis(idx, -1, 0))]

    fn = gather
    for _ in range(b):
        fn = jax.vmap(fn)
    return fn(x, indices)


def _axis_index_grids(x, indices, axis):
    """Full index tuple for scatter/gather-elements: iota grids everywhere
    except ``axis``, where ``indices`` (negative values wrapped) is used."""
    idx = jnp.where(indices < 0, indices + x.shape[axis], indices)
    grids = jnp.indices(indices.shape, sparse=True)
    return tuple(idx if d == axis else grids[d] for d in range(x.ndim))


def _apply_reduction(at, updates, red):
    if red == "add":
        return at.add(updates)
    if red == "mul":
        return at.multiply(updates)
    if red == "min":
        return at.min(updates)
    if red == "max":
        return at.max(updates)
    if red in ("none", None, ""):
        return at.set(updates)
    raise NotImplementedError(f"scatter reduction {red!r}")


@op("ScatterElements")
def _scatter_elements(ins, attrs):
    x, indices, updates = jnp.asarray(ins[0]), jnp.asarray(ins[1]), ins[2]
    axis = attrs.get("axis", 0)
    if axis < 0:
        axis += x.ndim
    at = x.at[_axis_index_grids(x, indices, axis)]
    return _apply_reduction(at, updates, attrs.get("reduction", "none"))


@op("ScatterND")
def _scatter_nd(ins, attrs):
    x = jnp.asarray(ins[0])
    indices, updates = jnp.asarray(ins[1]).astype(jnp.int32), ins[2]
    at = x.at[tuple(jnp.moveaxis(indices, -1, 0))]
    return _apply_reduction(at, updates, attrs.get("reduction", "none"))


@op("NonMaxSuppression")
def _non_max_suppression(ins, attrs):
    """Greedy per-(batch, class) NMS (the ONNX RT detection-head tail op).

    ONNX declares a dynamic [num_selected, 3] output; XLA needs static
    shapes, so the output has ``B * C * min(max_output_boxes_per_class, N)``
    rows, laid out as consecutive per-(batch, class) blocks with unused
    slots inside EACH block padded as [-1, -1, -1] rows (padding is
    interleaved per block, not gathered at the tail). Downstream consumers
    filter ``row[0] >= 0``.
    """
    boxes, scores = jnp.asarray(ins[0]), jnp.asarray(ins[1])  # [B,N,4], [B,C,N]
    if len(ins) > 2 and ins[2] is not None:
        if isinstance(ins[2], jax.core.Tracer):
            raise NotImplementedError(
                "NonMaxSuppression: max_output_boxes_per_class must be a "
                "constant/initializer (it fixes the static output shape)")
        max_out = int(np.asarray(ins[2]).ravel()[0])
    else:
        max_out = 0
    # thresholds may be runtime tensors — keep them traced
    iou_thr = (jnp.asarray(ins[3], jnp.float32).reshape(())
               if len(ins) > 3 and ins[3] is not None else jnp.float32(0.0))
    score_thr = (jnp.asarray(ins[4], jnp.float32).reshape(())
                 if len(ins) > 4 and ins[4] is not None else jnp.float32(-np.inf))
    B, N = boxes.shape[0], boxes.shape[1]
    C = scores.shape[1]
    if max_out <= 0 or N == 0:
        return jnp.zeros((0, 3), jnp.int32)
    max_out = min(max_out, N)

    if attrs.get("center_point_box", 0):
        xc, yc, w, h = (boxes[..., i] for i in range(4))
        y1, x1 = yc - h / 2, xc - w / 2
        y2, x2 = yc + h / 2, xc + w / 2
    else:
        # corners in either order per spec
        y1 = jnp.minimum(boxes[..., 0], boxes[..., 2])
        y2 = jnp.maximum(boxes[..., 0], boxes[..., 2])
        x1 = jnp.minimum(boxes[..., 1], boxes[..., 3])
        x2 = jnp.maximum(boxes[..., 1], boxes[..., 3])
    area = (y2 - y1) * (x2 - x1)                          # [B, N]

    def iou(b, i):                                        # [N] IoU vs box i
        yy1 = jnp.maximum(y1[b], y1[b, i])
        yy2 = jnp.minimum(y2[b], y2[b, i])
        xx1 = jnp.maximum(x1[b], x1[b, i])
        xx2 = jnp.minimum(x2[b], x2[b, i])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area[b] + area[b, i] - inter, 1e-12)

    def one_class(b, sc):                                 # sc: [N] scores
        def step(carry, k):
            alive, out_idx = carry
            masked = jnp.where(alive, sc, -jnp.inf)
            i = jnp.argmax(masked)
            ok = masked[i] > score_thr
            suppress = iou(b, i) > iou_thr
            alive = alive & ~suppress & (jnp.arange(N) != i) & ok
            out_idx = out_idx.at[k].set(jnp.where(ok, i, -1))
            return (alive, out_idx), None

        init = (jnp.ones(N, bool), jnp.full((max_out,), -1, jnp.int32))
        (_, out_idx), _ = jax.lax.scan(step, init, jnp.arange(max_out))
        return out_idx                                    # [max_out]

    rows = []
    for b in range(B):                                    # B, C are static
        sel = jax.vmap(lambda sc, b=b: one_class(b, sc))(scores[b])  # [C, max_out]
        for c in range(C):
            bc = jnp.stack([jnp.where(sel[c] >= 0, b, -1),
                            jnp.where(sel[c] >= 0, c, -1),
                            sel[c]], axis=-1)             # [max_out, 3]
            rows.append(bc)
    return jnp.concatenate(rows, axis=0).astype(jnp.int32)


# ---------------- trig / hyperbolic / misc unary tail ----------------

for _name, _fn in {
    "Tan": jnp.tan, "Asin": jnp.arcsin, "Acos": jnp.arccos,
    "Atan": jnp.arctan, "Sinh": jnp.sinh, "Cosh": jnp.cosh,
    "Asinh": jnp.arcsinh, "Acosh": jnp.arccosh, "Atanh": jnp.arctanh,
}.items():
    OP_REGISTRY[_name] = (lambda f: lambda ins, attrs: f(ins[0]))(_fn)


@op("Hardmax")
def _hardmax(ins, attrs):
    """One-hot of the argmax along ``axis`` (opset-13 elementwise semantics;
    ties go to the first index, matching ORT)."""
    x = ins[0]
    axis = attrs.get("axis", -1)
    return jax.nn.one_hot(jnp.argmax(x, axis=axis), x.shape[axis], axis=axis,
                          dtype=x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                          else jnp.float32)


@op("LRN")
def _lrn(ins, attrs):
    """AlexNet-era local response normalization over the channel axis (NCHW):
    y = x / (bias + alpha/size * sum_window x^2)^beta. The cross-channel
    window sum is a sum of ``size`` channel-shifted slices — XLA fuses these
    into one pass, no conv needed."""
    x = ins[0]
    size = int(attrs["size"])
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    bias = attrs.get("bias", 1.0)
    C = x.shape[1]
    lo = (size - 1) // 2          # window: [c - lo, c + (size - 1 - lo)]
    hi = size - 1 - lo
    pad = [(0, 0), (lo, hi)] + [(0, 0)] * (x.ndim - 2)
    sq = jnp.pad(jnp.square(x.astype(jnp.float32)), pad)
    acc = sum(jax.lax.slice_in_dim(sq, i, i + C, axis=1) for i in range(size))
    return (x / jnp.power(bias + (alpha / size) * acc, beta)).astype(x.dtype)


@op("LpNormalization")
def _lp_normalization(ins, attrs):
    x = ins[0]
    axis = attrs.get("axis", -1)
    p = attrs.get("p", 2)
    if p == 1:
        n = jnp.sum(jnp.abs(x), axis=axis, keepdims=True)
    elif p == 2:
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        raise NotImplementedError(f"LpNormalization p={p} (spec allows 1 or 2)")
    return x / n


@op("GlobalLpPool")
def _global_lp_pool(ins, attrs):
    x = ins[0]
    p = attrs.get("p", 2)
    axes = tuple(range(2, x.ndim))
    out = jnp.sum(jnp.abs(x.astype(jnp.float32)) ** p, axis=axes,
                  keepdims=True) ** (1.0 / p)
    return out.astype(x.dtype)


# ---------------- spatial sampling / losses / opset-18 tail ----------------


def _denorm_coord(g, size, align_corners):
    # normalized [-1, 1] -> pixel coordinates per the GridSample spec
    if align_corners:
        return (g + 1.0) * 0.5 * (size - 1)
    return ((g + 1.0) * size - 1.0) * 0.5


def _reflect_coord(c, lo, hi):
    # reflect into [lo, hi] with period 2*(hi-lo) (border pixels not doubled
    # in the align_corners sense ORT uses for padding_mode='reflection').
    # A degenerate span (size-1 dim under align_corners) has nothing to
    # reflect — everything maps to the single coordinate (mod 0 is NaN).
    span = hi - lo
    if span <= 0:
        return jnp.full_like(c, lo)
    c = jnp.abs(c - lo)
    c = jnp.mod(c, 2 * span)
    return jnp.where(c > span, 2 * span - c, c) + lo


@op("GridSample")
def _grid_sample(ins, attrs):
    """Spatial-transformer sampling (opset 16+, 4D): for each output pixel,
    sample the input at a grid-supplied normalized coordinate. Gathers are
    XLA ``gather`` ops — batched via one advanced-index per corner."""
    x, grid = ins[0], ins[1]
    mode = attrs.get("mode", b"bilinear")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    if mode == "linear":
        mode = "bilinear"
    pad = attrs.get("padding_mode", b"zeros")
    pad = pad.decode() if isinstance(pad, bytes) else pad
    align = bool(attrs.get("align_corners", 0))
    if x.ndim != 4:
        raise NotImplementedError("GridSample: only 4D (NCHW) supported")
    N, C, H, W = x.shape
    gx = _denorm_coord(grid[..., 0].astype(jnp.float32), W, align)  # [N,Ho,Wo]
    gy = _denorm_coord(grid[..., 1].astype(jnp.float32), H, align)

    if pad == "reflection":
        if align:
            gx, gy = _reflect_coord(gx, 0.0, W - 1), _reflect_coord(gy, 0.0, H - 1)
        else:
            gx = jnp.clip(_reflect_coord(gx, -0.5, W - 0.5), 0, W - 1)
            gy = jnp.clip(_reflect_coord(gy, -0.5, H - 0.5), 0, H - 1)
        pad = "border"  # reflected coords are in range; sample like border

    def sample_int(ix, iy):
        # gather x[n, :, iy, ix] with clipped indices; [N, C, Ho, Wo]
        ixc = jnp.clip(ix, 0, W - 1)
        iyc = jnp.clip(iy, 0, H - 1)
        n_idx = jnp.arange(N)[:, None, None]
        vals = x[n_idx, :, iyc, ixc]            # [N, Ho, Wo, C]
        vals = jnp.moveaxis(vals, -1, 1)        # [N, C, Ho, Wo]
        if pad == "zeros":
            inb = ((ix >= 0) & (ix <= W - 1) & (iy >= 0)
                   & (iy <= H - 1))[:, None, :, :]
            vals = vals * inb.astype(vals.dtype)
        return vals

    if mode == "nearest":
        out = sample_int(jnp.round(gx).astype(jnp.int32),
                         jnp.round(gy).astype(jnp.int32))
    elif mode == "bilinear":
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        wx = (gx - x0)[:, None, :, :].astype(x.dtype)
        wy = (gy - y0)[:, None, :, :].astype(x.dtype)
        if pad == "border":
            # clamp the CONTINUOUS coordinate first so the two corners
            # straddle the clamped point (matches ORT border semantics)
            gxc = jnp.clip(gx, 0, W - 1)
            gyc = jnp.clip(gy, 0, H - 1)
            x0 = jnp.floor(gxc).astype(jnp.int32)
            y0 = jnp.floor(gyc).astype(jnp.int32)
            wx = (gxc - x0)[:, None, :, :].astype(x.dtype)
            wy = (gyc - y0)[:, None, :, :].astype(x.dtype)
        out = (sample_int(x0, y0) * (1 - wx) * (1 - wy)
               + sample_int(x0 + 1, y0) * wx * (1 - wy)
               + sample_int(x0, y0 + 1) * (1 - wx) * wy
               + sample_int(x0 + 1, y0 + 1) * wx * wy)
    else:
        raise NotImplementedError(f"GridSample mode {mode!r}")
    return out


@op("AffineGrid")
def _affine_grid(ins, attrs):
    """Opset-20 AffineGrid (the torch ``affine_grid`` lowering): batched
    affine maps over a normalized base grid, feeding GridSample."""
    theta = jnp.asarray(ins[0], jnp.float32)
    size = [int(v) for v in np.asarray(ins[1])]
    align = bool(attrs.get("align_corners", 0))

    def coords(n):
        if align:
            return (jnp.linspace(-1.0, 1.0, n) if n > 1
                    else jnp.zeros((1,), jnp.float32))
        return (2.0 * jnp.arange(n) + 1.0) / n - 1.0

    if len(size) == 4:                       # 2D: [N, C, H, W] -> [N,H,W,2]
        _, _, H, W = size
        gx, gy = jnp.meshgrid(coords(W), coords(H))
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
        return jnp.einsum("hwk,nik->nhwi", base, theta)
    if len(size) == 5:                       # 3D: [N, C, D, H, W]
        _, _, D, H, W = size
        gz, gy, gx = jnp.meshgrid(coords(D), coords(H), coords(W),
                                  indexing="ij")
        base = jnp.stack([gx, gy, gz, jnp.ones_like(gx)], axis=-1)
        return jnp.einsum("dhwk,nik->ndhwi", base, theta)
    raise NotImplementedError(f"AffineGrid size rank {len(size)}")


@op("RoiAlign")
def _roi_align(ins, attrs):
    """Mask-R-CNN ROI pooling (opset 16): bilinear samples on a fixed grid
    per output bin, averaged (or maxed). ``sampling_ratio=0`` (adaptive,
    data-dependent grid) is approximated with a fixed 2x2 grid per bin —
    static shapes are the XLA constraint; torch exports set the ratio
    explicitly."""
    x = jnp.asarray(ins[0])  # numpy input + traced roi index can't mix
    rois, batch_idx = ins[1], ins[2]
    out_h = int(attrs.get("output_height", 1))
    out_w = int(attrs.get("output_width", 1))
    ratio = int(attrs.get("sampling_ratio", 0)) or 2
    scale = float(attrs.get("spatial_scale", 1.0))
    mode = attrs.get("mode", b"avg")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    ctm = attrs.get("coordinate_transformation_mode", b"half_pixel")
    ctm = ctm.decode() if isinstance(ctm, bytes) else ctm
    N, C, H, W = x.shape
    half_pixel = ctm == "half_pixel"
    offset = 0.5 if half_pixel else 0.0
    r = rois.astype(jnp.float32) * scale - offset    # [R, 4] x1 y1 x2 y2

    def one_roi(roi, b):
        x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
        rw, rh = x2 - x1, y2 - y1
        if not half_pixel:  # ORT applies the legacy >=1 clamp ONLY here
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_w, bin_h = rw / out_w, rh / out_h
        # sample centers: out_{h,w} bins x ratio points per bin
        sx = x1 + (jnp.arange(out_w * ratio) + 0.5) * (bin_w / ratio)
        sy = y1 + (jnp.arange(out_h * ratio) + 0.5) * (bin_h / ratio)
        gx, gy = jnp.meshgrid(sx, sy)              # [oh*r, ow*r]
        # ORT sample semantics: a point past the 1-pixel halo contributes
        # zero; anything else is CLAMPED into the image (border pixels at
        # full weight), never corner-zeroed
        empty = (gx < -1.0) | (gx > W) | (gy < -1.0) | (gy > H)
        gx = jnp.clip(gx, 0.0, W - 1)
        gy = jnp.clip(gy, 0.0, H - 1)
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        wx, wy = gx - x0, gy - y0

        def corner(ix, iy):
            v = x[b, :, jnp.clip(iy, 0, H - 1), jnp.clip(ix, 0, W - 1)]
            return jnp.moveaxis(v, -1, 0)

        live = (~empty).astype(x.dtype)
        contribs = (corner(x0, y0) * (1 - wx) * (1 - wy) * live,
                    corner(x0 + 1, y0) * wx * (1 - wy) * live,
                    corner(x0, y0 + 1) * (1 - wx) * wy * live,
                    corner(x0 + 1, y0 + 1) * wx * wy * live)
        if mode == "max":
            # ORT max mode: max over the WEIGHTED corner contributions of
            # every sample, not max of interpolated values
            vals = jnp.max(jnp.stack(contribs), axis=0)
            vals = vals.reshape(C, out_h, ratio, out_w, ratio)
            return jnp.max(vals, axis=(2, 4))
        vals = sum(contribs).reshape(C, out_h, ratio, out_w, ratio)
        return jnp.mean(vals, axis=(2, 4))

    return jax.vmap(one_roi)(r, batch_idx.astype(jnp.int32))


@op("GroupNormalization")
def _group_norm(ins, attrs):
    """Opset-18 GroupNormalization (the diffusion-UNet norm): normalize over
    each of ``num_groups`` channel groups. Handles both the opset-18
    per-group and opset-21 per-channel scale/bias shapes."""
    x, scale, bias = ins[0], ins[1], ins[2]
    eps = attrs.get("epsilon", 1e-5)
    G = int(attrs["num_groups"])
    N, C = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape(N, G, C // G, *spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    if scale.shape[0] == G != C:  # opset-18 per-group parameters
        scale = jnp.repeat(scale, C // G)
        bias = jnp.repeat(bias, C // G)
    shape = (1, C) + (1,) * len(spatial)
    return y * scale.reshape(shape) + bias.reshape(shape)


@op("MeanVarianceNormalization")
def _mvn(ins, attrs):
    x = ins[0]
    axes = tuple(attrs.get("axes", (0, 2, 3)))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    std = jnp.sqrt(jnp.var(x, axis=axes, keepdims=True))
    return (x - mean) / (std + 1e-9)


@op("BitwiseAnd")
def _bitwise_and(ins, attrs):
    return jnp.bitwise_and(ins[0], ins[1])


@op("BitwiseOr")
def _bitwise_or(ins, attrs):
    return jnp.bitwise_or(ins[0], ins[1])


@op("BitwiseXor")
def _bitwise_xor(ins, attrs):
    return jnp.bitwise_xor(ins[0], ins[1])


@op("BitwiseNot")
def _bitwise_not(ins, attrs):
    return jnp.bitwise_not(ins[0])


@op("CenterCropPad")
def _center_crop_pad(ins, attrs):
    """Opset-18: center-crop dims larger than the target, center-pad (zeros)
    dims smaller. ``shape`` input must be static (XLA shapes)."""
    x = ins[0]
    target = np.asarray(ins[1], np.int64)
    axes = attrs.get("axes")
    axes = (list(range(x.ndim)) if axes is None
            else [int(a) % x.ndim for a in axes])
    out = x
    for a, t in zip(axes, target.tolist()):
        cur = out.shape[a]
        if cur > t:  # crop, extra pixel goes to the end slice
            start = (cur - t) // 2
            out = jax.lax.slice_in_dim(out, start, start + t, axis=a)
        elif cur < t:
            before = (t - cur) // 2
            pads = [(0, 0, 0)] * out.ndim
            pads[a] = (before, t - cur - before, 0)
            out = jax.lax.pad(out, jnp.zeros((), out.dtype), pads)
    return out


def _nll_core(log_prob, labels, weights, reduction, ignore_index):
    """Shared NegativeLogLikelihoodLoss / SoftmaxCrossEntropyLoss core:
    gather -log p[label], apply class weights, mask ignore_index, reduce."""
    C = log_prob.shape[1]
    labels = labels.astype(jnp.int32)
    valid = (jnp.ones_like(labels, dtype=bool) if ignore_index is None
             else labels != ignore_index)
    safe = jnp.where(valid, labels, 0)
    picked = -jnp.take_along_axis(
        log_prob, safe[:, None] if log_prob.ndim == 2
        else safe[:, None, ...], axis=1).squeeze(1)
    w = (jnp.ones((C,), log_prob.dtype) if weights is None
         else weights.astype(log_prob.dtype))
    wl = jnp.take(w, safe) * valid.astype(log_prob.dtype)
    loss = picked * wl
    reduction = (reduction.decode()
                 if isinstance(reduction, bytes) else reduction)
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(wl), 1e-9)  # weighted mean


@op("NegativeLogLikelihoodLoss")
def _nll_loss(ins, attrs):
    weights = ins[2] if len(ins) > 2 else None
    return _nll_core(ins[0], ins[1], weights,
                     attrs.get("reduction", "mean"),
                     attrs.get("ignore_index"))


@op("SoftmaxCrossEntropyLoss")
def _softmax_ce_loss(ins, attrs):
    scores, labels = ins[0], ins[1]
    weights = ins[2] if len(ins) > 2 else None
    log_prob = jax.nn.log_softmax(scores, axis=1)
    loss = _nll_core(log_prob, labels, weights,
                     attrs.get("reduction", "mean"),
                     attrs.get("ignore_index"))
    return (loss, log_prob)  # second output is optional (log_prob)


@op("DFT")
def _dft(ins, attrs):
    """Discrete Fourier transform (opset 17 form: axis/inverse/onesided as
    attributes, optional dft_length input). Input trailing dim 1 = real,
    2 = complex; output is [..., 2] re/im along the transformed axis."""
    x = jnp.asarray(ins[0])
    axis = int(attrs.get("axis", 1))
    inverse = bool(attrs.get("inverse", 0))
    onesided = bool(attrs.get("onesided", 0))
    if inverse and onesided:
        raise NotImplementedError("DFT: inverse and onesided are exclusive")
    # axis counts against the FULL rank (component dim included, spec
    # DFT-17); the trailing re/im dim itself is not a valid transform axis
    axis = axis % x.ndim
    if axis == x.ndim - 1:
        raise NotImplementedError(
            "DFT axis must not be the trailing re/im component dimension")
    if x.shape[-1] == 2:
        sig = x[..., 0] + 1j * x[..., 1]
        if onesided:
            raise NotImplementedError(
                "DFT: onesided=1 requires a real input (ORT rejects the "
                "complex combination too)")
    elif x.shape[-1] == 1:
        sig = x[..., 0]
    else:
        raise NotImplementedError(
            f"DFT input trailing dim must be 1 (real) or 2 (complex), "
            f"got {x.shape[-1]}")
    if len(ins) > 1 and ins[1] is not None:
        n = int(np.asarray(ins[1]))
        cur = sig.shape[axis]
        if n < cur:
            sig = jax.lax.slice_in_dim(sig, 0, n, axis=axis)
        elif n > cur:
            pads = [(0, 0, 0)] * sig.ndim
            pads[axis] = (0, n - cur, 0)
            sig = jax.lax.pad(sig, jnp.zeros((), sig.dtype), pads)
    if inverse:
        spec = jnp.fft.ifft(sig, axis=axis)
    elif onesided:
        spec = jnp.fft.rfft(sig, axis=axis)
    else:
        spec = jnp.fft.fft(sig, axis=axis)
    real_dtype = jnp.real(jnp.zeros((), sig.dtype)).dtype
    return jnp.stack([jnp.real(spec), jnp.imag(spec)],
                     axis=-1).astype(real_dtype)


@op("STFT")
def _stft(ins, attrs):
    """Short-time Fourier transform (opset 17, the audio-frontend op):
    frame the signal, window, FFT per frame. Frame geometry must be static
    (XLA shapes); output is ``[B, frames, bins, 2]`` real/imag."""
    signal = jnp.asarray(ins[0])
    step = int(np.asarray(ins[1]))
    window = None if len(ins) <= 2 or ins[2] is None else jnp.asarray(ins[2])
    if len(ins) > 3 and ins[3] is not None:
        frame_len = int(np.asarray(ins[3]))
    elif window is not None:
        frame_len = window.shape[0]
    else:
        raise NotImplementedError("STFT needs window or frame_length")
    onesided = bool(attrs.get("onesided", 1))
    if signal.ndim == 3:
        if signal.shape[-1] == 2:  # complex [B, L, 2] layout
            signal = signal[..., 0] + 1j * signal[..., 1]
        else:  # real [B, L, 1] layout
            signal = signal[..., 0]
    B, L = signal.shape
    n_frames = (L - frame_len) // step + 1
    idx = (jnp.arange(n_frames)[:, None] * step
           + jnp.arange(frame_len)[None, :])        # [frames, frame_len]
    frames = signal[:, idx]                         # [B, frames, frame_len]
    if window is not None:
        frames = frames * window.astype(frames.dtype)
    if onesided and jnp.iscomplexobj(signal):
        raise NotImplementedError(
            "STFT: onesided=1 requires a real input (ORT rejects the "
            "complex combination too)")
    spec = (jnp.fft.rfft(frames, axis=-1) if onesided
            else jnp.fft.fft(frames, axis=-1))
    out = jnp.stack([jnp.real(spec), jnp.imag(spec)], axis=-1)
    real_dtype = jnp.real(jnp.zeros((), signal.dtype)).dtype
    return out.astype(real_dtype)


@op("Col2Im")
def _col2im(ins, attrs):
    """Opset-18 inverse im2col: scatter-ADD column blocks back into the
    image (overlaps accumulate). Block geometry must be static."""
    cols = jnp.asarray(ins[0])                      # [N, C*kh*kw, L]
    image_shape = [int(v) for v in np.asarray(ins[1])]
    block_shape = [int(v) for v in np.asarray(ins[2])]
    if len(image_shape) != 2:
        raise NotImplementedError("Col2Im: only 2D images supported")
    H, W = image_shape
    kh, kw = block_shape
    dh, dw = _pair(attrs.get("dilations"), 1)
    sh, sw = _pair(attrs.get("strides"), 1)
    pads = attrs.get("pads", (0, 0, 0, 0))
    pt, pl, pb, pr = (int(p) for p in pads)
    N = cols.shape[0]
    C = cols.shape[1] // (kh * kw)
    n_h = (H + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
    n_w = (W + pl + pr - (dw * (kw - 1) + 1)) // sw + 1
    x = cols.reshape(N, C, kh, kw, n_h, n_w)
    out = jnp.zeros((N, C, H + pt + pb, W + pl + pr), cols.dtype)
    rows = jnp.arange(n_h) * sh                     # block top edges (padded)
    cs = jnp.arange(n_w) * sw
    for i in range(kh):
        for j in range(kw):
            r = rows + i * dh                       # [n_h]
            c = cs + j * dw                         # [n_w]
            out = out.at[:, :, r[:, None], c[None, :]].add(x[:, :, i, j])
    return out[:, :, pt:pt + H, pl:pl + W]


# ---------------- random-sampling family ----------------
#
# LIMITATION (documented divergence from ORT): a random op inside a Loop/
# Scan body that lowers to lax.scan/lax.while_loop traces ONCE, so its key
# freezes and every iteration draws the same value — ORT draws fresh per
# iteration. Keep random nodes outside compiled loop bodies (or run the
# graph in eager mode, where each iteration re-executes the op).

_UNSEEDED_NODES = itertools.count()


def _rand_key(attrs):
    # the spec's optional float `seed` attr pins a node's stream. Unseeded
    # nodes fold a per-instantiation counter into a fixed base: distinct
    # nodes decorrelate (ORT draws independently per node) while jit keeps
    # replay deterministic — each node traces once, freezing its key.
    seed = attrs.get("seed")
    if seed is not None:
        return jax.random.PRNGKey(np.float32(seed).view(np.int32))
    return jax.random.fold_in(jax.random.PRNGKey(0), next(_UNSEEDED_NODES))


def _rand_dtype(attrs, default=jnp.float32):
    from .proto import _DTYPE_TO_NP

    return _DTYPE_TO_NP[attrs["dtype"]] if "dtype" in attrs else default


@op("RandomNormal")
def _random_normal(ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    x = jax.random.normal(_rand_key(attrs), shape, _rand_dtype(attrs))
    return x * attrs.get("scale", 1.0) + attrs.get("mean", 0.0)


@op("RandomUniform")
def _random_uniform(ins, attrs):
    shape = tuple(int(s) for s in attrs["shape"])
    return jax.random.uniform(_rand_key(attrs), shape, _rand_dtype(attrs),
                              minval=attrs.get("low", 0.0),
                              maxval=attrs.get("high", 1.0))


@op("RandomNormalLike")
def _random_normal_like(ins, attrs):
    x = ins[0]
    y = jax.random.normal(_rand_key(attrs), x.shape,
                          _rand_dtype(attrs, x.dtype))
    return y * attrs.get("scale", 1.0) + attrs.get("mean", 0.0)


@op("RandomUniformLike")
def _random_uniform_like(ins, attrs):
    x = ins[0]
    return jax.random.uniform(_rand_key(attrs), x.shape,
                              _rand_dtype(attrs, x.dtype),
                              minval=attrs.get("low", 0.0),
                              maxval=attrs.get("high", 1.0))


@op("Bernoulli")
def _bernoulli(ins, attrs):
    x = ins[0]
    out = jax.random.bernoulli(_rand_key(attrs),
                               jnp.asarray(x, jnp.float32))
    return out.astype(_rand_dtype(attrs, x.dtype))


@op("Multinomial")
def _multinomial(ins, attrs):
    """Sample class indices from unnormalized LOG probabilities (the spec's
    input is logits-like, matching torch.multinomial on softmax)."""
    x = ins[0]                                    # [batch, classes]
    n = int(attrs.get("sample_size", 1))
    out_dt = _rand_dtype(attrs, jnp.int32)
    keys = jax.random.split(_rand_key(attrs), x.shape[0])
    samples = jax.vmap(lambda k, logits: jax.random.categorical(
        k, logits, shape=(n,)))(keys, jnp.asarray(x, jnp.float32))
    return samples.astype(out_dt)


# ---------------- dynamically-shaped ops (eager execution only) ----------------

def _require_concrete(x, opname: str):
    import jax.core

    if isinstance(x, jax.core.Tracer):
        raise NotImplementedError(
            f"ONNX {opname} has a data-dependent output shape, which XLA's "
            f"static-shape model cannot express — run the model in eager "
            f"(non-jit) mode for this graph")
    return np.asarray(x)


@op("NonZero")
def _nonzero(ins, attrs):
    x = _require_concrete(ins[0], "NonZero")
    # int64 per spec; host numpy so disabled-x64 jnp doesn't clamp indices
    return np.stack(np.nonzero(x)).astype(np.int64)


@op("Compress")
def _compress(ins, attrs):
    # only the CONDITION must be concrete — the data may stay traced (the
    # output shape is known once the mask is)
    cond = _require_concrete(ins[1], "Compress").astype(bool)
    idx = jnp.asarray(np.nonzero(cond)[0].astype(np.int32))
    axis = attrs.get("axis")
    if axis is None:
        return jnp.take(jnp.reshape(ins[0], (-1,)), idx, axis=0)
    return jnp.take(ins[0], idx, axis=int(axis))


@op("Unique")
def _unique(ins, attrs):
    """Y, indices, inverse_indices, counts — all int64 per spec. For
    sorted=0 the uniques are reordered to first-occurrence order (numpy
    always sorts, so the inverse map is re-ranked through the permutation)."""
    x = _require_concrete(ins[0], "Unique")
    axis = attrs.get("axis")
    vals, index, inverse, counts = np.unique(
        x if axis is not None else x.ravel(), axis=axis,
        return_index=True, return_inverse=True, return_counts=True)
    if not attrs.get("sorted", 1):
        order = np.argsort(index, kind="stable")
        rank = np.empty_like(order)
        rank[order] = np.arange(len(order))
        vals = np.take(vals, order, axis=0 if axis is None else axis)
        index, counts = index[order], counts[order]
        inverse = rank[inverse]
    return (vals, index.astype(np.int64), inverse.ravel().astype(np.int64),
            counts.astype(np.int64))


# ---------------------------------------------------------------------------
# graph executor
# ---------------------------------------------------------------------------

def _load_initializers(graph) -> dict:
    """Initializers as env entries; int64 stays host numpy (sentinel-safe).
    Used for If subgraphs (typically a handful of scalars); the top-level
    graph's initializers are decoded once in ConvertedModel.__init__."""
    out = {}
    for t in graph.initializer:
        v = tensor_to_numpy(t)
        out[t.name] = v if v.dtype in (np.int64, np.uint64) else jnp.asarray(v)
    return out


def _exec_nodes(graph, env: dict) -> None:
    """Run a graph's nodes over ``env`` in place (shared by the top-level
    model and If subgraphs, which read outer-scope names per ONNX scoping)."""
    for node in graph.node:
        ins = [env[i] if i else None for i in node.input]
        if node.op_type == "If":
            out = _exec_if(node, ins, env)
        elif node.op_type == "Loop":
            out = _exec_loop(node, ins, env)
        elif node.op_type == "Scan":
            out = _exec_scan(node, ins, env)
        else:
            out = OP_REGISTRY[node.op_type](ins, node.attrs())
        outs = out if isinstance(out, tuple) else (out,)
        for name, val in zip(node.output, outs):
            if name:
                env[name] = val


def _exec_if(node, ins, env: dict):
    """ONNX If. A STATICALLY-resolved condition (the form torch's exporter
    emits for shape guards — host/concrete at trace time) traces exactly one
    branch. A traced (data-dependent) condition lowers to ``lax.cond`` when
    both branches produce matching shapes/dtypes — XLA's conditional, both
    branches compiled, one executed on-device; shape-divergent branches are
    rejected with a clear message (a dynamic output shape cannot exist
    under XLA)."""
    cond = ins[0]
    attrs = {a.name: a.g for a in node.attribute}
    if not _is_traced(cond):
        branch = (attrs["then_branch"] if bool(np.asarray(cond))
                  else attrs["else_branch"])
        return tuple(_run_subgraph(branch, env, {}))

    def run(branch):
        return lambda: tuple(jnp.asarray(o)
                             for o in _run_subgraph(branch, env, {}))

    then_fn, else_fn = run(attrs["then_branch"]), run(attrs["else_branch"])
    try:
        return jax.lax.cond(jnp.asarray(cond).ravel()[0].astype(bool),
                            then_fn, else_fn)
    except (TypeError, ValueError):
        # diagnose only on failure (the happy path stays single-trace):
        # re-trace each branch ALONE — a genuine op error inside a branch
        # body surfaces as itself here, while matching branch structures
        # mean the failure was lax.cond's own and is re-raised unchanged
        then_out = jax.eval_shape(then_fn)
        else_out = jax.eval_shape(else_fn)
        if then_out != else_out:
            raise NotImplementedError(
                "ONNX If with a data-dependent condition requires both "
                "branches to produce matching shapes/dtypes for lax.cond: "
                f"then={then_out} vs else={else_out}") from None
        raise


def _run_subgraph(body, env: dict, bound: dict):
    """Execute ``body`` with ``bound`` formal inputs over a read-only copy of
    the outer scope; returns the body outputs in declaration order."""
    sub_env = dict(env)
    sub_env.update(_load_initializers(body))
    sub_env.update(bound)
    _exec_nodes(body, sub_env)
    return [sub_env[vi.name] for vi in body.output]


def _is_traced(*xs) -> bool:
    import jax.core

    return any(isinstance(x, jax.core.Tracer) for x in xs if x is not None)


def _exec_scan(node, ins, env: dict):
    """ONNX Scan → ``lax.scan``: the body subgraph becomes the (traceable)
    step function, loop-state variables the carry, scan inputs the xs (sliced
    along ``scan_input_axes``, flipped for backward directions), and the
    stacked per-step outputs are placed on ``scan_output_axes``. One compiled
    step serves every iteration — no Python-loop unrolling in the jitted path.
    Reference runs the full opset through ORT (`ONNXRuntime.scala:25`)."""
    attrs = node.attrs()
    body = attrs["body"]
    n_scan = int(attrs["num_scan_inputs"])
    n_state = len(ins) - n_scan
    in_axes = [int(a) for a in (attrs.get("scan_input_axes") or [0] * n_scan)]
    in_dirs = [int(d) for d in (attrs.get("scan_input_directions") or [0] * n_scan)]
    n_scan_out = len(body.output) - n_state
    out_axes = [int(a) for a in (attrs.get("scan_output_axes") or [0] * n_scan_out)]
    out_dirs = [int(d) for d in (attrs.get("scan_output_directions") or [0] * n_scan_out)]

    state0 = tuple(jnp.asarray(s) for s in ins[:n_state])
    xs = []
    for x, ax, d in zip(ins[n_state:], in_axes, in_dirs):
        x = jnp.moveaxis(jnp.asarray(x), ax, 0)
        xs.append(jnp.flip(x, 0) if d else x)
    body_in = [vi.name for vi in body.input]

    def step(carry, xslice):
        bound = dict(zip(body_in[:n_state], carry))
        bound.update(zip(body_in[n_state:], xslice))
        outs = _run_subgraph(body, env, bound)
        new_state = tuple(jnp.asarray(o).astype(c.dtype)
                          for o, c in zip(outs[:n_state], carry))
        return new_state, tuple(jnp.asarray(o) for o in outs[n_state:])

    final_state, stacked = jax.lax.scan(step, state0, tuple(xs))
    outs = list(final_state)
    for y, ax, d in zip(stacked, out_axes, out_dirs):
        y = jnp.flip(y, 0) if d else y
        outs.append(jnp.moveaxis(y, 0, ax))
    return tuple(outs)


def _exec_loop(node, ins, env: dict):
    """ONNX Loop. In eager execution (concrete values — the default
    ``ConvertedModel.__call__`` path) this is a plain Python loop with exact
    spec semantics, including data-dependent early exit and dynamically-sized
    scan outputs. Under jit, two static forms lower to XLA control flow:

    - state-only loops (no scan outputs) → ``lax.while_loop`` on
      (iter < M) & cond — data-dependent trip counts stay on-device;
    - full-trip for-loops (concrete M, scan outputs) → ``lax.scan`` over M
      steps, the form torch's exporter emits for ``for`` loops. A traced
      early exit with scan outputs would need a dynamic output shape —
      rejected explicitly.
    """
    attrs = node.attrs()
    body = attrs["body"]
    M, cond0 = ins[0], ins[1]
    states = [jnp.asarray(v) for v in ins[2:]]
    n_state = len(states)
    body_in = [vi.name for vi in body.input]  # iter_num, cond_in, states...
    n_scan_out = len(body.output) - 1 - n_state
    traced = _is_traced(M, cond0, *states) or any(
        _is_traced(env.get(name)) for name in _outer_reads(body))

    if M is None or _is_traced(M):
        max_trip = None  # unbounded (or device-resident; see while_loop path)
    else:
        _m = np.asarray(M).ravel()
        max_trip = int(_m[0]) if _m.size else None
    keep = True if cond0 is None else cond0

    if not traced:
        # ---- eager: exact ONNX semantics, dynamic everything ----
        scan_rows: list[list] = [[] for _ in range(n_scan_out)]
        i = 0
        keep_b = bool(np.asarray(keep).ravel()[0]) if keep is not True else True
        while keep_b and (max_trip is None or i < max_trip):
            bound = {body_in[0]: jnp.asarray(i, jnp.int32),
                     body_in[1]: jnp.asarray(keep_b)}
            bound.update(zip(body_in[2:], states))
            outs = _run_subgraph(body, env, bound)
            keep_b = bool(np.asarray(outs[0]).ravel()[0])
            states = [jnp.asarray(o) for o in outs[1:1 + n_state]]
            for j in range(n_scan_out):
                scan_rows[j].append(jnp.asarray(outs[1 + n_state + j]))
            i += 1
        if n_scan_out and not scan_rows[0]:
            # zero-trip loop: recover each scan output's per-step shape/dtype
            # by speculatively running the body once (pure — no state commit)
            bound = {body_in[0]: jnp.asarray(0, jnp.int32),
                     body_in[1]: jnp.asarray(True)}
            bound.update(zip(body_in[2:], states))
            try:
                outs = _run_subgraph(body, env, bound)
                templates = [jnp.asarray(o) for o in outs[1 + n_state:]]
            except Exception:  # noqa: BLE001 — fall back to rank-1 empties
                templates = [jnp.zeros((), jnp.float32)] * n_scan_out
            return tuple(states) + tuple(
                jnp.zeros((0,) + t.shape, t.dtype) for t in templates)
        return tuple(states) + tuple(jnp.stack(rows) for rows in scan_rows)

    # ---- traced ----
    I32_MAX = np.iinfo(np.int32).max
    if n_scan_out == 0:
        if _is_traced(M):
            # clamp in the source dtype BEFORE narrowing: torch exports
            # while-loops with M = INT64_MAX, which would wrap to -1
            m_dev = jnp.minimum(jnp.asarray(M).ravel()[0],
                                I32_MAX).astype(jnp.int32)
        elif max_trip is None:
            m_dev = jnp.asarray(I32_MAX, jnp.int32)
        else:
            m_dev = jnp.asarray(min(max_trip, I32_MAX), jnp.int32)
        cond_init = (jnp.asarray(True) if keep is True
                     else jnp.asarray(keep).ravel()[0].astype(bool))

        def cond_fn(carry):
            i, c, _ = carry
            return c & (i < m_dev)

        def body_fn(carry):
            i, c, st = carry
            bound = {body_in[0]: i, body_in[1]: c}
            bound.update(zip(body_in[2:], st))
            outs = _run_subgraph(body, env, bound)
            new_c = jnp.asarray(outs[0]).ravel()[0].astype(bool)
            new_st = tuple(jnp.asarray(o).astype(s.dtype)
                           for o, s in zip(outs[1:], st))
            return i + 1, new_c, new_st

        _, _, final = jax.lax.while_loop(
            cond_fn, body_fn, (jnp.asarray(0, jnp.int32), cond_init,
                               tuple(states)))
        return tuple(final)

    if not isinstance(max_trip, int) or max_trip > 2 ** 24:
        # the huge-M form is torch's while-loop export (M = INT64_MAX):
        # materializing M-length scan outputs is not meaningful — reject
        # clearly instead of attempting jnp.arange(2^63)
        raise NotImplementedError(
            "ONNX Loop with scan outputs under jit requires a static "
            "(concrete, reasonably-sized) trip count M — a traced or "
            "unbounded early exit would produce a dynamically-shaped output")
    if _is_traced(keep):
        raise NotImplementedError(
            "ONNX Loop with scan outputs under jit requires a concrete "
            "initial condition — a traced cond would produce a "
            "dynamically-shaped output")
    if keep is not True and not bool(np.asarray(keep).ravel()[0]):
        # concrete-False initial cond: zero trips — statically expressible.
        # One dead body execution recovers each scan output's row template
        # (XLA DCE removes the unused ops from the jitted graph).
        bound = {body_in[0]: jnp.asarray(0, jnp.int32),
                 body_in[1]: np.asarray(True)}
        bound.update(zip(body_in[2:], states))
        outs = _run_subgraph(body, env, bound)
        return tuple(states) + tuple(
            jnp.zeros((0,) + jnp.shape(o), jnp.asarray(o).dtype)
            for o in outs[1 + n_state:])

    def step(carry, i):
        st = carry
        # cond_in bound CONCRETE True: a for-loop body (cond_out = Identity/
        # logic of cond_in, the torch-export form) constant-folds to a
        # concrete True we can verify; a data-dependent cond surfaces as a
        # tracer and is rejected at trace time rather than silently ignored
        bound = {body_in[0]: i, body_in[1]: np.asarray(True)}
        bound.update(zip(body_in[2:], st))
        outs = _run_subgraph(body, env, bound)
        if _is_traced(outs[0]) or not bool(np.asarray(outs[0]).ravel()[0]):
            raise NotImplementedError(
                "ONNX Loop with scan outputs under jit supports only "
                "full-trip for-loops (cond stays true); this body's exit "
                "condition is data-dependent (or immediately false), which "
                "would produce a dynamically-shaped output")
        new_st = tuple(jnp.asarray(o).astype(s.dtype)
                       for o, s in zip(outs[1:1 + n_state], st))
        return new_st, tuple(jnp.asarray(o) for o in outs[1 + n_state:])

    final, stacked = jax.lax.scan(step, tuple(states),
                                  jnp.arange(max_trip, dtype=jnp.int32))
    return tuple(final) + tuple(stacked)


def _outer_reads(body) -> set:
    """Names a subgraph reads from the outer scope (inputs of its nodes that
    no local node/initializer/formal-input produces), recursing into nested
    If/Loop/Scan bodies — a nested branch reading a traced outer tensor must
    flip the enclosing Loop onto its traced lowering path."""
    local = {vi.name for vi in body.input} | {t.name for t in body.initializer}
    reads = set()
    for n in body.node:
        for i in n.input:
            if i and i not in local:
                reads.add(i)
        for a in n.attribute:
            if a.g is not None:
                reads |= {r for r in _outer_reads(a.g) if r not in local}
        local.update(o for o in n.output if o)
    return reads


def _all_op_types(graph) -> set:
    """Op types in a graph INCLUDING If subgraphs (registry validation)."""
    ops = set()
    for node in graph.node:
        ops.add(node.op_type)
        for a in node.attribute:
            if a.g is not None:
                ops |= _all_op_types(a.g)
    return ops


class ConvertedModel:
    """A parsed + converted ONNX model: ``fn(**inputs) -> dict[name, array]``.

    ``input_names``/``output_names``/``input_shapes`` expose the session-style
    metadata (OrtSession.getInputInfo analog)."""

    def __init__(self, model: ModelProto):
        self.model = model
        g = model.graph
        init_names = {t.name for t in g.initializer}
        self.weights = {t.name: tensor_to_numpy(t) for t in g.initializer}
        self.input_names = [vi.name for vi in g.input if vi.name not in init_names]
        self.output_names = [vi.name for vi in g.output]
        self.input_shapes = {vi.name: tuple(vi.dims) for vi in g.input
                             if vi.name not in init_names}
        self.input_types = {vi.name: vi.elem_type for vi in g.input
                            if vi.name not in init_names}
        unsupported = sorted(o for o in _all_op_types(g)
                             if o not in ("If", "Loop", "Scan")
                             and o not in OP_REGISTRY)
        if unsupported:
            raise NotImplementedError(
                f"ONNX ops not supported by the TPU converter: {unsupported} "
                f"(supported: {sorted(OP_REGISTRY)})")

    def __call__(self, **inputs):
        g = self.model.graph
        # int64 initializers (Slice ends, Reshape shapes, axes...) stay numpy:
        # jnp.asarray under disabled-x64 wraps them to int32 (INT64_MAX -> -1),
        # corrupting "to end" sentinels before the op ever sees them.
        # self.weights is decoded ONCE at construction — re-decoding proto
        # per call costs ~100MB of parsing for ResNet-50-class graphs
        env: dict[str, object] = {
            k: v if v.dtype in (np.int64, np.uint64) else jnp.asarray(v)
            for k, v in self.weights.items()}
        for name in self.input_names:
            if name not in inputs:
                raise KeyError(f"missing input {name!r}; expects {self.input_names}")
            env[name] = inputs[name]
        _exec_nodes(g, env)
        missing = [o for o in self.output_names if o not in env]
        if missing:
            raise ValueError(f"graph did not produce outputs {missing}")
        return {o: env[o] for o in self.output_names}

    def jit_fn(self):
        """Positional jitted callable over ``input_names`` order."""
        def fn(*args):
            return self(**dict(zip(self.input_names, args)))
        return jax.jit(fn)


def convert_graph(model_bytes: bytes) -> ConvertedModel:
    return ConvertedModel(parse_model(model_bytes))


# com.microsoft contrib opset (ORT transformer-fusion ops) registers itself
# into OP_REGISTRY; imported last so the registry base exists
from . import contrib  # noqa: E402,F401  (registration side effect)
