"""ImageFeaturizer (reference ``onnx/ImageFeaturizer.scala:35-270``):
ImageTransformer preprocessing -> headless ONNX model -> feature vector column.

``set_model(name)`` pulls from the local :class:`ONNXHub`
(ref ``ImageFeaturizer.setModel:66-71``); ``head_less=True`` slices the graph
at ``feature_tensor_name`` (the reference's ``extraPorts`` cut) and flattens
the activations into the output vector.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer
from ..image import ImageTransformer
from .hub import ONNXHub
from .model import ONNXModel, slice_model_at_outputs

__all__ = ["ImageFeaturizer"]

IMAGENET_MEANS = [0.485, 0.456, 0.406]
IMAGENET_STDS = [0.229, 0.224, 0.225]


class ImageFeaturizer(Transformer):
    feature_name = "onnx"

    input_col = Param("input_col", "image column", default="image")
    output_col = Param("output_col", "feature vector column", default="features")
    model_payload = ComplexParam("model_payload", "ONNX model bytes")
    head_less = Param("head_less", "cut at the feature tensor (transfer learning)",
                      default=True, converter=TypeConverters.to_bool)
    feature_tensor_name = Param("feature_tensor_name",
                                "intermediate output to cut at when head_less",
                                default=None)
    image_height = Param("image_height", "model input height", default=224,
                         converter=TypeConverters.to_int)
    image_width = Param("image_width", "model input width", default=224,
                        converter=TypeConverters.to_int)
    mini_batch_size = Param("mini_batch_size", "device batch size", default=32,
                            converter=TypeConverters.to_int)
    center_crop = Param("center_crop", "aspect-preserving resize + center crop",
                        default=True, converter=TypeConverters.to_bool)

    def set_model(self, name: str, hub: ONNXHub | None = None) -> "ImageFeaturizer":
        return self.set(model_payload=(hub or ONNXHub()).load(name))

    def set_model_location(self, path: str) -> "ImageFeaturizer":
        with open(path, "rb") as f:
            return self.set(model_payload=f.read())

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        h, w = self.get("image_height"), self.get("image_width")
        it = ImageTransformer(input_col=self.get("input_col"), output_col="_img_tensor")
        if self.get("center_crop"):
            it = it.resize(size=max(h, w) * 256 // 224, keep_aspect_ratio=True)
            it = it.center_crop(h, w)
        else:
            it = it.resize(height=h, width=w)
        it = it.normalize(means=IMAGENET_MEANS, stds=IMAGENET_STDS,
                          color_scale_factor=1 / 255.0)

        payload = self.get("model_payload")
        if payload is None:
            raise ValueError("ImageFeaturizer: model_payload not set "
                             "(set_model / set_model_location)")
        if self.get("head_less"):
            cut = self.get("feature_tensor_name")
            if not cut:
                raise ValueError(
                    "ImageFeaturizer: head_less=True requires "
                    "feature_tensor_name (the intermediate output to cut at); "
                    "set head_less=False to use the full model's outputs")
            payload = slice_model_at_outputs(payload, [cut])
        om = ONNXModel(model_bytes=payload,
                       mini_batch_size=self.get("mini_batch_size"))
        in_name = om.model_input_names[0]
        out_name = om.model_output_names[0]
        om.set(feed_dict={in_name: "_img_tensor"},
               fetch_dict={"_raw_feats": out_name})

        out = om.transform(it.transform(df))

        def flatten(p):
            feats = np.asarray(p["_raw_feats"])
            return feats.reshape(len(feats), -1)

        return (out.with_column(self.get("output_col"), flatten)
                   .drop("_img_tensor", "_raw_feats"))
