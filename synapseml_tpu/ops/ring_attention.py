"""Ring attention — sequence/context parallelism over a named mesh axis.

Net-new capability (SURVEY.md §5 "Long-context / sequence parallelism:
absent" in the reference): sequences longer than one chip's HBM are sharded
over the ``seq`` mesh axis; each device holds a Q/K/V shard, and K/V blocks
rotate around the ring via ``jax.lax.ppermute`` (ICI neighbor exchange) while
a running-softmax accumulates the local contribution — attention memory stays
O(T/n per device) and the K/V transfer overlaps with block compute in XLA's
pipeline.

``ring_attention`` is the collective form, called INSIDE ``jax.shard_map``
with per-device shards. ``ring_attention_sharded`` wraps full arrays for
callers holding a :class:`~synapseml_tpu.parallel.MeshContext`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, axis_size: int, kv_mask=None,
                   causal: bool = False):
    """Blockwise ring attention over ``axis_name``; call inside ``shard_map``.

    Args:
      q, k, v: local shards ``[B, T_local, H, D]`` (equal-length shards; global
        position of row t on shard i is ``i * T_local + t``).
      axis_name: mesh axis carrying the sequence dimension.
      axis_size: static size of that axis (ring length).
      kv_mask: optional ``[B, T_local]`` bool for the local K/V shard.
      causal: apply a global causal mask built from shard offsets.

    Fully-masked query rows yield zeros. Accumulation is float32.
    """
    B, T, H, D = q.shape
    my = jax.lax.axis_index(axis_name)
    scale = 1.0 / np.sqrt(D)
    qf = q.astype(jnp.float32)

    if kv_mask is None:
        kv_mask = jnp.ones((B, T), bool)

    q_pos = my * T + jnp.arange(T)                      # [T] global positions

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(s, carry):
        k_cur, v_cur, mask_cur, m, l, acc = carry
        origin = (my - s) % axis_size                   # shard the block came from
        kv_pos = origin * T + jnp.arange(T)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(mask_cur[:, None, None, :], scores, _NEG_INF)
        if causal:
            allowed = kv_pos[None, :] <= q_pos[:, None]  # [T, T]
            scores = jnp.where(allowed[None, None], scores, _NEG_INF)
        blk_max = jnp.max(scores, axis=-1)              # [B, H, T]
        new_m = jnp.maximum(m, blk_max)
        alpha = jnp.exp(m - new_m)
        # gated: fully-masked rows keep p == 0 (zero output, zero gradient)
        p = jnp.where(scores <= _NEG_INF * 0.5, 0.0,
                      jnp.exp(scores - new_m[..., None]))  # [B, H, Tq, Tk]
        new_l = l * alpha + jnp.sum(p, axis=-1)
        new_acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        # rotate K/V/mask to the next device; the final rotation restores the
        # original residency (harmless) and keeps the loop body uniform
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        return k_nxt, v_nxt, mask_nxt, new_m, new_l, new_acc

    # derive accumulators from q so they carry the same shard_map
    # varying-axes type as the loop outputs (check_vma)
    zeros_bht = jnp.transpose(jnp.sum(qf, axis=-1) * 0.0, (0, 2, 1))
    m0 = zeros_bht + _NEG_INF
    l0 = zeros_bht
    acc0 = jnp.transpose(qf * 0.0, (0, 2, 1, 3))
    carry = (k, v, kv_mask, m0, l0, acc0)
    carry = jax.lax.fori_loop(0, axis_size, step, carry, unroll=True)
    _, _, _, m, l, acc = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]        # [B, H, T, D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _mesh_of(mesh_like):
    """Accept a MeshContext, a jax Mesh, or an AbstractMesh."""
    mesh = getattr(mesh_like, "mesh", mesh_like)
    sizes = dict(zip(mesh.axis_names, mesh.axis_sizes
                     if hasattr(mesh, "axis_sizes") else mesh.devices.shape))
    return mesh, sizes


def ring_attention_sharded(mesh_ctx, q, k, v, kv_mask=None, causal: bool = False,
                           seq_axis: str = "seq", batch_axes=("data", "fsdp"),
                           head_axis: str | None = "tensor"):
    """Full-array entry point: shard_map ``ring_attention`` over the mesh.

    q, k, v: ``[B, T, H, D]`` global arrays (T divisible by the seq-axis size).
    ``mesh_ctx`` may be a :class:`~synapseml_tpu.parallel.MeshContext`, a
    ``jax.sharding.Mesh``, or an ``AbstractMesh``.
    """
    from jax.sharding import PartitionSpec as P

    mesh, sizes = _mesh_of(mesh_ctx)
    n = sizes.get(seq_axis, 1)
    H = q.shape[2]
    batch_axes = tuple(a for a in batch_axes if a in sizes)
    head = (head_axis if head_axis and head_axis in sizes
            and H % max(sizes.get(head_axis, 1), 1) == 0 else None)
    if n <= 1:
        from .attention import reference_attention
        return reference_attention(q, k, v, kv_mask=kv_mask, causal=causal)
    qkv_spec = P(batch_axes or None, seq_axis, head, None)
    mask_spec = P(batch_axes or None, seq_axis)
    fn = functools.partial(ring_attention, axis_name=seq_axis, axis_size=n,
                           causal=causal)
    mapped = jax.shard_map(
        lambda q_, k_, v_, m_: fn(q_, k_, v_, kv_mask=m_),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], bool)
    return mapped(q, k, v, kv_mask)
