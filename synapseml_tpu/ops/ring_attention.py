"""Ring attention — sequence/context parallelism over a named mesh axis.

Net-new capability (SURVEY.md §5 "Long-context / sequence parallelism:
absent" in the reference): sequences longer than one chip's HBM are sharded
over the ``seq`` mesh axis; each device holds a Q/K/V shard, and K/V blocks
rotate around the ring via ``jax.lax.ppermute`` (ICI neighbor exchange) while
a running softmax accumulates the local contribution.

Memory/compile properties (long-context hardening):
  * the ring loop is ROLLED (``lax.fori_loop``) — compile size is independent
    of the ring length;
  * the inner block attention is CHUNKED (``lax.scan`` over K/V chunks with a
    running max/denominator) — no ``[T_loc, T_loc]`` score materialization;
    peak per-device live scores are ``[B, H, T_loc, chunk]``;
  * backward is a CUSTOM VJP that saves only (out, lse) and recomputes
    probabilities per ring step (flash-attention-style two-pass), with dK/dV
    accumulators traveling around the ring back to their owner shard.

``ring_attention`` is the collective form, called INSIDE ``jax.shard_map``
with per-device shards. ``ring_attention_sharded`` wraps full arrays for
callers holding a :class:`~synapseml_tpu.parallel.MeshContext`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def _pick_chunk(t_local: int, chunk: int) -> int:
    """Largest divisor of t_local that is <= chunk (static shapes for scan)."""
    c = min(chunk, t_local)
    while t_local % c:
        c -= 1
    return max(c, 1)


def _block_fwd(q, q_pos, k_blk, v_blk, mask_blk, kv_pos0, causal, m, l, acc,
               chunk):
    """Fold one K/V block into the running softmax, scanning over chunks.

    q/k_blk/v_blk: [B, T, H, D] in the INPUT dtype — the einsums run in that
    dtype (bf16 on the training path keeps the MXU off its ~4x slower f32
    path) with f32 accumulation; the softmax statistics are f32.
    mask_blk: [B, T] bool; kv_pos0: scalar global position of the block's
    first row. m, l: [B, H, T]; acc: [B, H, T, D] (f32). Returns (m, l, acc).
    """
    B, T, H, D = q.shape
    C = _pick_chunk(T, chunk)
    n_chunks = T // C
    scale = 1.0 / np.sqrt(D)

    def body(carry, c_idx):
        m, l, acc = carry
        start = c_idx * C
        ks = jax.lax.dynamic_slice_in_dim(k_blk, start, C, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(v_blk, start, C, axis=1)
        ms = jax.lax.dynamic_slice_in_dim(mask_blk, start, C, axis=1)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, ks,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(ms[:, None, None, :], scores, _NEG_INF)
        if causal:
            kv_pos = kv_pos0 + start + jnp.arange(C)
            allowed = kv_pos[None, :] <= q_pos[:, None]        # [T, C]
            scores = jnp.where(allowed[None, None], scores, _NEG_INF)
        blk_max = jnp.max(scores, axis=-1)                     # [B, H, T]
        new_m = jnp.maximum(m, blk_max)
        alpha = jnp.exp(m - new_m)
        # gated: fully-masked rows keep p == 0 (zero output, zero gradient)
        p = jnp.where(scores <= _NEG_INF * 0.5, 0.0,
                      jnp.exp(scores - new_m[..., None]))      # [B, H, T, C]
        new_l = l * alpha + jnp.sum(p, axis=-1)
        new_acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(vs.dtype), vs,
            preferred_element_type=jnp.float32)
        return (new_m, new_l, new_acc), None

    (m, l, acc), _ = jax.lax.scan(body, (m, l, acc), jnp.arange(n_chunks))
    return m, l, acc


def _block_bwd(q, q_pos, k_blk, v_blk, mask_blk, kv_pos0, causal, lse, do,
               delta, dq, dk_blk, dv_blk, chunk):
    """Backward for one visiting K/V block: accumulate local dq and the
    block's traveling dk/dv. Matmul operands stay in the input dtype with f32
    accumulation; probability/score statistics and the dq/dk/dv accumulators
    are f32. lse: [B, H, T]; do: [B, H, T, D] (input dtype);
    delta: [B, H, T] (f32 sum(do * out)). Returns (dq, dk_blk, dv_blk)."""
    B, T, H, D = q.shape
    C = _pick_chunk(T, chunk)
    n_chunks = T // C
    scale = 1.0 / np.sqrt(D)

    def body(carry, c_idx):
        dq, dk_blk, dv_blk = carry
        start = c_idx * C
        ks = jax.lax.dynamic_slice_in_dim(k_blk, start, C, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(v_blk, start, C, axis=1)
        ms = jax.lax.dynamic_slice_in_dim(mask_blk, start, C, axis=1)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, ks,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(ms[:, None, None, :], scores, _NEG_INF)
        if causal:
            kv_pos = kv_pos0 + start + jnp.arange(C)
            allowed = kv_pos[None, :] <= q_pos[:, None]
            scores = jnp.where(allowed[None, None], scores, _NEG_INF)
        p = jnp.where(scores <= _NEG_INF * 0.5, 0.0,
                      jnp.exp(scores - lse[..., None]))        # [B, H, T, C]
        dv_c = jnp.einsum("bhqk,bhqd->bkhd", p.astype(do.dtype), do,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bkhd->bhqk", do, vs,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None])                       # [B, H, T, C]
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds.astype(ks.dtype), ks,
                             preferred_element_type=jnp.float32) * scale
        dk_c = jnp.einsum("bhqk,bqhd->bkhd", ds.astype(q.dtype), q,
                          preferred_element_type=jnp.float32) * scale
        dk_blk = jax.lax.dynamic_update_slice_in_dim(
            dk_blk, jax.lax.dynamic_slice_in_dim(dk_blk, start, C, 1) + dk_c,
            start, axis=1)
        dv_blk = jax.lax.dynamic_update_slice_in_dim(
            dv_blk, jax.lax.dynamic_slice_in_dim(dv_blk, start, C, 1) + dv_c,
            start, axis=1)
        return (dq, dk_blk, dv_blk), None

    (dq, dk_blk, dv_blk), _ = jax.lax.scan(body, (dq, dk_blk, dv_blk),
                                           jnp.arange(n_chunks))
    return dq, dk_blk, dv_blk


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_core(q, k, v, kv_mask, axis_name, axis_size, causal, chunk):
    out, _ = _ring_fwd_impl(q, k, v, kv_mask, axis_name, axis_size, causal, chunk)
    return out


def _ring_fwd_impl(q, k, v, kv_mask, axis_name, axis_size, causal, chunk):
    B, T, H, D = q.shape
    my = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)
    q_pos = my * T + jnp.arange(T)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(s, carry):
        k_cur, v_cur, mask_cur, m, l, acc = carry
        origin = (my - s) % axis_size
        m, l, acc = _block_fwd(q, q_pos, k_cur, v_cur, mask_cur, origin * T,
                               causal, m, l, acc, chunk)
        # rotate K/V/mask to the next device; the final rotation restores the
        # original residency and keeps the loop body uniform
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        return k_nxt, v_nxt, mask_nxt, m, l, acc

    # derive accumulators from q so they carry the same shard_map
    # varying-axes type as the loop outputs (check_vma)
    zeros_bht = jnp.transpose(jnp.sum(qf, axis=-1) * 0.0, (0, 2, 1))
    m0 = zeros_bht + _NEG_INF
    l0 = zeros_bht
    acc0 = jnp.transpose(qf * 0.0, (0, 2, 1, 3))
    carry = (k, v, kv_mask, m0, l0, acc0)
    carry = jax.lax.fori_loop(0, axis_size, step, carry)
    _, _, _, m, l, acc = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]               # [B, H, T, D]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))                   # [B, H, T]
    out_bthd = jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
    return out_bthd, (out, lse)


def _ring_core_fwd(q, k, v, kv_mask, axis_name, axis_size, causal, chunk):
    out_bthd, (out_f32, lse) = _ring_fwd_impl(q, k, v, kv_mask, axis_name,
                                              axis_size, causal, chunk)
    return out_bthd, (q, k, v, kv_mask, out_f32, lse)


def _ring_core_bwd(axis_name, axis_size, causal, chunk, res, g):
    q, k, v, kv_mask, out, lse = res
    B, T, H, D = q.shape
    my = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)
    q_pos = my * T + jnp.arange(T)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    do = jnp.transpose(g, (0, 2, 1, 3)).astype(q.dtype)        # [B, H, T, D]
    # re-apply the softmax-normalization jacobian piece: out = acc / l and
    # d(acc/l) folds into ds via delta = sum(do * out)
    delta = jnp.sum(do.astype(jnp.float32) * out, axis=-1)     # [B, H, T]

    def step(s, carry):
        k_cur, v_cur, mask_cur, dk_cur, dv_cur, dq = carry
        origin = (my - s) % axis_size
        dq, dk_cur, dv_cur = _block_bwd(
            q, q_pos, k_cur, v_cur,
            mask_cur, origin * T, causal, lse, do, delta, dq, dk_cur, dv_cur,
            chunk)
        # dk/dv travel WITH their block so every shard adds its contribution;
        # after axis_size rotations they are back at the owner
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return k_nxt, v_nxt, mask_nxt, dk_nxt, dv_nxt, dq

    dk0 = qf * 0.0
    dv0 = qf * 0.0
    dq0 = qf * 0.0
    carry = (k, v, kv_mask, dk0, dv0, dq0)
    _, _, _, dk, dv, dq = jax.lax.fori_loop(0, axis_size, step, carry)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(q, k, v, axis_name: str, axis_size: int, kv_mask=None,
                   causal: bool = False, chunk: int = 512):
    """Blockwise ring attention over ``axis_name``; call inside ``shard_map``.

    Args:
      q, k, v: local shards ``[B, T_local, H, D]`` (equal-length shards; global
        position of row t on shard i is ``i * T_local + t``).
      axis_name: mesh axis carrying the sequence dimension.
      axis_size: static size of that axis (ring length).
      kv_mask: optional ``[B, T_local]`` bool for the local K/V shard.
      causal: apply a global causal mask built from shard offsets.
      chunk: inner K/V chunk size bounding live score memory to
        ``[B, H, T_local, chunk]``.

    Fully-masked query rows yield zeros. Accumulation is float32;
    differentiable via a recompute-per-ring-step custom VJP.
    """
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], bool)
    return _ring_core(q, k, v, kv_mask, axis_name, axis_size, causal, chunk)


def _mesh_of(mesh_like):
    """Accept a MeshContext, a jax Mesh, or an AbstractMesh."""
    mesh = getattr(mesh_like, "mesh", mesh_like)
    sizes = dict(zip(mesh.axis_names, mesh.axis_sizes
                     if hasattr(mesh, "axis_sizes") else mesh.devices.shape))
    return mesh, sizes


def seq_parallel_shard_map(mesh_ctx, q, k, v, kv_mask, causal, seq_axis,
                           batch_axes, head_axis, fn_factory,
                           head_needs_seq_factor: bool = False,
                           check_vma: bool = True):
    """Shared full-array wrapper for the sequence-parallel strategies.

    Resolves the mesh, falls back to plain attention when the seq axis is
    absent/size-1, builds the batch/seq/head PartitionSpecs (the head axis is
    used only when the head count divides its sharding — times the seq size
    too when ``head_needs_seq_factor``, as Ulysses splits heads across the
    seq axis as well), and shard_maps ``fn_factory(axis_size)`` which must
    return a per-shard ``fn(q, k, v, kv_mask)``.
    """
    from jax.sharding import PartitionSpec as P

    mesh, sizes = _mesh_of(mesh_ctx)
    n = sizes.get(seq_axis, 1)
    H = q.shape[2]
    batch_axes = tuple(a for a in batch_axes if a in sizes)
    divisor = max(sizes.get(head_axis, 1), 1) * (n if head_needs_seq_factor else 1)
    head = (head_axis if head_axis and head_axis in sizes
            and H % divisor == 0 else None)
    if n <= 1:
        from .attention import reference_attention
        return reference_attention(q, k, v, kv_mask=kv_mask, causal=causal)
    qkv_spec = P(batch_axes or None, seq_axis, head, None)
    mask_spec = P(batch_axes or None, seq_axis)
    fn = fn_factory(n)
    from ..parallel.collectives import compat_shard_map

    mapped = compat_shard_map(
        lambda q_, k_, v_, m_: fn(q_, k_, v_, kv_mask=m_),
        mesh,
        (qkv_spec, qkv_spec, qkv_spec, mask_spec),
        qkv_spec,
        check_vma=check_vma,
    )
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], bool)
    return mapped(q, k, v, kv_mask)


def ring_attention_sharded(mesh_ctx, q, k, v, kv_mask=None, causal: bool = False,
                           seq_axis: str = "seq", batch_axes=("data", "fsdp"),
                           head_axis: str | None = "tensor", chunk: int = 512):
    """Full-array entry point: shard_map ``ring_attention`` over the mesh.

    q, k, v: ``[B, T, H, D]`` global arrays (T divisible by the seq-axis size).
    ``mesh_ctx`` may be a :class:`~synapseml_tpu.parallel.MeshContext`, a
    ``jax.sharding.Mesh``, or an ``AbstractMesh``.
    """
    return seq_parallel_shard_map(
        mesh_ctx, q, k, v, kv_mask, causal, seq_axis, batch_axes, head_axis,
        lambda n: functools.partial(ring_attention, axis_name=seq_axis,
                                    axis_size=n, causal=causal, chunk=chunk))
