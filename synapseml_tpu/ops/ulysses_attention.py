"""All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

The second long-context strategy next to :mod:`ring_attention` (SURVEY.md §5
"ring attention or all-to-all sequence/context parallelism"; the reference
has neither). Where the ring keeps tokens resident and rotates K/V shards
around the mesh (n-1 ``ppermute`` hops, O(T/n) memory, arbitrary lengths),
Ulysses swaps WHICH dimension is sharded for the attention op itself:

  [B, T/n, H, D]  --all_to_all-->  [B, T, H/n, D]

Each device then runs full-context attention for its head subset — by
default through the blockwise flash path (O(T) memory; a full-context
einsum would materialize the [T, T] scores the long-context path exists to
avoid) — and a second all-to-all restores sequence sharding. Communication
is 4 all-to-alls
per layer (q/k/v in, out back; their VJPs are all-to-alls too), each moving
activations once, vs the ring's (n-1) K/V rotations: cheaper on
all-to-all-friendly interconnects (ICI torus) when n divides the head count;
the ring remains the choice when heads are too few or T/n is still too big
to attend locally.

Requires ``n_heads % axis_size == 0``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .attention import flash_attention, reference_attention
from .ring_attention import seq_parallel_shard_map

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, kv_mask=None, *, axis_name: str,
                      axis_size: int, causal: bool = False,
                      local_impl: str = "flash"):
    """Per-shard body (use under ``shard_map``).

    q, k, v: ``[B, T/n, H, D]`` local shards in global token order;
    kv_mask: ``[B, T/n]`` local validity. Returns ``[B, T/n, H, D]``.
    ``local_impl``: 'flash' (bounded memory, the long-context default) or
    'einsum' (materializes [T, T] scores — only for short sequences).
    """
    H = q.shape[2]
    if H % axis_size:
        raise ValueError(f"ulysses needs n_heads ({H}) divisible by the "
                         f"'{axis_name}' axis size ({axis_size})")
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    # heads scatter across the axis, tokens gather: [B, T, H/n, D]
    qh = a2a(q, split_axis=2, concat_axis=1)
    kh = a2a(k, split_axis=2, concat_axis=1)
    vh = a2a(v, split_axis=2, concat_axis=1)
    full_mask = None
    if kv_mask is not None:
        full_mask = jax.lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
    if local_impl == "flash":
        out = flash_attention(qh, kh, vh, kv_mask=full_mask, causal=causal)
    else:
        out = reference_attention(qh, kh, vh, kv_mask=full_mask, causal=causal)
    # tokens scatter back, heads gather: [B, T/n, H, D]
    return a2a(out, split_axis=1, concat_axis=2)


def ulysses_attention_sharded(mesh_ctx, q, k, v, kv_mask=None,
                              causal: bool = False, seq_axis: str = "seq",
                              batch_axes=("data", "fsdp"),
                              head_axis: str | None = "tensor",
                              local_impl: str = "flash"):
    """Full-array entry point: ``shard_map`` :func:`ulysses_attention` over
    the mesh (mirror of ``ring_attention_sharded``).

    q, k, v: ``[B, T, H, D]`` global arrays (T divisible by the seq-axis
    size, H divisible by seq-axis x any head-axis sharding).
    """
    return seq_parallel_shard_map(
        mesh_ctx, q, k, v, kv_mask, causal, seq_axis, batch_axes, head_axis,
        lambda n: functools.partial(ulysses_attention, axis_name=seq_axis,
                                    axis_size=n, causal=causal,
                                    local_impl=local_impl),
        head_needs_seq_factor=True,  # ulysses splits heads across seq too
        # only the flash local step needs the vma check off: its pallas_call
        # out_shape carries no varying-mesh-axes annotation (the specs pin
        # the sharding contract); einsum bodies keep full validation
        check_vma=(local_impl != "flash"))
