"""Blockwise (flash) attention — Pallas TPU kernel + XLA fallback.

The reference's attention hot loop lives inside torch CUDA kernels reached via
``dl/LitDeepTextModel.py`` / ONNX Runtime (SURVEY.md §2.3); the TPU-native
equivalent is a fused Pallas kernel: Q/K/V stream HBM→VMEM in blocks, the
running-softmax (max/sum) accumulators stay in VMEM scratch, and only the
normalized output is written back — O(T) memory instead of materializing the
[T, T] score matrix.

Layout contract: ``q, k, v: [B, T, H, D]`` (same as :mod:`models.flax_nets`),
``kv_mask: [B, T]`` boolean (True = attend). Fully-masked query rows output
exactly zero (same contract as :func:`reference_attention` and ring
attention) — padding rows carry no gradient and are sliced away downstream.

Backward pass: a custom VJP recomputes attention blockwise in XLA from the
saved log-sum-exp — no [T, T] materialization, no second Pallas kernel needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def _pick_interpret() -> bool:
    return jax.default_backend() != "tpu"


def reference_attention(q, k, v, kv_mask=None, causal: bool = False,
                        q_offset=0, kv_offset=0):
    """Plain XLA attention (the correctness oracle). [B,T,H,D] layout.

    ``q_offset``/``kv_offset`` are global position offsets so sequence-parallel
    shards can build the right causal mask (used by ring attention).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(D)
    if causal:
        q_pos = q_offset + jnp.arange(Tq)[:, None]
        kv_pos = kv_offset + jnp.arange(Tk)[None, :]
        scores = jnp.where((kv_pos <= q_pos)[None, None], scores, _NEG_INF)
    if kv_mask is not None:
        scores = jnp.where(kv_mask[:, None, None, :], scores, _NEG_INF)
    any_valid = jnp.any(scores > _NEG_INF * 0.5, axis=-1)        # [B,H,Tq]
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(any_valid[..., None], probs, 0.0)          # zero masked rows
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *,
                      block_k: int, n_kblocks: int, scale: float, causal: bool,
                      block_q: int):
    """One (batch*head, q-block, kv-block) program. Only ONE block_k-sized K/V
    tile is VMEM-resident at a time (streamed by the grid's innermost
    dimension); the running max/sum/accumulator live in VMEM scratch that
    persists across the kv dimension and is written out on the last step."""
    from jax.experimental import pallas as pl

    q_blk = pl.program_id(1)
    kv_blk = pl.program_id(2)

    @pl.when(kv_blk == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        # dots stay in the INPUT dtype (bf16 on the training path) with f32
        # accumulation — a pre-cast to f32 would push the MXU onto its ~4x
        # slower f32 path; only the softmax statistics need f32. The scale is
        # applied post-dot in f32 (no bf16 rounding of q, no padded-D fixup).
        q = q_ref[0]                                    # [block_q, D]
        k_blk = k_ref[0]                                # [block_k, D]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        valid = mask_ref[0, 0] != 0                     # [bk]
        s = jnp.where(valid[None, :], s, _NEG_INF)
        if causal:
            q_pos = q_blk * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kv_pos = kv_blk * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kv_pos <= q_pos, s, _NEG_INF)
        m = m_scr[:, 0]
        new_m = jnp.maximum(m, jnp.max(s, axis=1))
        alpha = jnp.exp(m - new_m)
        # gate, not just subtract: for fully-masked rows s == new_m == -1e30
        # and exp(0) would count masked entries (f32 absorbs log(l) into -1e30)
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, jnp.exp(s - new_m[:, None]))
        l_scr[...] = (l_scr[...] * alpha[:, None]
                      + jnp.broadcast_to(jnp.sum(p, axis=1)[:, None], l_scr.shape))
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(new_m[:, None], m_scr.shape)

    if causal:
        # skip kv blocks fully above the diagonal
        pl.when(kv_blk * block_k <= (q_blk + 1) * block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kv_blk == n_kblocks - 1)
    def _finalize():
        l = l_scr[:, 0]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[...] / safe_l[:, None]).astype(o_ref.dtype)
        lse_ref[0, :, 0] = m_scr[:, 0] + jnp.log(safe_l)


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_core(q, k, v, kv_mask, causal, block_q, block_k, scale):
    out, _ = _flash_core_fwd_impl(q, k, v, kv_mask, causal, block_q, block_k,
                                  scale)
    return out


def _flash_core_fwd_impl(q, k, v, kv_mask, causal, block_q, block_k, scale):
    """q,k,v: [BH, T, Dp]; kv_mask: [BH, Tk] bool. ``scale`` is 1/sqrt of the
    TRUE head dim (D may be lane-padded here). Returns (out, lse)."""
    from jax.experimental import pallas as pl

    from jax.experimental.pallas import tpu as pltpu

    BH, Tq, Dp = q.shape
    Tk = k.shape[1]
    n_kblocks = Tk // block_k
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               n_kblocks=n_kblocks, scale=scale, causal=causal,
                               block_q=block_q)
    grid = (BH, Tq // block_q, n_kblocks)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, Dp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, Dp), q.dtype),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max (lane-bcast)
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum (lane-bcast)
            pltpu.VMEM((block_q, Dp), jnp.float32),    # output accumulator
        ],
        interpret=_pick_interpret(),
    )(q, k, v, kv_mask.astype(jnp.int32)[:, None, :])
    return out, lse[:, :, 0]


def _flash_core_fwd(q, k, v, kv_mask, causal, block_q, block_k, scale):
    out, lse = _flash_core_fwd_impl(q, k, v, kv_mask, causal, block_q, block_k,
                                    scale)
    return out, (q, k, v, kv_mask, out, lse)


def _flash_core_bwd(causal, block_q, block_k, scale, res, g):
    """Blockwise XLA backward from saved LSE — O(T·block) memory via lax.scan
    over kv blocks (dq) / q blocks (dk, dv). Matmul operands stay in the
    input dtype (bf16 on the training path) with f32 accumulation; only the
    softmax/probability statistics are f32."""
    q, k, v, kv_mask, out, lse = res
    BH, Tq, Dp = q.shape
    Tk = k.shape[1]
    qf, kf, vf, gf = q, k, v, g.astype(q.dtype)
    # delta_i = sum_d out_i * g_i  (rowwise), standard flash bwd identity
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)

    q_pos = jnp.arange(Tq)
    kv_pos = jnp.arange(Tk)

    def p_block(q_blk, lse_blk, kb_idx, k_all, qi0):
        """probs for one (q block, kv block): [BH, bq, bk]."""
        kb = jax.lax.dynamic_slice_in_dim(k_all, kb_idx * block_k, block_k, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", q_blk, kb,
                       preferred_element_type=jnp.float32) * scale
        mb = jax.lax.dynamic_slice_in_dim(kv_mask, kb_idx * block_k, block_k, axis=1)
        s = jnp.where(mb[:, None, :], s, _NEG_INF)
        if causal:
            qp = qi0 + q_pos[:block_q][None, :, None]
            kp = kb_idx * block_k + kv_pos[:block_k][None, None, :]
            s = jnp.where(kp <= qp, s, _NEG_INF)
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, jnp.exp(s - lse_blk[:, :, None]))
        return p, kb

    n_qb, n_kb = Tq // block_q, Tk // block_k

    def dq_one(_, qi):
        qi0 = qi * block_q
        q_blk = jax.lax.dynamic_slice_in_dim(qf, qi0, block_q, axis=1)
        lse_blk = jax.lax.dynamic_slice_in_dim(lse, qi0, block_q, axis=1)
        g_blk = jax.lax.dynamic_slice_in_dim(gf, qi0, block_q, axis=1)
        d_blk = jax.lax.dynamic_slice_in_dim(delta, qi0, block_q, axis=1)

        def inner(ki, dq_acc):
            p, kb = p_block(q_blk, lse_blk, ki, kf, qi0)
            vb = jax.lax.dynamic_slice_in_dim(vf, ki * block_k, block_k, axis=1)
            dp = jnp.einsum("bqd,bkd->bqk", g_blk, vb,
                            preferred_element_type=jnp.float32)
            ds = p * (dp - d_blk[:, :, None])
            return dq_acc + jnp.einsum("bqk,bkd->bqd", ds.astype(kb.dtype), kb,
                                       preferred_element_type=jnp.float32) * scale

        dq_blk = jax.lax.fori_loop(0, n_kb, inner,
                                   jnp.zeros((BH, block_q, Dp), jnp.float32))
        return None, dq_blk

    _, dq_blocks = jax.lax.scan(dq_one, None, jnp.arange(n_qb))
    dq = jnp.reshape(dq_blocks.transpose(1, 0, 2, 3), (BH, Tq, Dp))

    def dkv_one(_, ki):
        ki0 = ki * block_k
        kb = jax.lax.dynamic_slice_in_dim(kf, ki0, block_k, axis=1)
        vb = jax.lax.dynamic_slice_in_dim(vf, ki0, block_k, axis=1)

        def inner(qi, carry):
            dk_acc, dv_acc = carry
            qi0 = qi * block_q
            q_blk = jax.lax.dynamic_slice_in_dim(qf, qi0, block_q, axis=1)
            lse_blk = jax.lax.dynamic_slice_in_dim(lse, qi0, block_q, axis=1)
            g_blk = jax.lax.dynamic_slice_in_dim(gf, qi0, block_q, axis=1)
            d_blk = jax.lax.dynamic_slice_in_dim(delta, qi0, block_q, axis=1)
            p, _ = p_block(q_blk, lse_blk, ki, kf, qi0)
            dv_acc = dv_acc + jnp.einsum("bqk,bqd->bkd", p.astype(g_blk.dtype),
                                         g_blk,
                                         preferred_element_type=jnp.float32)
            dp = jnp.einsum("bqd,bkd->bqk", g_blk, vb,
                            preferred_element_type=jnp.float32)
            ds = p * (dp - d_blk[:, :, None])
            dk_acc = dk_acc + jnp.einsum("bqk,bqd->bkd", ds.astype(q_blk.dtype),
                                         q_blk,
                                         preferred_element_type=jnp.float32) * scale
            return dk_acc, dv_acc

        dk_blk, dv_blk = jax.lax.fori_loop(
            0, n_qb, inner, (jnp.zeros((BH, block_k, Dp), jnp.float32),
                             jnp.zeros((BH, block_k, Dp), jnp.float32)))
        return None, (dk_blk, dv_blk)

    _, (dk_blocks, dv_blocks) = jax.lax.scan(dkv_one, None, jnp.arange(n_kb))
    dk = jnp.reshape(dk_blocks.transpose(1, 0, 2, 3), (BH, Tk, Dp))
    dv = jnp.reshape(dv_blocks.transpose(1, 0, 2, 3), (BH, Tk, Dp))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, kv_mask=None, causal: bool = False,
                    block_q: int = 128, block_k: int = 128):
    """Fused blockwise attention. [B, T, H, D] layout, differentiable.

    Pads T to the block size and D to the 128-lane TPU tile (zero-padding D
    leaves dot products unchanged; padded kv positions are masked; padded q
    rows are sliced away).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if causal and Tq != Tk:
        # the kernel aligns q/kv positions at 0 with no offset; a causal mask
        # with Tq != Tk would be silently misaligned (cf. reference_attention's
        # q_offset/kv_offset)
        raise ValueError(f"causal flash_attention requires Tq == Tk, got "
                         f"Tq={Tq} Tk={Tk}")
    if kv_mask is None:
        kv_mask = jnp.ones((B, Tk), bool)

    block_q = min(block_q, _ceil_to(Tq, 8))
    block_k = min(block_k, _ceil_to(Tk, 8))
    Tq_p, Tk_p = _ceil_to(Tq, block_q), _ceil_to(Tk, block_k)
    Dp = _ceil_to(D, 128)
    scale = 1.0 / np.sqrt(D)  # true head dim — padding D must not change it

    def to_bh(x, T, Tp):
        x = jnp.pad(x, ((0, 0), (0, Tp - T), (0, 0), (0, Dp - D)))
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, Tp, Dp)

    qb = to_bh(q, Tq, Tq_p)
    kb = to_bh(k, Tk, Tk_p)
    vb = to_bh(v, Tk, Tk_p)
    maskb = jnp.pad(kv_mask, ((0, 0), (0, Tk_p - Tk)))
    maskb = jnp.broadcast_to(maskb[:, None, :], (B, H, Tk_p)).reshape(B * H, Tk_p)

    out = _flash_core(qb, kb, vb, maskb, causal, block_q, block_k, scale)
    out = out.reshape(B, H, Tq_p, Dp)[:, :, :Tq, :D]
    return jnp.transpose(out, (0, 2, 1, 3))
