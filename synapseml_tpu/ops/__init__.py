"""TPU kernel ops: the compute hot paths of the framework.

The reference delegates its hot loops to prebuilt native engines (LightGBM
C++ histograms, VW C++ SGD, ONNX Runtime CUDA kernels — SURVEY.md §1 L0).
Here the hot ops are first-class TPU kernels:

  * :mod:`attention` — blockwise flash attention (Pallas TPU kernel with an
    XLA blockwise fallback) for the on-chip attention hot path;
  * :mod:`ring_attention` — cross-chip sequence parallelism over a named
    mesh axis via ``ppermute`` (net-new capability, SURVEY.md §5
    "long-context"; the reference has none);
  * :mod:`ulysses_attention` — the all-to-all sequence-parallel strategy
    (heads scatter, tokens gather, local full-context attention).
"""

from .attention import flash_attention, reference_attention
from .ring_attention import ring_attention, ring_attention_sharded
from .ulysses_attention import ulysses_attention, ulysses_attention_sharded

__all__ = [
    "flash_attention",
    "reference_attention",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
