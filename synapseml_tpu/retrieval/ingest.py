"""Continual ingest: flywheel request logs -> delta shards -> next version.

Freshly logged documents (the PR-12 ``continual.RequestLogger`` feedstock)
embed and commit as NEW ``kind="delta"`` shards — no index rebuild. The
whole path is deterministic and exactly-once so a SIGKILLed ingest job
resumed in a fresh process produces a byte-identical index:

* only DONE-committed log parts are read (torn parts invisible);
* the extracted docs file is a pure function of (base manifest, committed
  parts) and is rewritten atomically on resume;
* the embed is a ``scoring.transform_source`` job (DONE-gated parts,
  resume skips completed work);
* delta shards commit via the atomic stage-and-rename in ``shards.py`` —
  a torn delta is a ``.tmp-*`` directory no reader ever lists, and an
  unpublished one is invisible to ``registry.resolve()`` by construction.

The published manifest's ``extra.retrieval.ingested_parts`` records which
log parts each version already absorbed, making re-runs no-ops and the
freshness lag (earliest logged ``ts`` -> publish) a measured metric.
``compact_index`` merges deltas past a threshold into one base shard and
republishes under the next version (same roster discipline).
"""

from __future__ import annotations

import json
import os
import shutil
import time

from .build import embed_corpus, shards_from_parts
from .metrics import retrieval_metrics
from .model import VectorIndexModel
from .shards import list_shards

__all__ = ["ingest_deltas", "compact_index", "extract_documents"]


def _default_doc_fn(record: dict):
    """Pull an ingestible document out of one request-log record: a body
    carrying ``doc`` or ``text``. Return None to skip (non-document
    traffic logs alongside document traffic on a shared front)."""
    body = record.get("body")
    if not isinstance(body, dict):
        return None
    text = body.get("doc") or body.get("text")
    if not text or not isinstance(text, str):
        return None
    return {"text": text, "payload": {"text": text}, "ts": record.get("ts")}


def _committed_log_parts(log_dir: str) -> list[str]:
    out = []
    for name in sorted(os.listdir(log_dir)):
        if (name.startswith("part-") and name.endswith(".jsonl")
                and os.path.exists(os.path.join(log_dir, name + ".DONE"))):
            out.append(name)
    return out


def extract_documents(log_dir: str, parts: list[str], out_path: str, *,
                      doc_fn=None, base_rows: int = 0) -> dict:
    """Deterministically extract documents from the named committed log
    parts into ``out_path`` (JSONL ``{id, text, payload, ts}``; atomic
    write). Doc ids continue the index's global id space at ``base_rows``.
    Returns ``{"docs": n, "min_ts": float|None}``."""
    doc_fn = doc_fn or _default_doc_fn
    docs, min_ts = [], None
    for part in parts:
        with open(os.path.join(log_dir, part)) as f:
            for ln in f:
                if not ln.strip():
                    continue
                doc = doc_fn(json.loads(ln))
                if doc is None:
                    continue
                ts = doc.get("ts")
                if ts is not None:
                    min_ts = ts if min_ts is None else min(min_ts, ts)
                docs.append({"id": base_rows + len(docs),
                             "text": doc["text"],
                             "payload": doc.get("payload")})
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        for d in docs:
            f.write(json.dumps(d, sort_keys=True) + "\n")
    os.replace(tmp, out_path)
    return {"docs": len(docs), "min_ts": min_ts}


def _assemble_index(resolved_path: str, index_dir: str) -> None:
    """Copy the base version's committed shards into the new index tree
    (content-addressed blobs dedupe them at publish, so this costs local
    disk only). Already-copied shards are kept (resume)."""
    src = os.path.join(resolved_path, "shards")
    dst = os.path.join(index_dir, "shards")
    os.makedirs(dst, exist_ok=True)
    for sh in list_shards(src):
        target = os.path.join(dst, sh.name)
        if not os.path.exists(os.path.join(target, "MANIFEST.json")):
            tmp = os.path.join(dst, ".tmp-" + sh.name)
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            shutil.copytree(sh.path, tmp)
            os.rename(tmp, target)


def _republish(registry, name: str, resolved, index_dir: str,
               extra_retrieval: dict, set_latest: bool = True):
    """Publish the assembled tree under the next version, carrying the
    base stage's search params forward."""
    committed = list_shards(os.path.join(index_dir, "shards"))
    base = resolved.stage
    model = VectorIndexModel(
        index_name=name, shard_names=[s.name for s in committed],
        dim=int(committed[0].dim), metric=base.get("metric"),
        k=base.get("k"), query_batch=base.get("query_batch"))
    extra = {"retrieval": dict(extra_retrieval)}
    extra["retrieval"].update({
        "shards": [{"name": s.name, "rows": s.rows, "kind": s.kind}
                   for s in committed],
        "rows": int(sum(s.rows for s in committed)),
        "dim": int(committed[0].dim),
        "metric": base.get("metric"),
    })
    return registry.publish(name, model, extra=extra,
                            set_latest=set_latest, extra_tree=index_dir)


def ingest_deltas(registry, name: str, log_dir: str, embedder,
                  work_dir: str, *, ref: str = "latest", doc_fn=None,
                  vector_col: str = "embedding", batch_rows: int = 256,
                  set_latest: bool = True) -> dict | None:
    """Embed the not-yet-ingested committed log parts under ``log_dir`` as
    delta shards and publish the next index version. Returns the ingest
    report, or None when there is nothing new (also the crash-after-publish
    resume path: the republished manifest already lists the parts).

    ``work_dir`` is the job's scratch/resume root: re-running with the
    same ``work_dir`` after a SIGKILL resumes the embed exactly-once and
    recommits the identical shards."""
    resolved = registry.resolve(name, ref)
    extra = dict((resolved.manifest.get("extra") or {}).get("retrieval") or {})
    already = set(extra.get("ingested_parts") or [])
    parts = [p for p in _committed_log_parts(log_dir) if p not in already]
    if not parts:
        return None
    base_rows = int(extra.get("rows") or 0)
    os.makedirs(work_dir, exist_ok=True)
    docs_path = os.path.join(work_dir, "docs.jsonl")
    info = extract_documents(log_dir, parts, docs_path, doc_fn=doc_fn,
                             base_rows=base_rows)
    if not info["docs"]:
        return None
    from ..data.source import ShardedSource

    source = ShardedSource.jsonl([docs_path])
    sink, report = embed_corpus(embedder, source,
                                os.path.join(work_dir, "emb"),
                                vector_col=vector_col, id_col="id",
                                batch_rows=batch_rows)
    index_dir = os.path.join(work_dir, "index")
    payloads = {}
    with open(docs_path) as f:
        for ln in f:
            d = json.loads(ln)
            payloads[int(d["id"])] = d.get("payload")
    deltas = shards_from_parts(
        sink, index_dir, vector_col=vector_col, id_col="id",
        payload_fn=payloads.get, prefix=f"delta-{resolved.version}",
        kind="delta")
    _assemble_index(resolved.path, index_dir)
    extra["ingested_parts"] = sorted(already | set(parts))
    published = _republish(registry, name, resolved, index_dir, extra,
                           set_latest=set_latest)
    lag = (time.time() - info["min_ts"]) if info["min_ts"] else 0.0
    retrieval_metrics()["freshness"].set(lag, index=name)
    return {
        "name": name, "base_version": resolved.version,
        "version": published.version, "docs": info["docs"],
        "delta_shards": [s.name for s in deltas],
        "freshness_lag_s": lag,
        "quarantined": int(report.rows_quarantined),
    }


def compact_index(registry, name: str, work_dir: str, *,
                  ref: str = "latest", threshold: int = 4,
                  set_latest: bool = True) -> dict | None:
    """Merge the base version's delta shards into ONE new base shard once
    there are >= ``threshold`` of them, republishing under the next
    version. Returns the compaction report, or None below threshold.
    Shards are immutable: compaction writes a new roster, never edits."""
    import numpy as np

    resolved = registry.resolve(name, ref)
    src_shards = list_shards(os.path.join(resolved.path, "shards"))
    deltas = [s for s in src_shards if s.kind == "delta"]
    if len(deltas) < threshold:
        return None
    index_dir = os.path.join(work_dir, "index")
    shards_dir = os.path.join(index_dir, "shards")
    os.makedirs(shards_dir, exist_ok=True)
    # keep bases as-is; fold every delta into one new base shard
    from .shards import write_shard

    vectors = np.concatenate([s.vectors() for s in deltas], axis=0)
    ids = np.concatenate([s.ids() for s in deltas], axis=0)
    payload_lists = [s.payloads() for s in deltas]
    payloads = (None if any(p is None for p in payload_lists)
                else [p for lst in payload_lists for p in lst])
    merged_name = f"base-{resolved.version}-compacted"
    write_shard(shards_dir, merged_name, vectors, ids=ids,
                payloads=payloads, kind="base")
    for s in src_shards:
        if s.kind != "delta":
            target = os.path.join(shards_dir, s.name)
            if not os.path.exists(os.path.join(target, "MANIFEST.json")):
                tmp = os.path.join(shards_dir, ".tmp-" + s.name)
                if os.path.exists(tmp):
                    shutil.rmtree(tmp)
                shutil.copytree(s.path, tmp)
                os.rename(tmp, target)
    extra = dict((resolved.manifest.get("extra") or {}).get("retrieval") or {})
    published = _republish(registry, name, resolved, index_dir, extra,
                           set_latest=set_latest)
    return {"name": name, "base_version": resolved.version,
            "version": published.version,
            "merged": [s.name for s in deltas], "into": merged_name}
