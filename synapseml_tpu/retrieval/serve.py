"""Retrieval worker process: a residency-managed ``/m/<index>`` holder.

Patterned on ``fleet.autoscaler.fleet_worker_main``: the worker resolves
the published index artifact through a byte-budgeted ``ResidencyManager``
(shard bytes count against the same budget as any resident model), serves
it behind ``serve_multi_model``, and registers with the driver advertising
which shard NAMES it is responsible for — the fan-out front assigns each
shard of a query to a worker advertising it, and a worker that advertises
a subset scores only that subset (all workers materialize the full
artifact; the advertisement partitions scoring work, not bytes on disk).

An alias-watch thread polls the registry ref and evicts the resident on
movement, so a delta-shard publish becomes queryable on the NEXT request
with zero serve downtime (the reload rides the residency miss path).
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["retrieval_worker_main"]


def retrieval_worker_main(registry_root: str, index: str,
                          register_url: str | None = None, *,
                          ref: str = "latest",
                          shards: list[str] | None = None,
                          byte_budget: int = 1 << 30, port: int = 0,
                          refresh_s: float = 0.5) -> None:
    """Serve published index ``index`` from one worker process and park.
    ``shards`` limits the advertised scoring responsibility (None = the
    full roster); ``refresh_s`` is the alias-watch poll interval (0
    disables the watch)."""
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    from ..fleet.autoscaler import _post_json
    from ..fleet.residency import ResidencyManager, serve_multi_model
    from ..registry import ModelRegistry

    registry = ModelRegistry(registry_root)
    residency = ResidencyManager(registry, byte_budget, refs={index: ref})
    server = serve_multi_model(residency, port=port)
    stage, version = residency.acquire(index)
    roster = list(stage.get("shard_names") or [])
    advertised = [s for s in (shards if shards is not None else roster)
                  if s in roster] or roster
    info = {"host": server.host, "port": server.port, "pid": os.getpid(),
            "version": version, "model": index,
            "shards": advertised, "total_shards": len(roster)}

    if refresh_s > 0:
        def watch():
            current = version
            while True:
                time.sleep(refresh_s)
                try:
                    target = registry.resolve_ref(index, ref)
                except Exception:  # noqa: BLE001 — transient registry I/O
                    continue
                if target != current:
                    residency.evict(index)  # next acquire loads the mover
                    current = target
                    if register_url:
                        # re-register: a new version may carry new shards
                        # (deltas); a subset worker adds the fresh ones to
                        # its advertisement, a full worker tracks the roster
                        try:
                            st, v = residency.acquire(index)
                            new_roster = list(st.get("shard_names") or [])
                            if shards is None:
                                new_adv = new_roster
                            else:
                                fresh = [s for s in new_roster
                                         if s not in roster]
                                new_adv = sorted(set(info["shards"])
                                                 | set(fresh))
                            info.update(version=v, shards=new_adv,
                                        total_shards=len(new_roster))
                            _post_json(register_url, info)
                        except Exception:  # noqa: BLE001
                            continue

        threading.Thread(target=watch, daemon=True).start()

    if register_url:
        def on_drained(_report):
            from ..io.distributed_serving import deregister_worker

            deregister_worker(register_url, info)
            os._exit(0)

        server.on_drained = on_drained
        _post_json(register_url, info, timeout_s=30.0)
    print(f"retrieval worker ready {json.dumps(info)}", flush=True)
    while True:  # killed by the launcher, or exits via on_drained
        time.sleep(1.0)
