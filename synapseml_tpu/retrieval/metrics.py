"""Retrieval-plane metric handles (``synapseml_retrieval_*`` series).

One HandleCache per process wired to the default observability registry —
the same pattern as ``fleet/residency.py``. Series (see
docs/OBSERVABILITY.md):

* ``synapseml_retrieval_queries_total{index}`` — query vectors scored (QPS)
* ``synapseml_retrieval_shard_scoring_ms{index}`` — per-request worker-side
  shard scoring wall
* ``synapseml_retrieval_merge_ms{index}`` — front-side fan-out + top-k
  merge wall
* ``synapseml_retrieval_shard_coverage{index}`` — scored/expected shard
  fraction per fan-out (the recall proxy: 1.0 = exact)
* ``synapseml_retrieval_partial_total{index}`` — fan-outs answered with
  ``X-Retrieval-Partial``
* ``synapseml_retrieval_freshness_lag_s{index}`` — logged-doc to queryable
  lag measured at delta-shard publish
* ``synapseml_retrieval_resident_shard_bytes{index}`` — shard bytes this
  process holds resident
"""

from __future__ import annotations

from ..core import observability as obs

__all__ = ["retrieval_metrics"]

_RETRIEVAL_METRICS = obs.HandleCache(lambda reg: {
    "queries": reg.counter(
        "synapseml_retrieval_queries_total",
        "query vectors scored through the retrieval plane", ("index",)),
    "shard_ms": reg.histogram(
        "synapseml_retrieval_shard_scoring_ms",
        "worker-side shard scoring wall per request", ("index",)),
    "merge_ms": reg.histogram(
        "synapseml_retrieval_merge_ms",
        "front-side fan-out + global top-k merge wall", ("index",)),
    "coverage": reg.histogram(
        "synapseml_retrieval_shard_coverage",
        "scored/expected shard fraction per fan-out (recall proxy)",
        ("index",)),
    "partial": reg.counter(
        "synapseml_retrieval_partial_total",
        "fan-outs degraded to partial results (X-Retrieval-Partial)",
        ("index",)),
    "freshness": reg.gauge(
        "synapseml_retrieval_freshness_lag_s",
        "logged-document to queryable lag at delta publish", ("index",)),
    "resident_bytes": reg.gauge(
        "synapseml_retrieval_resident_shard_bytes",
        "shard bytes resident in this process", ("index",)),
})


def retrieval_metrics() -> dict:
    """The per-registry handle dict (create-on-first-use)."""
    return _RETRIEVAL_METRICS.get()
