"""Shared per-shard top-k scoring kernel (the ONE |q|^2 - 2 q.x + |x|^2
matmul + ``top_k`` program behind both ``nn/knn.py`` and the retrieval
serving plane).

TVM's pay-compile-once lesson applied to ANN serving: shard scoring is one
[Q, N] MXU matmul + ``jax.lax.top_k`` vmapped over query batches, so every
shard of the same (rows, dim) shape shares ONE executable per query-ladder
rung. Unlike the seed ``KNNModel._topk_fn``, the index matrix is a TRACED
ARGUMENT rather than a closure capture — executables are keyed by shard
SHAPE, not shard identity, so an N-shard index compiles ladder-many
programs total instead of ladder-many per shard.
"""

from __future__ import annotations

import numpy as np

from ..core import batching as cb

__all__ = ["INF", "FN_ID", "score_shard", "score_batches"]

# sentinel distance for masked-out candidates (conditional KNN bias); kept
# below float32 max so the additive mask cannot overflow to inf
INF = np.float32(3.0e38)

FN_ID = "retrieval_score_shard"


def _shard_fn(bucket: int, n: int, d: int, k: int, variant: str):
    """The compiled (Q, X, x_sq[, bias]) -> (dist, idx) executable for one
    static shape, via the shared CompiledCache. ``instance`` stays None on
    purpose: nothing instance-specific is captured, so every caller in the
    process (seed KNN, VectorIndexModel, the bench arms) shares the same
    ladder of executables."""
    def build():
        import jax
        import jax.numpy as jnp

        def fn(Q, X, x_sq, bias=None):
            # [Q, N] squared L2 distances via one MXU matmul
            dist = (jnp.sum(Q * Q, axis=1, keepdims=True)
                    - 2.0 * Q @ X.T + x_sq[None, :])
            if bias is not None:
                dist = dist + bias
            neg_d, idx = jax.lax.top_k(-dist, k)
            return -neg_d, idx

        if variant == "bias":
            return jax.jit(lambda Q, X, x_sq, b: fn(Q, X, x_sq, b))
        return jax.jit(lambda Q, X, x_sq: fn(Q, X, x_sq))

    return cb.get_compiled_cache().get(
        FN_ID, (bucket, n, d, k, variant), build, dtype="float32")


def score_shard(Qb: np.ndarray, X: np.ndarray, x_sq: np.ndarray, k: int,
                bias: np.ndarray | None = None):
    """Top-k of one PADDED query bucket ``Qb`` [B, D] against one shard
    ``X`` [N, D] (``x_sq`` = per-row squared norms, precomputed once per
    shard). Returns numpy ``(dist [B, k'], idx [B, k'])`` with squared L2
    distances, ``k' = min(k, N)``. ``bias`` [B, N] is an additive mask
    (0 = allowed, :data:`INF` = excluded — the conditional-KNN contract)."""
    Qb = np.ascontiguousarray(Qb, np.float32)
    n, d = X.shape
    kk = min(int(k), n)
    variant = "bias" if bias is not None else "plain"
    fn = _shard_fn(Qb.shape[0], n, d, kk, variant)
    if bias is None:
        dist, idx = fn(Qb, X, x_sq)
    else:
        dist, idx = fn(Qb, X, x_sq, np.ascontiguousarray(bias, np.float32))
    return np.asarray(dist), np.asarray(idx)


def score_batches(Q: np.ndarray, X: np.ndarray, k: int, *,
                  x_sq: np.ndarray | None = None, bias_fn=None,
                  bucketer: cb.ShapeBucketer | None = None,
                  query_batch: int = 256):
    """Score EVERY query row against one shard, streaming queries through
    ladder-bucketed padded batches (``bucketer.slices``), so a mixed-size
    query stream compiles at most ladder-many executables per shard shape.

    ``bias_fn(s, e)`` (optional) returns the [e-s, N] additive mask for one
    query slice, or None. Returns ``(dist [n, k'], idx [n, k'])`` numpy
    arrays of squared L2 distances (callers take sqrt for reporting)."""
    Q = np.asarray(Q, np.float32)
    X = np.ascontiguousarray(X, np.float32)
    if x_sq is None:
        x_sq = np.sum(X * X, axis=1, dtype=np.float32)
    n = len(Q)
    kk = min(int(k), X.shape[0])
    dist = np.empty((n, kk), np.float32)
    idx = np.empty((n, kk), np.int64)
    bucketer = bucketer or cb.default_bucketer()
    for s, e, bucket in bucketer.slices(n, query_batch):
        Qb = cb.pad_rows(Q[s:e], bucket)
        bias = bias_fn(s, e) if bias_fn is not None else None
        if bias is not None:
            bias = cb.pad_rows(np.asarray(bias, np.float32), bucket)
        db, ib = score_shard(Qb, X, x_sq, kk, bias)
        dist[s:e] = db[:e - s]
        idx[s:e] = ib[:e - s]
    return dist, idx
