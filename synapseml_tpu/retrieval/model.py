"""VectorIndexModel: the servable sharded-vector-index stage.

A published index IS a registry artifact: the stage's simple params carry
the shard roster (names, dim, metric), the shard data rides the artifact
tree under ``shards/`` (content-addressed blobs, pinned/canaried/GC'd
exactly like model weights), and ``core.serialization.load_stage`` hands
the materialized artifact directory to ``_artifact_dir`` so shards load
lazily on first touch. Per-shard scoring goes through the shared
:mod:`~synapseml_tpu.retrieval.scorer` kernel — query batches ride the
bucket ladder, executables are keyed by shard SHAPE, so N same-shape
shards compile one ladder of programs, not N.

Serving rows (the ``/m/<index>`` residency path) carry a parsed JSON
``body``::

    {"queries": [[...], ...] | "query": [...], "k": 10, "shards": [names]}

and the reply column holds ``{"matches": [[{id, distance, payload,
shard}, ...] per query], "shards": [...], "scoring_ms": ...}`` — the
fan-out front merges these per-shard top-k replies into global top-k.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Model
from . import scorer
from . import shards as _shards
from .metrics import retrieval_metrics

__all__ = ["VectorIndexModel"]


class VectorIndexModel(Model):
    """Top-k search over a roster of immutable :class:`IndexShard`s."""

    feature_name = "retrieval"

    index_name = Param("index_name", "published index name (metric label)",
                       default="index")
    shard_names = Param("shard_names", "committed shard roster, name-sorted",
                        default=None)
    dim = Param("dim", "vector dimensionality", default=0,
                converter=TypeConverters.to_int)
    metric = Param("metric", "distance metric: 'l2' or 'cosine' (cosine "
                   "indexes store L2-normalized vectors; queries are "
                   "normalized host-side)", default="l2")
    k = Param("k", "neighbors returned per query", default=10,
              converter=TypeConverters.to_int)
    query_batch = Param("query_batch", "padded query rows per device batch",
                        default=256, converter=TypeConverters.to_int)
    output_col = Param("output_col", "reply column", default="reply")
    inline_shards = ComplexParam(
        "inline_shards", "in-memory shard dict name -> {name, vectors, ids, "
        "payloads} (tests / small indexes; real indexes ride the artifact "
        "tree)", default=None)

    # -- shard residency ----------------------------------------------------
    def attach(self, shards_root: str) -> "VectorIndexModel":
        """Point the model at an explicit ``shards/`` directory (builds and
        tests; a registry-resolved artifact wires ``_artifact_dir``)."""
        self.__dict__["_shards_root"] = shards_root
        self.__dict__.pop("_resident", None)
        return self

    def shards_root(self) -> str | None:
        root = self.__dict__.get("_shards_root")
        if root:
            return root
        art = getattr(self, "_artifact_dir", None)
        if art:
            return os.path.join(art, "shards")
        return None

    def _shard_data(self, name: str):
        """(X, x_sq, ids, payloads) for one shard, loaded once and memoized
        (bytes accounted in ``synapseml_retrieval_resident_shard_bytes``;
        whole-index residency is byte-budgeted one level up by the fleet
        ``ResidencyManager`` holding this stage)."""
        resident = self.__dict__.setdefault("_resident", {})
        entry = resident.get(name)
        if entry is not None:
            return entry
        inline = self.get("inline_shards") or {}
        if name in inline:
            rec = inline[name]
            X = np.ascontiguousarray(rec["vectors"], np.float32)
            ids = (np.asarray(rec["ids"], np.int64) if rec.get("ids") is not None
                   else np.arange(len(X), dtype=np.int64))
            payloads = rec.get("payloads")
        else:
            root = self.shards_root()
            if root is None:
                raise ValueError(
                    f"shard {name!r} is not inline and no shards root is "
                    "attached (load via the registry, or call attach())")
            sh = _shards.open_shard(os.path.join(root, name))
            X = np.ascontiguousarray(sh.vectors(), np.float32)
            ids = sh.ids()
            payloads = sh.payloads()
        x_sq = np.sum(X * X, axis=1, dtype=np.float32)
        entry = (X, x_sq, ids, payloads)
        resident[name] = entry
        nbytes = X.nbytes + x_sq.nbytes + ids.nbytes
        self.__dict__["_resident_nbytes"] = (
            self.__dict__.get("_resident_nbytes", 0) + nbytes)
        retrieval_metrics()["resident_bytes"].set(
            self.__dict__["_resident_nbytes"], index=self.get("index_name"))
        return entry

    # -- search --------------------------------------------------------------
    def search(self, queries, k: int | None = None,
               shard_names: list[str] | None = None) -> list[list[dict]]:
        """Global top-k per query over ``shard_names`` (default: the full
        roster). Returns one match list per query, each match
        ``{"id", "distance" (sqrt L2), "payload", "shard"}``, distance-
        sorted with ``(distance, id)`` tie-break — byte-stable across shard
        partitionings, which is what the parity tests assert."""
        Q = np.asarray(queries, np.float32)
        if Q.ndim == 1:
            Q = Q[None, :]
        k = int(k if k is not None else self.get("k"))
        names = (list(shard_names) if shard_names is not None
                 else list(self.get("shard_names") or []))
        if self.get("metric") == "cosine":
            Q = Q / np.maximum(np.linalg.norm(Q, axis=1, keepdims=True), 1e-9)
        per_query: list[list[dict]] = [[] for _ in range(len(Q))]
        t0 = time.perf_counter()
        for nm in names:
            X, x_sq, ids, payloads = self._shard_data(nm)
            kk = min(k, X.shape[0])
            if kk == 0:
                continue
            dist, idx = scorer.score_batches(
                Q, X, kk, x_sq=x_sq, query_batch=self.get("query_batch"))
            for i in range(len(Q)):
                row = per_query[i]
                for d, j in zip(dist[i], idx[i]):
                    row.append({
                        "id": int(ids[j]),
                        "distance": float(np.sqrt(max(float(d), 0.0))),
                        "payload": payloads[j] if payloads is not None else None,
                        "shard": nm,
                    })
        for i, row in enumerate(per_query):
            row.sort(key=lambda m: (m["distance"], m["id"]))
            per_query[i] = row[:k]
        m = retrieval_metrics()
        label = self.get("index_name")
        m["queries"].inc(len(Q), index=label)
        m["shard_ms"].observe((time.perf_counter() - t0) * 1000.0, index=label)
        return per_query

    # -- serving -------------------------------------------------------------
    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, "body")

        def per_part(p):
            bodies = p["body"]
            replies = np.empty(len(bodies), dtype=object)
            for i, b in enumerate(bodies):
                if not isinstance(b, dict):
                    b = json.loads(b)
                qs = b.get("queries")
                if qs is None and "query" in b:
                    qs = [b["query"]]
                if qs is None:
                    replies[i] = {"error": "body needs 'queries' or 'query'"}
                    continue
                k = int(b.get("k") or self.get("k"))
                names = b.get("shards")
                t0 = time.perf_counter()
                matches = self.search(np.asarray(qs, np.float32), k=k,
                                      shard_names=names)
                replies[i] = {
                    "matches": matches,
                    "shards": list(names if names is not None
                                   else self.get("shard_names") or []),
                    "scoring_ms": (time.perf_counter() - t0) * 1000.0,
                }
            q = dict(p)
            q[self.get("output_col")] = replies
            return q

        return df.map_partitions(per_part)
