"""IndexShard: the immutable on-disk unit of a vector index.

One shard is one directory::

    <name>/
      vectors.npy     float32 [N, D]
      ids.npy         int64 [N] global document ids
      payload.jsonl   N JSON lines (optional; the match's returned payload)
      MANIFEST.json   {name, rows, dim, kind, format_version, files:{...}}

``MANIFEST.json`` carries a sha256 per data file, so a shard is verifiable
end-to-end after riding the registry's content-addressed blob store.
Commit is ATOMIC: a shard is staged under ``.tmp-<name>`` and renamed into
place, so readers (``list_shards``, ``open_shard``) can never observe a
torn shard — the same part/DONE discipline as the scoring sinks, one level
up. Shards never mutate; continual ingest adds NEW ``kind="delta"`` shards
and compaction republishes merged ``kind="base"`` shards under the next
index version.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil

import numpy as np

__all__ = ["IndexShard", "SHARD_MANIFEST", "write_shard", "open_shard",
           "list_shards"]

SHARD_MANIFEST = "MANIFEST.json"
FORMAT_VERSION = 1
_TMP_PREFIX = ".tmp-"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


@dataclasses.dataclass
class IndexShard:
    """Handle on one committed shard directory (data loads lazily)."""

    name: str
    path: str
    rows: int
    dim: int
    kind: str  # "base" | "delta"
    manifest: dict

    def vectors(self) -> np.ndarray:
        return np.load(os.path.join(self.path, "vectors.npy"))

    def ids(self) -> np.ndarray:
        return np.load(os.path.join(self.path, "ids.npy"))

    def payloads(self) -> list | None:
        p = os.path.join(self.path, "payload.jsonl")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return [json.loads(ln) for ln in f if ln.strip()]

    @property
    def nbytes(self) -> int:
        return sum(e["bytes"] for e in self.manifest["files"].values())

    def verify(self) -> None:
        """Recompute every data file's sha256 against the manifest."""
        for fname, entry in self.manifest["files"].items():
            got = _sha256(os.path.join(self.path, fname))
            if got != entry["sha256"]:
                raise ValueError(
                    f"shard {self.name!r}: {fname} sha mismatch "
                    f"(manifest {entry['sha256'][:12]}, file {got[:12]})")


def write_shard(shards_dir: str, name: str, vectors: np.ndarray,
                ids: np.ndarray | None = None, payloads: list | None = None,
                kind: str = "base", overwrite: bool = False) -> IndexShard:
    """Atomically commit one shard under ``shards_dir/name``. An existing
    committed shard is returned as-is unless ``overwrite`` (idempotent
    resume: a re-run of an interrupted build skips what already landed)."""
    if kind not in ("base", "delta"):
        raise ValueError(f"shard kind must be 'base' or 'delta', got {kind!r}")
    final = os.path.join(shards_dir, name)
    if os.path.exists(os.path.join(final, SHARD_MANIFEST)):
        if not overwrite:
            return open_shard(final)
        shutil.rmtree(final)
    vectors = np.ascontiguousarray(vectors, np.float32)
    if vectors.ndim != 2:
        raise ValueError(f"shard vectors must be [N, D], got {vectors.shape}")
    n, d = vectors.shape
    if ids is None:
        ids = np.arange(n, dtype=np.int64)
    ids = np.ascontiguousarray(ids, np.int64)
    if len(ids) != n:
        raise ValueError(f"{len(ids)} ids for {n} vectors")
    if payloads is not None and len(payloads) != n:
        raise ValueError(f"{len(payloads)} payloads for {n} vectors")
    tmp = os.path.join(shards_dir, _TMP_PREFIX + name)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.save(os.path.join(tmp, "vectors.npy"), vectors)
    np.save(os.path.join(tmp, "ids.npy"), ids)
    if payloads is not None:
        with open(os.path.join(tmp, "payload.jsonl"), "w") as f:
            for p in payloads:
                f.write(json.dumps(p) + "\n")
    files = {}
    for fname in sorted(os.listdir(tmp)):
        fp = os.path.join(tmp, fname)
        files[fname] = {"sha256": _sha256(fp), "bytes": os.path.getsize(fp)}
    manifest = {"name": name, "rows": n, "dim": d, "kind": kind,
                "format_version": FORMAT_VERSION, "files": files}
    with open(os.path.join(tmp, SHARD_MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.rename(tmp, final)  # the atomic commit point
    return IndexShard(name=name, path=final, rows=n, dim=d, kind=kind,
                      manifest=manifest)


def open_shard(path: str, verify: bool = False) -> IndexShard:
    """Open one committed shard directory; ``verify`` recomputes shas."""
    with open(os.path.join(path, SHARD_MANIFEST)) as f:
        manifest = json.load(f)
    shard = IndexShard(name=manifest["name"], path=path,
                       rows=int(manifest["rows"]), dim=int(manifest["dim"]),
                       kind=manifest.get("kind", "base"), manifest=manifest)
    if verify:
        shard.verify()
    return shard


def list_shards(shards_dir: str) -> list[IndexShard]:
    """Every COMMITTED shard under ``shards_dir``, name-sorted. Staged
    ``.tmp-*`` directories (a torn write) are invisible by construction."""
    out = []
    try:
        names = sorted(os.listdir(shards_dir))
    except OSError:
        return []
    for name in names:
        if name.startswith(_TMP_PREFIX):
            continue
        p = os.path.join(shards_dir, name)
        if os.path.isdir(p) and os.path.exists(os.path.join(p, SHARD_MANIFEST)):
            out.append(open_shard(p))
    return out
