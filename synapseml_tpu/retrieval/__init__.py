"""Retrieval serving plane: sharded vector index with continual ingest.

The plane composes existing seams instead of inventing new ones:

* **build** — embedding backfill as a ``scoring.transform_source`` job;
  DONE-gated ``NpySink`` parts become immutable :class:`IndexShard`s;
  ``publish_index`` rides the registry's content-addressed blob store so
  indexes are pinned/aliased/canaried/GC'd exactly like model weights;
* **serve** — :class:`VectorIndexModel` scores each shard through the ONE
  shared matmul+top_k kernel (:mod:`.scorer`, also the engine behind
  ``nn/knn.py``) on the bucket ladder; workers are byte-budgeted
  ``ResidencyManager`` holders behind ``/m/<index>``; the ``RoutingFront``
  fans a query to the workers advertising the index's shards and merges
  per-shard top-k into global top-k (missing shards degrade to partial
  results with ``X-Retrieval-Partial``, never 500s);
* **ingest** — freshly logged documents (the continual-flywheel request
  log) embed and commit as NEW delta shards under the next version, no
  rebuild; ``compact_index`` folds deltas past a threshold; freshness lag
  is a measured metric;
* **observe** — ``synapseml_retrieval_*`` series (:mod:`.metrics`).

Submodules import lazily (PEP 562): ``nn/knn.py`` pulls only the scorer
without dragging the fleet/registry serve chain into every KNN import.
"""

from __future__ import annotations

import importlib

__all__ = [
    "INF", "score_shard", "score_batches",
    "IndexShard", "SHARD_MANIFEST", "write_shard", "open_shard",
    "list_shards",
    "VectorIndexModel",
    "HashEmbedder", "embed_corpus", "shards_from_parts", "index_model_for",
    "publish_index", "build_index",
    "ingest_deltas", "compact_index", "extract_documents",
    "retrieval_worker_main",
    "retrieval_metrics",
]

_LOCATIONS = {
    "INF": "scorer", "score_shard": "scorer", "score_batches": "scorer",
    "IndexShard": "shards", "SHARD_MANIFEST": "shards",
    "write_shard": "shards", "open_shard": "shards", "list_shards": "shards",
    "VectorIndexModel": "model",
    "HashEmbedder": "build", "embed_corpus": "build",
    "shards_from_parts": "build", "index_model_for": "build",
    "publish_index": "build", "build_index": "build",
    "ingest_deltas": "ingest", "compact_index": "ingest",
    "extract_documents": "ingest",
    "retrieval_worker_main": "serve",
    "retrieval_metrics": "metrics",
}


def __getattr__(name: str):
    submodule = _LOCATIONS.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(f".{submodule}", __name__), name)
    globals()[name] = value  # cache: one import, stable identity
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
