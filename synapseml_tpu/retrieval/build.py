"""Index build: embedding backfill -> immutable shards -> published artifact.

The backfill IS a bulk-scoring job: any vector-producing stage
(``HuggingFaceSentenceEmbedder``, the dependency-free :class:`HashEmbedder`
below) runs over a ``ShardedSource`` corpus via
``scoring.transform_source`` into an ``NpySink`` — exactly-once, resumable,
quarantining — and each completed DONE-gated part becomes one immutable
:class:`~synapseml_tpu.retrieval.shards.IndexShard`. ``publish_index``
then rides ``ModelRegistry.publish(extra_tree=...)``: the shard files land
in the manifest's ``files`` list as content-addressed blobs, so an index
version is pinned, aliased (``latest``/``prod``), canaried and GC'd
exactly like model weights — and unchanged shards dedupe across versions
(a delta publish re-ingests only the new shards' bytes).
"""

from __future__ import annotations

import os

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer
from .model import VectorIndexModel
from .shards import IndexShard, list_shards, write_shard

__all__ = ["HashEmbedder", "embed_corpus", "shards_from_parts",
           "publish_index", "build_index"]


class HashEmbedder(Transformer):
    """Deterministic feature-hashing text embedder (pure numpy, zero model
    weights) — the corpus-scale stand-in for
    ``hf.HuggingFaceSentenceEmbedder`` in tests and the CPU bench arms.
    Tokens hash to a signed coordinate (the classic hashing trick), so the
    same text always embeds to the same vector in any process."""

    feature_name = "retrieval"

    text_col = Param("text_col", "input text column", default="text")
    output_col = Param("output_col", "embedding column", default="embedding")
    dim = Param("dim", "embedding dimensionality", default=64,
                converter=TypeConverters.to_int)
    seed = Param("seed", "hash seed (a different seed is a different "
                 "embedding space)", default=0, converter=TypeConverters.to_int)
    normalize = Param("normalize", "L2-normalize embeddings (cosine indexes)",
                      default=False, converter=TypeConverters.to_bool)

    def embed(self, texts) -> np.ndarray:
        import hashlib

        dim = self.get("dim")
        seed = self.get("seed")
        out = np.zeros((len(texts), dim), np.float32)
        for i, t in enumerate(texts):
            for tok in str(t).lower().split():
                h = hashlib.md5(f"{seed}:{tok}".encode()).digest()
                j = int.from_bytes(h[:4], "little") % dim
                out[i, j] += 1.0 if h[4] & 1 else -1.0
        if self.get("normalize"):
            out /= np.maximum(np.linalg.norm(out, axis=1, keepdims=True), 1e-9)
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("text_col"))

        def per_part(p):
            q = dict(p)
            q[self.get("output_col")] = self.embed(list(p[self.get("text_col")]))
            return q

        return df.map_partitions(per_part)


def embed_corpus(stage, source, sink_dir: str, *,
                 vector_col: str = "embedding", id_col: str = "id",
                 batch_rows: int = 256, **transform_kw):
    """Run the embedding backfill: ``stage`` over ``source`` into an
    ``NpySink`` at ``sink_dir`` carrying ``[vector_col, id_col]``. Returns
    ``(sink, report)``. Exactly-once: a re-run (crash resume) skips
    DONE-committed parts, so the sink bytes are identical to an
    uninterrupted run."""
    from ..scoring import NpySink, transform_source

    sink = NpySink(sink_dir, columns=[vector_col, id_col])
    report = transform_source(stage, source, sink, batch_rows=batch_rows,
                              **transform_kw)
    return sink, report


def shards_from_parts(sink, index_dir: str, *,
                      vector_col: str = "embedding", id_col: str = "id",
                      payload_fn=None, prefix: str = "base",
                      kind: str = "base") -> list[IndexShard]:
    """One immutable shard per completed sink part, committed atomically
    under ``index_dir/shards/<prefix>-NNNNN``. Idempotent: already-committed
    shards are kept as-is (byte-identical resume). ``payload_fn(id)``
    (optional) supplies each row's returned payload — payloads are
    non-numeric, so they ride the shard sidecar, not the npy sink."""
    shards_dir = os.path.join(index_dir, "shards")
    os.makedirs(shards_dir, exist_ok=True)
    out = []
    done = sink.completed()
    for i in sorted(done):
        stem = sink.part_stem(i)
        vec_name = f"{stem}.{vector_col}.npy"
        if vec_name not in done[i]["files"]:
            continue  # zero-row part (every row quarantined)
        vectors = np.load(os.path.join(sink.path, vec_name))
        if not vectors.shape[0]:
            continue
        ids = np.asarray(np.load(os.path.join(
            sink.path, f"{stem}.{id_col}.npy")), np.int64)
        payloads = ([payload_fn(int(d)) for d in ids]
                    if payload_fn is not None else None)
        out.append(write_shard(shards_dir, f"{prefix}-{i:05d}", vectors,
                               ids=ids, payloads=payloads, kind=kind))
    return out


def index_model_for(index_dir: str, *, name: str = "index",
                    metric: str = "l2", k: int = 10,
                    query_batch: int = 256) -> VectorIndexModel:
    """A :class:`VectorIndexModel` over the committed shards of
    ``index_dir`` (roster read from disk, data attached lazily)."""
    committed = list_shards(os.path.join(index_dir, "shards"))
    if not committed:
        raise ValueError(f"no committed shards under {index_dir!r}")
    dims = {s.dim for s in committed}
    if len(dims) != 1:
        raise ValueError(f"mixed shard dims {sorted(dims)} under {index_dir!r}")
    model = VectorIndexModel(index_name=name,
                             shard_names=[s.name for s in committed],
                             dim=dims.pop(), metric=metric, k=k,
                             query_batch=query_batch)
    return model.attach(os.path.join(index_dir, "shards"))


def publish_index(registry, name: str, index_dir: str, *,
                  metric: str = "l2", k: int = 10, query_batch: int = 256,
                  version: str | None = None, set_latest: bool = True,
                  metrics: dict | None = None):
    """Publish ``index_dir`` (its ``shards/`` tree) as registry artifact
    ``name``: the stage is a :class:`VectorIndexModel` carrying the shard
    roster, ``extra_tree`` rides the shard files into the content-addressed
    manifest, and the manifest's ``extra.retrieval`` section records the
    roster + row counts for operators. Returns the ``PublishedVersion``."""
    model = index_model_for(index_dir, name=name, metric=metric, k=k,
                            query_batch=query_batch)
    committed = list_shards(os.path.join(index_dir, "shards"))
    extra = {"retrieval": {
        "shards": [{"name": s.name, "rows": s.rows, "kind": s.kind}
                   for s in committed],
        "rows": int(sum(s.rows for s in committed)),
        "dim": int(committed[0].dim),
        "metric": metric,
    }}
    return registry.publish(name, model, version=version, metrics=metrics,
                            extra=extra, set_latest=set_latest,
                            extra_tree=index_dir)


def build_index(registry, name: str, stage, source, work_dir: str, *,
                vector_col: str = "embedding", id_col: str = "id",
                payload_fn=None, metric: str = "l2", k: int = 10,
                batch_rows: int = 256, version: str | None = None,
                **transform_kw):
    """The whole v1 pipeline: backfill -> shards -> publish. Returns
    ``(published, report)``."""
    sink, report = embed_corpus(stage, source, os.path.join(work_dir, "emb"),
                                vector_col=vector_col, id_col=id_col,
                                batch_rows=batch_rows, **transform_kw)
    index_dir = os.path.join(work_dir, "index")
    shards_from_parts(sink, index_dir, vector_col=vector_col, id_col=id_col,
                      payload_fn=payload_fn)
    published = publish_index(registry, name, index_dir, metric=metric, k=k,
                              version=version)
    return published, report
