"""Mesh construction and sharding policy — the heart of the TPU runtime.

Replaces the reference's three per-engine comm stacks (LightGBM socket ring
``NetworkManager.scala``, VW spanning-tree ``VowpalWabbitClusterUtil.scala:15-42``,
horovod ring-allreduce ``dl/utils.py:31-46``) with ONE backend: a named
`jax.sharding.Mesh` whose axes express every parallelism the framework uses:

  axis      meaning                                   reference analog
  ----      -------                                   ----------------
  'data'    data parallelism (batch sharding)         Spark partitions / horovod DP
  'fsdp'    parameter sharding inside the DP group    (none — net new)
  'tensor'  tensor (model) parallelism                (none — net new)
  'seq'     sequence/context parallelism              (none — net new, ring attention)
  'expert'  expert parallelism for MoE                (none — net new)
  'pipe'    pipeline (stage) parallelism              (none — net new, GPipe schedule)

Collectives ride ICI within a slice, DCN across slices; XLA inserts them from
sharding annotations (GSPMD), we only name axes and place constraints.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["MeshConfig", "MeshContext", "create_mesh", "batch_sharding", "replicated",
           "logical_axis_rules", "shard_params", "shard_inference_params", "P"]

AXES = ("data", "fsdp", "tensor", "seq", "expert", "pipe")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes; -1 on `data` means 'absorb all remaining devices'."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dataclasses.asdict(self)
        fixed = math.prod(v for v in sizes.values() if v > 0)
        free = [k for k, v in sizes.items() if v <= 0]
        if len(free) > 1:
            raise ValueError(f"at most one axis may be -1, got {free}")
        if free:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[free[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(f"mesh {sizes} does not cover {n_devices} devices")
        return sizes


class MeshContext:
    """A constructed mesh plus sharding helpers; the framework-wide handle that
    estimators receive instead of a horovod backend / NetworkManager."""

    def __init__(self, mesh: Mesh, config: MeshConfig):
        self.mesh = mesh
        self.config = config

    @property
    def axis_sizes(self) -> dict[str, int]:
        return dict(zip(self.mesh.axis_names, self.mesh.devices.shape))

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def batch_sharding(self) -> NamedSharding:
        """Shard leading (batch) dim over every data-like axis."""
        return self.sharding(("data", "fsdp"))

    def replicated(self) -> NamedSharding:
        return self.sharding()

    def data_parallel_size(self) -> int:
        s = self.axis_sizes
        return s.get("data", 1) * s.get("fsdp", 1)

    def shard_batch(self, batch: Any) -> Any:
        """Place a host pytree of arrays onto the mesh, batch-dim sharded.
        Cross-process meshes build from local slices (``place_leaf``) —
        every process supplies the same global batch."""
        from .partition import place_leaf

        sh = self.batch_sharding()
        return jax.tree.map(lambda x: place_leaf(x, sh), batch)

    def shard_stacked_batch(self, batch: Any) -> Any:
        """Place [K, batch, ...] step-stacked arrays: K replicated (scan axis),
        batch dim sharded over the data axes."""
        from .partition import place_leaf

        sh = self.sharding(None, ("data", "fsdp"))
        return jax.tree.map(lambda x: place_leaf(x, sh), batch)

    def __enter__(self):
        self._ctx = self.mesh.__enter__()
        return self

    def __exit__(self, *exc):
        return self.mesh.__exit__(*exc)


def create_mesh(config: MeshConfig | None = None, devices: Sequence[Any] | None = None,
                allow_fewer: bool = True) -> MeshContext:
    """Build the framework mesh over the available devices.

    Device order: `jax.devices()` already orders TPU devices so that adjacent
    ids are ICI neighbors within a host; we lay the fastest-varying mesh axes
    (tensor/seq) innermost so their collectives stay on-host/ICI and `data`
    outermost so DP gradient reduction crosses DCN only when unavoidable —
    the TPU equivalent of the reference's "sort machine list by min partition id"
    determinism (``NetworkManager.scala:354-425``).
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    try:
        sizes = config.resolve(n)
    except ValueError:
        if not allow_fewer:
            raise
        # degrade gracefully on smaller device counts (e.g. 1-chip CI)
        sizes = {k: 1 for k in AXES}
        sizes["data"] = n
    shape = tuple(sizes[a] for a in AXES)
    arr = np.asarray(devices).reshape(shape)
    mesh = Mesh(arr, AXES)
    return MeshContext(mesh, config)


def batch_sharding(mesh_ctx: MeshContext) -> NamedSharding:
    return mesh_ctx.batch_sharding()


def replicated(mesh_ctx: MeshContext) -> NamedSharding:
    return mesh_ctx.replicated()


# ---- logical axis rules: Flax `nn.with_partitioning` names -> mesh axes ----

DEFAULT_RULES: tuple[tuple[str, Any], ...] = (
    ("batch", ("data", "fsdp")),
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("vocab", "tensor"),
    ("seq", "seq"),
    ("expert", "expert"),
    ("pipe", "pipe"),
)


def logical_axis_rules(extra: Sequence[tuple[str, Any]] = ()) -> list[tuple[str, Any]]:
    return list(DEFAULT_RULES) + list(extra)


def shard_params(params: Any, mesh_ctx: MeshContext, rules: Sequence[tuple[str, Any]] | None = None) -> Any:
    """Apply logical->physical sharding to a Flax param pytree with
    `nn.Partitioned` metadata; plain arrays replicate."""
    import flax.linen as nn
    from flax.core import meta

    rules = rules or logical_axis_rules()

    def to_sharding(x):
        if isinstance(x, meta.Partitioned):
            spec = nn.logical_to_mesh_axes(x.names, rules=rules)
            return jax.device_put(x.value, NamedSharding(mesh_ctx.mesh, spec))
        return jax.device_put(x, mesh_ctx.replicated())

    return jax.tree.map(to_sharding, params,
                        is_leaf=lambda x: isinstance(x, meta.Partitioned))


def shard_inference_params(module, example_inputs: dict, params, mesh_ctx,
                           rules: Sequence[tuple[str, Any]] | None = None):
    """Place a PLAIN param pytree (e.g. from models.convert_hf) onto the mesh
    with the module's logical shardings — the inference-side analog of the
    trainer's init-time sharding (Llama-2-7B sharded batch inference,
    BASELINE.md). The module is abstractly initialized (eval_shape: no
    compute, no memory) just to recover each param's ``nn.Partitioned`` axis
    names; values then device_put with those shardings.
    """
    import jax

    from flax.core import meta

    abstract = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0), **example_inputs))
    boxes = abstract["params"]
    flat_boxes = {tuple(str(getattr(k, "key", k)) for k in path): leaf
                  for path, leaf in jax.tree_util.tree_flatten_with_path(
                      boxes, is_leaf=lambda x: isinstance(x, meta.Partitioned))[0]}

    # re-box the plain values with the module's metadata, then delegate to
    # shard_params so train and inference placement share one code path
    def rebox(path, v):
        key = tuple(str(getattr(k, "key", k)) for k in path)
        box = flat_boxes.get(key)
        if isinstance(box, meta.Partitioned):
            return box.replace_boxed(v)
        return v

    boxed = jax.tree_util.tree_map_with_path(rebox, params)
    return shard_params(boxed, mesh_ctx, rules)
