"""Elastic gang layer: preemption-tolerant multi-host training runs.

The multi-controller SPMD shape (every host one failure domain) plus the
scale-reliability arithmetic of arXiv:1810.11112 (mean time between host
failures drops below run length) make *survival* the gating property of a
long run. This module keeps the :class:`~synapseml_tpu.parallel.backend.
DriverRendezvous` TCP channel ALIVE after bootstrap and turns it into the
gang's membership plane:

* **failure detection** — every worker sends one heartbeat per optimizer
  step (fed from the ``supervisor.heartbeat(step)`` seam via
  ``Trainer.fit(gang=...)``); the driver tracks per-rank last-beat times
  against a missed-beat deadline and treats a dropped connection (SIGKILL,
  OOM, host loss) as immediate death. Per-host step latencies export as
  ``synapseml_train_gang_*`` gauges, so stragglers are visible before they
  become failures.
* **verdicts** — the driver broadcasts one of two verdicts:
  ``abort_and_checkpoint`` (a member received a preemption notice: all
  hosts run the coordinated-checkpoint dance inside the grace window, then
  exit :data:`EXIT_PREEMPTED`) or ``resize`` (a member is already dead —
  no complete checkpoint is possible, survivors exit :data:`EXIT_RESIZE`
  and the launcher resumes M survivors from the last *committed* step).
* **coordinated checkpoints** — periodic saves go through
  ``parallel.checkpoint.save_checkpoint_shard`` (each host writes only its
  locally-addressable slices + its per-host ``data_iter`` cursors); the
  driver's commit scanner writes the two-phase ``DONE`` marker only when
  every rank's ACK landed, then runs keep-last-K verified retention GC.
* **N→M elastic resume** — :func:`elastic_restore` reassembles the
  N-shard checkpoint on ANY number of survivors and rebuilds the
  :class:`~synapseml_tpu.data.state.ElasticPlan` from the per-rank
  cursors; ``models.trainer.fit_gang_source`` re-derives placement from
  the PR-10 rule tables and continues the batch stream with zero replayed
  and zero skipped rows.

The emergency-checkpoint dance (preemption notice, SIGTERM):

    worker i --preempt--> driver
    driver   --verdict: abort_and_checkpoint--> all workers
    worker j --ready(step_j)--> driver           (stops at its boundary)
    driver   --sync(S = max step_j)--> all       (lockstep SPMD: all equal)
    worker j  trains to S, writes its shard, --ack(S)--> driver
    driver    commit_checkpoint(S) --committed(S)--> all
    worker j  exits EXIT_PREEMPTED

Every phase is deadline-bounded (``core.resilience.Deadline``); a dance
that cannot complete inside the grace window degrades to ``resize`` —
survivors resume from the previous committed step (bounded lost work,
never a torn artifact: an uncommitted step dir is invisible to restore).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import threading
import time

from ..core import observability as obs
from ..core.faults import active_fault_plan
from ..core.resilience import Deadline, resilience_measures
from .checkpoint import (checkpoint_meta, checkpoint_world, commit_checkpoint,
                         gc_checkpoints, latest_verified_step,
                         restore_checkpoint, restore_host_states)

__all__ = ["GangCoordinator", "GangWorker", "GangAborted", "Preempted",
           "ElasticResume", "elastic_restore", "run_gang_member",
           "launch_gang_processes", "finish_gang_processes",
           "EXIT_PREEMPTED", "EXIT_RESIZE"]

# distinct exit codes so a supervisor/launcher can tell "resume me" apart
# from a crash: EX_TEMPFAIL for a preemption-notice exit (a coordinated
# emergency checkpoint WAS committed), +1 for a resize exit (a member died;
# resume from the last periodic commit)
EXIT_PREEMPTED = 75
EXIT_RESIZE = 76


class GangAborted(RuntimeError):
    """The driver broadcast a ``resize`` verdict (a gang member died) —
    exit now and let the launcher resume the survivors from the last
    committed checkpoint."""


class Preempted(RuntimeError):
    """This worker completed the emergency-checkpoint dance: ``step`` is
    the committed step. Exit with :data:`EXIT_PREEMPTED`."""

    def __init__(self, step: int):
        super().__init__(f"gang preempted: emergency checkpoint committed "
                         f"at step {step}")
        self.step = int(step)


_GANG_METRICS = obs.HandleCache(lambda reg: {
    "members": reg.gauge(
        "synapseml_train_gang_members",
        "gang members currently alive (driver view)"),
    "last_step": reg.gauge(
        "synapseml_train_gang_last_step",
        "newest heartbeat step per rank", ("rank",)),
    "step_latency": reg.gauge(
        "synapseml_train_gang_step_latency_ms",
        "wall time between a rank's consecutive heartbeats — the "
        "straggler gauge", ("rank",)),
    "beats": reg.counter(
        "synapseml_train_gang_beats_total",
        "heartbeats received per rank", ("rank",)),
    "beats_missed": reg.counter(
        "synapseml_train_gang_beats_missed_total",
        "missed-beat detections per rank (deadline exceeded)", ("rank",)),
    "verdicts": reg.counter(
        "synapseml_train_gang_verdicts_total",
        "driver verdict broadcasts", ("verdict",)),
    "commits": reg.counter(
        "synapseml_train_gang_commits_total",
        "coordinated checkpoints committed (two-phase DONE written)",
        ("kind",)),
})


def _send_line(sock: socket.socket, payload: dict) -> None:
    sock.sendall((json.dumps(payload) + "\n").encode())


class _Member:
    """Driver-side per-rank record."""

    def __init__(self, rank: int, conn: socket.socket):
        self.rank = rank
        self.conn = conn
        self.last_seen = time.monotonic()
        self.last_step = -1
        self.alive = True
        self.done_code: str | None = None  # orderly exit ("bye") reason
        self.ready_step: int | None = None
        self.ack_step: int | None = None
        self.lock = threading.Lock()  # serialize sends to this conn


class GangCoordinator:
    """Driver side of the gang channel.

    Built on the sockets :class:`~synapseml_tpu.parallel.backend.
    DriverRendezvous` keeps open after bootstrap (``keep_alive=True``) —
    the same deterministic rank order. ``beat_timeout_s`` is the
    missed-beat deadline (cover your slowest compile), ``grace_s`` bounds
    the whole emergency-checkpoint dance (the preemption grace window).
    ``checkpoint_dir`` enables the commit scanner: periodic per-rank shard
    writes become restorable the moment the full ACK set lands, and
    ``keep`` verified steps are retained.
    """

    def __init__(self, conns: dict[int, socket.socket], *,
                 checkpoint_dir: str | None = None,
                 beat_timeout_s: float = 30.0, grace_s: float = 20.0,
                 keep: int = 3, poll_s: float = 0.1,
                 run_id: str | None = None):
        self.world = len(conns)
        # this launch's incarnation id (DriverRendezvous.run_id): commits
        # only accept ACKs stamped with it — stale acks from a killed
        # previous run over the same dir can never complete a set
        self.run_id = run_id
        self.members = {rank: _Member(rank, conn)
                        for rank, conn in sorted(conns.items())}
        self.checkpoint_dir = checkpoint_dir
        self.beat_timeout_s = float(beat_timeout_s)
        self.grace_s = float(grace_s)
        self.keep = int(keep)
        self.poll_s = float(poll_s)
        self.failure: tuple[int, str] | None = None
        self.committed_steps: list[int] = []
        self.preempt_commit_step: int | None = None
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._dance = threading.Event()   # one dance at a time
        self._verified_cache: dict = {}  # step -> verification outcome
        self._threads: list[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "GangCoordinator":
        for m in self.members.values():
            t = threading.Thread(target=self._reader, args=(m,), daemon=True)
            t.start()
            self._threads.append(t)
        for fn in (self._monitor, self._commit_scan):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        _GANG_METRICS.get()["members"].set(self.alive_count())
        return self

    def close(self) -> None:
        self._stop.set()
        for m in self.members.values():
            try:
                m.conn.close()
            except OSError:
                pass

    # -- queries ------------------------------------------------------------
    def alive_count(self) -> int:
        return sum(1 for m in self.members.values() if m.alive)

    def alive_ranks(self) -> list[int]:
        return [r for r, m in self.members.items() if m.alive]

    def status(self) -> dict:
        return {r: {"alive": m.alive, "last_step": m.last_step,
                    "done": m.done_code}
                for r, m in self.members.items()}

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def wait_failure(self, timeout_s: float) -> tuple[int, str] | None:
        deadline = Deadline(timeout_s)
        while not deadline.expired():
            if self.failure is not None:
                return self.failure
            time.sleep(self.poll_s)
        return self.failure

    def wait_all_exited(self, timeout_s: float) -> bool:
        """True once every member is done (orderly bye) or dead."""
        deadline = Deadline(timeout_s)
        while not deadline.expired():
            if all(not m.alive or m.done_code is not None
                   for m in self.members.values()):
                return True
            time.sleep(self.poll_s)
        return False

    def wait_commit(self, step: int | None = None,
                    timeout_s: float = 30.0) -> int | None:
        """Block until a coordinated checkpoint commits (any, or ``step``)."""
        deadline = Deadline(timeout_s)
        while not deadline.expired():
            with self._lock:
                hits = [s for s in self.committed_steps
                        if step is None or s == step]
            if hits:
                return hits[-1]
            time.sleep(self.poll_s)
        return None

    # -- protocol: reader / monitor / commit scanner ------------------------
    def _record(self, **event) -> None:
        with self._lock:
            self._events.append(event)

    def _reader(self, m: _Member) -> None:
        f = m.conn.makefile("r")
        try:
            for line in f:
                if self._stop.is_set():
                    return
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                self._on_message(m, msg)
                if msg.get("t") == "bye":
                    return
        except (OSError, ValueError):
            pass
        finally:
            if not self._stop.is_set() and m.alive and m.done_code is None:
                # connection died without an orderly bye: the process is
                # gone (SIGKILL / host loss) — immediate failure, no need
                # to wait out the beat deadline
                self._mark_dead(m, "connection lost")

    def _on_message(self, m: _Member, msg: dict) -> None:
        t = msg.get("t")
        now = time.monotonic()
        if t == "beat":
            gm = _GANG_METRICS.get()
            dt_ms = (now - m.last_seen) * 1e3
            m.last_step = int(msg.get("step", m.last_step))
            m.last_seen = now
            gm["beats"].inc(rank=str(m.rank))
            gm["last_step"].set(m.last_step, rank=str(m.rank))
            gm["step_latency"].set(dt_ms, rank=str(m.rank))
        elif t == "preempt":
            m.last_seen = now
            self._record(event="preempt_notice", rank=m.rank)
            self.request_checkpoint(f"preemption notice from rank {m.rank}")
        elif t == "ready":
            m.last_seen = now
            m.ready_step = int(msg["step"])
        elif t == "ack":
            m.last_seen = now
            m.ack_step = int(msg["step"])
        elif t == "bye":
            m.done_code = str(msg.get("code", "done"))
            m.alive = False
            self._record(event="bye", rank=m.rank, code=m.done_code)
            _GANG_METRICS.get()["members"].set(self.alive_count())

    def _monitor(self) -> None:
        while not self._stop.is_set():
            time.sleep(min(self.poll_s, self.beat_timeout_s / 4))
            now = time.monotonic()
            for m in self.members.values():
                if not m.alive or m.done_code is not None:
                    continue
                if now - m.last_seen > self.beat_timeout_s:
                    _GANG_METRICS.get()["beats_missed"].inc(rank=str(m.rank))
                    resilience_measures("parallel").count("beats_missed")
                    self._mark_dead(
                        m, f"missed beats for {self.beat_timeout_s:.1f}s "
                           f"(last step {m.last_step})")

    def _mark_dead(self, m: _Member, reason: str) -> None:
        first = False
        with self._lock:
            if not m.alive:
                return
            m.alive = False
            if self.failure is None:
                self.failure = (m.rank, reason)
                first = True
            self._events.append({"event": "member_dead", "rank": m.rank,
                                 "reason": reason})
        _GANG_METRICS.get()["members"].set(self.alive_count())
        if first:
            # a dead member cannot contribute a shard — no complete
            # coordinated checkpoint is possible; survivors must exit and
            # resume from the last committed step on the new world
            self._broadcast_verdict("resize", reason=reason)

    def _broadcast_verdict(self, verdict: str, **extra) -> None:
        _GANG_METRICS.get()["verdicts"].inc(verdict=verdict)
        resilience_measures("parallel").count("gang_abort")
        self._record(event="verdict", verdict=verdict, **extra)
        self._broadcast({"t": "verdict", "verdict": verdict, **extra})

    def _broadcast(self, payload: dict) -> None:
        for m in self.members.values():
            if not m.alive:
                continue
            try:
                with m.lock:
                    _send_line(m.conn, payload)
            except OSError:
                pass  # the reader thread will notice the dead conn

    # -- the emergency-checkpoint dance -------------------------------------
    def request_checkpoint(self, reason: str = "driver request") -> None:
        """Kick off the coordinated emergency checkpoint (idempotent; runs
        on its own thread — the caller may be a reader). Outcome lands in
        ``preempt_commit_step`` / the event log."""
        if self._dance.is_set():
            return
        self._dance.set()
        t = threading.Thread(target=self._run_dance, args=(reason,),
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _run_dance(self, reason: str) -> None:
        deadline = Deadline(self.grace_s)
        self._broadcast_verdict("abort_and_checkpoint", reason=reason)
        live = [m for m in self.members.values() if m.alive]
        while not deadline.expired():
            if self.failure is not None:
                return  # a member died mid-dance: resize already sent
            if all(m.ready_step is not None for m in live
                   if m.alive and m.done_code is None):
                break
            time.sleep(self.poll_s)
        readys = [m.ready_step for m in live if m.ready_step is not None]
        if not readys or deadline.expired():
            self._record(event="dance_failed", phase="ready",
                         reason="grace window expired")
            self._broadcast_verdict("resize",
                                    reason="emergency checkpoint "
                                           "could not synchronize")
            return
        sync_step = max(readys)
        self._record(event="sync", step=sync_step)
        self._broadcast({"t": "sync", "step": sync_step})
        while not deadline.expired():
            if self.failure is not None:
                return
            if all(m.ack_step == sync_step for m in live
                   if m.alive and m.done_code is None):
                break
            time.sleep(self.poll_s)
        target = None
        if self.checkpoint_dir is not None and not deadline.expired():
            target = commit_checkpoint(self.checkpoint_dir, sync_step,
                                       self.world, run_id=self.run_id)
        if target is None:
            self._record(event="dance_failed", phase="commit",
                         reason="ACK set incomplete inside grace window")
            self._broadcast_verdict("resize",
                                    reason="emergency checkpoint "
                                           "did not commit")
            return
        with self._lock:
            self.committed_steps.append(sync_step)
        self.preempt_commit_step = sync_step
        _GANG_METRICS.get()["commits"].inc(kind="emergency")
        self._record(event="committed", step=sync_step, kind="emergency")
        self._broadcast({"t": "committed", "step": sync_step})

    def _commit_scan(self) -> None:
        """Periodic-checkpoint committer: a step dir whose full ACK set has
        landed gets its DONE marker (+ retention GC). Workers never commit
        — a lone surviving worker must not be able to publish a world-N
        checkpoint that N-1 ranks never finished."""
        if self.checkpoint_dir is None:
            return
        # dir mtime_ns at the last FAILED commit attempt: any progress
        # (a new ACK or payload landing) bumps the step dir's mtime, so an
        # unchanged dir needs no re-parse — without this, a run whose ACKs
        # never satisfy the fence (or a slow straggler's half-written step)
        # costs a full ACK-set parse per dir every poll tick, forever
        attempted: dict[str, int] = {}
        while not self._stop.is_set():
            time.sleep(self.poll_s)
            try:
                seen = set()
                for d in sorted(os.listdir(self.checkpoint_dir)):
                    if not d.startswith("step_"):
                        continue
                    try:
                        step = int(d.split("_", 1)[1])
                    except ValueError:
                        continue
                    seen.add(d)
                    target = os.path.join(self.checkpoint_dir, d)
                    if os.path.exists(os.path.join(target, "DONE")):
                        attempted.pop(d, None)
                        continue
                    try:
                        mtime = os.stat(target).st_mtime_ns
                    except OSError:
                        continue
                    if attempted.get(d) == mtime:
                        continue  # nothing landed since the last attempt
                    if commit_checkpoint(self.checkpoint_dir, step,
                                         self.world,
                                         run_id=self.run_id) is not None:
                        attempted.pop(d, None)
                        with self._lock:
                            self.committed_steps.append(step)
                        _GANG_METRICS.get()["commits"].inc(kind="periodic")
                        self._record(event="committed", step=step,
                                     kind="periodic")
                        gc_checkpoints(self.checkpoint_dir, self.keep,
                                       verified_cache=self._verified_cache)
                    else:
                        attempted[d] = mtime
                for gone in set(attempted) - seen:  # GC'd / pruned dirs
                    attempted.pop(gone, None)
            except OSError:
                continue


class GangWorker:
    """Worker side of the gang channel (one per training process).

    ``heartbeat(step)`` is wired into the per-step fit loop
    (``Trainer.fit(gang=...)``; the ``supervisor.heartbeat(step)`` seam
    feeds the same call in supervised runs). ``check(step)`` surfaces the
    driver's verdicts; the fit loop turns them into :class:`GangAborted`
    (resize) or the emergency-checkpoint dance + :class:`Preempted`.
    ``install_preemption_hook()`` converts SIGTERM (the cloud preemption
    notice) into the ``preempt`` message at the next step boundary.
    """

    def __init__(self, sock: socket.socket, rank: int, world: int,
                 grace_s: float = 20.0, run_id: str | None = None):
        self.sock = sock
        self.rank = int(rank)
        self.world = int(world)
        self.grace_s = float(grace_s)
        # the rendezvous reply's run_id; fit_gang_source stamps every
        # shard ACK with it so the driver's commit fence recognizes THIS
        # incarnation's writes
        self.run_id = run_id
        self.driver_lost = False
        self._verdict: str | None = None
        self._sync_step: int | None = None
        self._committed_step: int | None = None
        self._preempt_flag = False
        self._preempt_sent = False
        self._ready_sent = False
        self._send_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def start(self) -> "GangWorker":
        self._thread = threading.Thread(target=self._reader, daemon=True)
        self._thread.start()
        return self

    def _reader(self) -> None:
        try:
            for line in self.sock.makefile("r"):
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                t = msg.get("t")
                if t == "verdict":
                    # resize overrides an in-flight dance (a member died)
                    v = msg.get("verdict")
                    if self._verdict != "resize":
                        self._verdict = v
                elif t == "sync":
                    self._sync_step = int(msg["step"])
                elif t == "committed":
                    self._committed_step = int(msg["step"])
        except (OSError, ValueError):
            pass
        finally:
            self.driver_lost = True

    def _send(self, payload: dict) -> None:
        if self.driver_lost:
            return
        try:
            with self._send_lock:
                _send_line(self.sock, payload)
        except OSError:
            self.driver_lost = True  # keep training; the driver is gone

    # -- the per-step seam --------------------------------------------------
    def heartbeat(self, step: int) -> None:
        """One beat per optimizer step. Consults the ``gang`` fault plane
        first: a ``drop`` spec suppresses the send (missed-beat chaos), a
        ``crash`` spec kills this worker at an exact step."""
        plan = active_fault_plan()
        if plan is not None and plan.on_gang(
                f"beat:rank={self.rank}:step={int(step)}"):
            return
        self._send({"t": "beat", "rank": self.rank, "step": int(step)})

    def check(self, step: int):
        """Poll the driver's verdict at a step boundary. Returns None
        (keep training), ``"resize"`` (exit now, resume from the last
        commit), or ``("sync", S)`` — train to step S, checkpoint, then
        :meth:`ack_and_wait_commit`."""
        if self._preempt_flag and not self._preempt_sent:
            self._preempt_sent = True
            self._send({"t": "preempt", "rank": self.rank})
        v = self._verdict
        if v == "resize":
            return "resize"
        if v == "abort_and_checkpoint":
            if not self._ready_sent:
                self._ready_sent = True
                self._send({"t": "ready", "rank": self.rank,
                            "step": int(step)})
            deadline = Deadline(self.grace_s)
            while self._sync_step is None:
                if self._verdict == "resize" or self.driver_lost \
                        or deadline.expired():
                    return "resize"
                time.sleep(0.02)
            return ("sync", self._sync_step)
        return None

    def ack_and_wait_commit(self, step: int,
                            timeout_s: float | None = None) -> bool:
        """Phase-2 handshake after the local shard write: ack, then wait
        for the driver's ``committed`` broadcast. False = the commit never
        landed (treat as resize: the last PERIODIC commit is the resume
        point)."""
        self._send({"t": "ack", "rank": self.rank, "step": int(step)})
        deadline = Deadline(timeout_s if timeout_s is not None
                            else self.grace_s)
        while self._committed_step != int(step):
            if self._verdict == "resize" or self.driver_lost \
                    or deadline.expired():
                return False
            time.sleep(0.02)
        return True

    def preempt(self) -> None:
        """Mark this worker preempted (the SIGTERM hook body): the next
        ``check()`` forwards the notice to the driver."""
        self._preempt_flag = True

    def install_preemption_hook(self, signum: int = signal.SIGTERM) -> None:
        """SIGTERM = the cloud's preemption notice. The handler only sets
        a flag — all real work (socket send, checkpoint) happens at the
        next step boundary, inside the grace window."""
        signal.signal(signum, lambda *_: self.preempt())

    def close(self, code: str = "done") -> None:
        """Orderly exit: tell the driver (so EOF is not read as a death),
        then close."""
        self._send({"t": "bye", "rank": self.rank, "code": code})
        try:
            self.sock.close()
        except OSError:
            pass


class ElasticResume:
    """What :func:`elastic_restore` hands the worker: the reassembled
    global train-state tree, the committed ``step``, the rebuilt
    :class:`~synapseml_tpu.data.state.ElasticPlan` (None for single-host
    checkpoints) and the rank-0 ``meta`` dict."""

    def __init__(self, step: int, tree, plan, meta: dict):
        self.step = int(step)
        self.tree = tree
        self.plan = plan
        self.meta = dict(meta)


def elastic_restore(checkpoint_dir: str) -> ElasticResume | None:
    """Restore the latest VERIFIED committed checkpoint for an elastic
    resume on any surviving world size. Returns None when the directory
    holds no committed checkpoint (fresh start).

    The global tree reassembles from the N per-rank shards host-side
    (``restore_checkpoint``); params and optimizer state then re-derive
    their :class:`PartitionSpec` placement from the trainer's rule table
    exactly as any restore does (``Trainer.resume_state`` →
    ``checkpoint_sharding_fn``) — the M-survivor mesh reshards without a
    host ever holding a device-resident full copy. The per-rank
    ``data_iter`` cursors become the :class:`ElasticPlan` that maps the N
    virtual streams onto the survivors."""
    from ..data.state import ElasticPlan

    step = latest_verified_step(checkpoint_dir)
    if step is None:
        return None
    # the scan above already hashed every payload; committed checkpoints
    # are immutable, so the restore reads skip re-verification — recovery
    # time is on the bench's recovery_s critical path
    tree = restore_checkpoint(checkpoint_dir, step, verify=False)
    world = checkpoint_world(checkpoint_dir, step)
    meta = checkpoint_meta(checkpoint_dir, step)
    plan = None
    if world is not None:
        host_states = restore_host_states(checkpoint_dir, step,
                                          verify=False)
        orig = int(meta.get("orig_world", world))
        plan = ElasticPlan.from_host_states(orig, host_states)
    resilience_measures("parallel").count("gang_resume")
    return ElasticResume(step=step, tree=tree, plan=plan, meta=meta)


def run_gang_member(driver_address: str, partition_id: int, *,
                    trainer_fn, source, checkpoint_dir: str,
                    total_steps: int, batch_size: int, seed: int,
                    checkpoint_every: int = 10, grace_s: float = 60.0,
                    executor_id: str | None = None, on_exit=None,
                    **fit_kwargs) -> int:
    """One process's whole gang-member lifecycle, protocol included:
    rendezvous (keep-alive) → :class:`GangWorker` stamped with the
    rendezvous ``run_id`` → SIGTERM preemption hook →
    :func:`~synapseml_tpu.models.trainer.fit_gang_source` → orderly
    ``bye`` + exit-code mapping. Returns the code a launcher should
    ``sys.exit()`` with: 0 (done), :data:`EXIT_PREEMPTED` (emergency
    checkpoint committed — relaunch to resume) or :data:`EXIT_RESIZE`
    (a member died — relaunch on the survivors).

    ``trainer_fn(info)`` builds this rank's Trainer from the rendezvous
    reply (``info["rank"]``/``info["world"]``) — mesh construction is the
    caller's (each host builds over ITS OWN devices). ``on_exit(kind,
    payload)`` observes the outcome: ``("done", TrainState)``,
    ``("preempted", Preempted)`` or ``("resize", GangAborted)``. Extra
    keyword args pass through to ``fit_gang_source`` (epochs,
    shuffle_rows, callback, ...). This is the ONE copy of the worker
    protocol — the chaos tests and the kill-and-resume bench both launch
    through it."""
    from ..models.trainer import fit_gang_source
    from .backend import worker_rendezvous

    info, sock = worker_rendezvous(
        driver_address, executor_id or f"exec-{partition_id}",
        int(partition_id), keep_alive=True)
    gw = GangWorker(sock, info["rank"], info["world"], grace_s=grace_s,
                    run_id=info.get("run_id")).start()
    gw.install_preemption_hook()
    trainer = trainer_fn(info)
    try:
        state = fit_gang_source(
            trainer, source, batch_size=batch_size,
            total_steps=total_steps, seed=seed, gang=gw,
            checkpoint_dir=checkpoint_dir, rank=info["rank"],
            world=info["world"], checkpoint_every=checkpoint_every,
            **fit_kwargs)
    except Preempted as e:
        if on_exit is not None:
            on_exit("preempted", e)
        gw.close("preempted")
        return EXIT_PREEMPTED
    except GangAborted as e:
        if on_exit is not None:
            on_exit("resize", e)
        gw.close("resize")
        return EXIT_RESIZE
    if on_exit is not None:
        on_exit("done", state)
    gw.close("done")
    return 0


def launch_gang_processes(script_path: str, world: int, *,
                          checkpoint_dir: str, worker_args_fn,
                          env: dict | None = None,
                          coordinator_kw: dict | None = None,
                          rendezvous_timeout_s: float = 120.0):
    """Launcher side of :func:`run_gang_member`: spawn one OS process per
    rank running ``script_path`` (a worker script built on
    ``run_gang_member``), bootstrap the keep-alive rendezvous, and start
    the :class:`GangCoordinator` over the live sockets. A failed launch
    (worker import error, rendezvous timeout) kills every spawned process
    before re-raising — it must never orphan live training subprocesses.

    ``worker_args_fn(rank, addr)`` returns the argv AFTER the interpreter
    and script (the worker's own parameters). Returns ``(procs, coord,
    driver)``; pair with :func:`finish_gang_processes`. The chaos tests
    and the kill-and-resume bench both launch through here — this is the
    ONE copy of the bootstrap/teardown ordering."""
    import subprocess
    import sys

    from .backend import DriverRendezvous

    driver = DriverRendezvous(world_size=int(world), keep_alive=True)
    driver.start()
    addr = f"127.0.0.1:{driver.port}"
    procs = [subprocess.Popen(
        [sys.executable, script_path, *worker_args_fn(p, addr)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
        for p in range(int(world))]
    # drain each worker's pipe from launch: a worker writing more than the
    # OS pipe buffer (XLA warnings, a traceback) would otherwise block in
    # write() mid-step, stop heartbeating, and get a healthy gang resized
    for p in procs:
        buf: list[str] = []
        t = threading.Thread(target=lambda f=p.stdout, b=buf:
                             b.extend(iter(f.readline, "")), daemon=True)
        t.start()
        p._gang_drain = (t, buf)
    try:
        driver.join(timeout_s=rendezvous_timeout_s)
        coord = driver.gang(checkpoint_dir=checkpoint_dir,
                            **(coordinator_kw or {}))
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        raise
    return procs, coord, driver


def finish_gang_processes(procs, coord, *, timeout_s: float = 120.0,
                          wait_commit_step: int | None = None):
    """Teardown side of :func:`launch_gang_processes`: drain every
    worker's output, optionally wait for the commit scanner's poll tick
    on ``wait_commit_step`` (the last ACKs land right before the workers
    exit), then unconditionally kill stragglers and close the
    coordinator. Returns ``(outputs, exit_codes)``."""
    outs, codes = [], []
    try:
        for p in procs:
            p.wait(timeout=timeout_s)
            drain, buf = getattr(p, "_gang_drain", (None, None))
            if drain is not None:
                drain.join(timeout=10.0)
                outs.append("".join(buf))
            else:  # launched outside launch_gang_processes
                out, _ = p.communicate(timeout=timeout_s)
                outs.append(out)
            codes.append(p.returncode)
        if wait_commit_step is not None:
            coord.wait_commit(step=wait_commit_step, timeout_s=15)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        coord.close()
    return outs, codes
