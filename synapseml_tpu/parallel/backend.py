"""Distributed bootstrap: driver-rendezvous -> jax.distributed.

The reference bootstraps topology three different ways (SURVEY.md §2.7 item 7):
LightGBM's driver ServerSocket rendezvous (``NetworkManager.scala:59-125``), VW's
spanning-tree coordinator (``VowpalWabbitClusterUtil.scala:15-42``) and horovod's
SparkBackend (``dl/utils.py:31-46``). All reduce to the same shape: a driver
collects worker endpoints, computes a deterministic ordering, broadcasts the
peer list, then a native collective ring forms.

TPU-native: the only thing workers need is the coordinator address + their
process index; `jax.distributed.initialize` then wires ICI/DCN. This module
implements that rendezvous over a plain TCP socket so a Spark-like driver (or
any launcher) can hand each executor its (coordinator, rank, world) triple —
and a single-process fallback that skips rendezvous entirely.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from dataclasses import dataclass

__all__ = ["DriverRendezvous", "worker_rendezvous", "DistributedBackend", "initialize_backend"]


@dataclass
class WorkerInfo:
    host: str
    executor_id: str
    partition_id: int


class DriverRendezvous:
    """Driver side: collect `world_size` worker registrations, assign ranks by
    (min partition id, executor id) — the reference's deterministic ordering
    (``NetworkManager.waitForAllTasksToReport:354-425``) — and reply with
    {coordinator, rank, world}.

    ``keep_alive=True`` keeps every worker connection OPEN after the rank
    reply: the same TCP channel then serves as the gang-membership /
    failure-detector plane — hand the sockets to
    :meth:`gang` (a :class:`~synapseml_tpu.parallel.gang.GangCoordinator`)
    and pair it with ``worker_rendezvous(..., keep_alive=True)`` on the
    worker side."""

    def __init__(self, world_size: int, coordinator_port: int = 9377,
                 bind: str = "0.0.0.0", keep_alive: bool = False):
        self.world_size = world_size
        self.coordinator_port = coordinator_port
        self.keep_alive = bool(keep_alive)
        # one id per LAUNCH incarnation, handed to every worker in the
        # rank reply: coordinated-checkpoint ACKs carry it, and the gang
        # driver's commit fences on it so a relaunch over a torn step dir
        # can never combine stale acks with fresh ones
        self.run_id = uuid.uuid4().hex[:12]
        self.conns: dict[int, socket.socket] = {}  # rank -> live socket
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind, 0))
        self._srv.listen(world_size * 2)
        self.port = self._srv.getsockname()[1]
        self._thread: threading.Thread | None = None
        self.error: BaseException | None = None

    @property
    def address(self) -> str:
        return f"{socket.gethostname()}:{self.port}"

    def start(self) -> "DriverRendezvous":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        try:
            conns, infos = [], []
            while len(conns) < self.world_size:
                conn, _ = self._srv.accept()
                data = json.loads(conn.makefile("r").readline())
                infos.append(WorkerInfo(**data))
                conns.append(conn)
            order = sorted(range(len(infos)),
                           key=lambda i: (infos[i].partition_id, infos[i].executor_id))
            coord_host = infos[order[0]].host
            coordinator = f"{coord_host}:{self.coordinator_port}"
            for rank, i in enumerate(order):
                reply = {"coordinator": coordinator, "rank": rank,
                         "world": self.world_size, "run_id": self.run_id}
                conns[i].sendall((json.dumps(reply) + "\n").encode())
                if self.keep_alive:
                    self.conns[rank] = conns[i]
            if not self.keep_alive:
                for c in conns:
                    c.close()
        except BaseException as e:  # surfaced via .error for the driver loop
            self.error = e
        finally:
            self._srv.close()

    def join(self, timeout_s: float = 120.0) -> None:
        assert self._thread is not None
        self._thread.join(timeout_s)
        if self.error:
            raise self.error

    def gang(self, **kwargs):
        """The bootstrap channel, promoted to the gang plane: a started
        :class:`~synapseml_tpu.parallel.gang.GangCoordinator` over the
        kept-alive worker sockets. Call after :meth:`join`; requires
        ``keep_alive=True``."""
        if not self.keep_alive:
            raise RuntimeError("DriverRendezvous(keep_alive=True) is "
                               "required for a gang channel")
        if len(self.conns) != self.world_size:
            raise RuntimeError("rendezvous incomplete: "
                               f"{len(self.conns)}/{self.world_size} "
                               "workers connected")
        from .gang import GangCoordinator

        kwargs.setdefault("run_id", self.run_id)
        return GangCoordinator(self.conns, **kwargs).start()


def worker_rendezvous(driver_address: str, executor_id: str, partition_id: int,
                      timeout_s: float = 120.0, retry_interval_s: float = 0.25,
                      policy=None, deadline=None, keep_alive: bool = False):
    """Worker side: register with the driver, receive (coordinator, rank, world).
    Retries with jittered backoff like ``NetworkManager.initLightGBMNetwork:195-218``,
    bounded by a ``core.resilience.Deadline`` — every connect attempt's
    timeout is capped by the remaining budget, so a hung coordinator can
    never stall a worker past ``timeout_s`` total. Retries and expiries are
    counted on ``resilience_measures("parallel")``.

    ``keep_alive=True`` returns ``(info, socket)`` with the rendezvous
    connection still open — the gang-membership channel a
    :class:`~synapseml_tpu.parallel.gang.GangWorker` runs heartbeats and
    verdicts over for the rest of the run."""
    from ..core import observability as obs

    with obs.get_tracer().span("parallel.rendezvous",
                               {"driver": driver_address,
                                "partition_id": partition_id}):
        t0 = time.perf_counter()
        try:
            info = _worker_rendezvous(driver_address, executor_id,
                                      partition_id, timeout_s,
                                      retry_interval_s, policy, deadline,
                                      keep_alive)
        finally:
            # rendezvous wall time — connect retries included — is the
            # startup tax every MPMD/DP launch pays before step 0
            obs.get_registry().histogram(
                "synapseml_rendezvous_duration_ms",
                "worker rendezvous wall time (connect retries included)",
            ).observe((time.perf_counter() - t0) * 1e3)
        return info


def _worker_rendezvous(driver_address: str, executor_id: str,
                       partition_id: int, timeout_s: float,
                       retry_interval_s: float, policy, deadline,
                       keep_alive: bool = False):
    from ..core.resilience import Deadline, DeadlineExpired, RetryPolicy, \
        resilience_measures

    host, port = driver_address.rsplit(":", 1)
    measures = resilience_measures("parallel")
    deadline = deadline if deadline is not None else Deadline(timeout_s)
    if policy is None:
        # geometric schedule seeded from retry_interval_s, capped at 5s —
        # the old doubling loop, now with full jitter so a fleet of workers
        # restarting together doesn't hammer the driver in lockstep
        backoffs, b = [], retry_interval_s * 1000.0
        while len(backoffs) < 64:
            backoffs.append(min(b, 5000.0))
            b *= 2
        policy = RetryPolicy(backoffs_ms=tuple(backoffs))
    last: BaseException | None = None
    attempt = 0
    while True:
        try:
            connect_timeout = deadline.cap(timeout_s)
        except DeadlineExpired:
            measures.count("deadline_expired")
            raise TimeoutError(
                f"rendezvous with {driver_address} failed: {last}") from last
        try:
            s = socket.create_connection((host, int(port)),
                                         timeout=connect_timeout)
            try:
                payload = {"host": socket.gethostname(), "executor_id": executor_id,
                           "partition_id": partition_id}
                s.sendall((json.dumps(payload) + "\n").encode())
                if keep_alive:
                    # read the reply UNBUFFERED (byte-at-a-time up to the
                    # newline): a buffered makefile could pull gang bytes
                    # already behind the reply (e.g. an instant verdict)
                    # into a reader this function then discards
                    line = b""
                    while not line.endswith(b"\n"):
                        ch = s.recv(1)
                        if not ch:
                            raise OSError("rendezvous connection closed "
                                          "before the rank reply")
                        line += ch
                    info = json.loads(line)
                    s.settimeout(None)  # the gang channel blocks on reads
                    return info, s
                info = json.loads(s.makefile("r").readline())
                s.close()
                return info
            except BaseException:
                s.close()
                raise
        except OSError as e:
            last = e
            wait_s = policy.backoff_ms(attempt) / 1000.0
            attempt += 1
            if not deadline.sleep_allowed(wait_s):
                measures.count("deadline_expired")
                raise TimeoutError(
                    f"rendezvous with {driver_address} failed: {last}") from last
            if not policy.acquire_retry():
                measures.count("retry_budget_exhausted")
                raise TimeoutError(
                    f"rendezvous with {driver_address} failed "
                    f"(retry budget exhausted): {last}") from last
            measures.count("retry")
            time.sleep(wait_s)


@dataclass
class DistributedBackend:
    """The one comm backend handle estimators receive."""

    rank: int
    world: int
    coordinator: str | None
    initialized: bool

    @property
    def is_distributed(self) -> bool:
        return self.world > 1


_BACKEND: DistributedBackend | None = None


def initialize_backend(driver_address: str | None = None, executor_id: str | None = None,
                       partition_id: int = 0,
                       rendezvous_timeout_s: float = 120.0) -> DistributedBackend:
    """Initialize jax.distributed from rendezvous (multi-host) or env/defaults.

    Single-process (tests, 1 TPU VM, CPU mesh): no-op beyond recording a
    world-of-1 backend. Multi-host: deadline-bounded rendezvous (at most
    ``rendezvous_timeout_s`` total across all connect retries) ->
    jax.distributed.initialize.
    """
    global _BACKEND
    if _BACKEND is not None:
        return _BACKEND
    import jax

    if driver_address is None:
        _BACKEND = DistributedBackend(rank=jax.process_index(), world=jax.process_count(),
                                      coordinator=os.environ.get("JAX_COORDINATOR_ADDRESS"),
                                      initialized=False)
        return _BACKEND
    info = worker_rendezvous(driver_address, executor_id or socket.gethostname(),
                             partition_id, timeout_s=rendezvous_timeout_s)
    jax.distributed.initialize(coordinator_address=info["coordinator"],
                               num_processes=info["world"], process_id=info["rank"])
    _BACKEND = DistributedBackend(rank=info["rank"], world=info["world"],
                                  coordinator=info["coordinator"], initialized=True)
    return _BACKEND


def reset_backend() -> None:
    global _BACKEND
    _BACKEND = None
