"""Collective helpers over the named mesh (shard_map wrappers).

The reference's collectives are native TCP rings (LGBM_NetworkInit allreduce,
VW spanning-tree, horovod ring — SURVEY.md §2.7 items 2-4). Here every
collective is an XLA op over mesh axes; these helpers wrap the common shapes
so estimator code never touches lax primitives directly.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import MeshContext

__all__ = ["compat_shard_map", "psum_over", "pmean_over", "all_gather_over",
           "data_parallel_map", "ring_permute"]


def compat_shard_map(fn, mesh, in_specs, out_specs,
                     check_vma: bool | None = None):
    """``shard_map`` across the jax range the framework supports: the
    top-level ``jax.shard_map`` (with its ``check_vma`` kwarg when it
    exists) on new versions, ``jax.experimental.shard_map`` (whose
    equivalent knob is ``check_rep``) on older ones."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
        except TypeError:
            # a jax.shard_map generation without the check_vma kwarg
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return legacy_shard_map(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, **kw)


def shard_map(fn, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """Backwards-compatible alias of :func:`compat_shard_map` (this module
    historically re-exported the jax symbol)."""
    return compat_shard_map(fn, mesh, in_specs, out_specs,
                            check_vma=check_vma)


def psum_over(mesh_ctx: MeshContext, axis: str | Sequence[str] = "data"):
    """Return fn(x)->x summed over `axis`, runnable under jit on the mesh."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)

    def inner(x):
        return jax.lax.psum(x, axes)

    return functools.partial(_run_collective, mesh_ctx, inner)


def _run_collective(mesh_ctx: MeshContext, fn, x):
    sharded = shard_map(fn, mesh=mesh_ctx.mesh, in_specs=P(), out_specs=P(), check_vma=False)
    return sharded(x)


def pmean_over(mesh_ctx: MeshContext, axis: str = "data"):
    def inner(x):
        return jax.lax.pmean(x, axis)

    return functools.partial(_run_collective, mesh_ctx, inner)


def all_gather_over(mesh_ctx: MeshContext, axis: str = "data", tiled: bool = True):
    def inner(x):
        return jax.lax.all_gather(x, axis, tiled=tiled)

    def run(x):
        sharded = shard_map(inner, mesh=mesh_ctx.mesh,
                            in_specs=P(axis), out_specs=P(), check_vma=False)
        return sharded(x)

    return run


def ring_permute(mesh_ctx: MeshContext, axis: str = "seq", shift: int = 1):
    """Neighbor exchange along a mesh axis ring — building block for ring
    attention / pipeline microbatch handoff."""
    n = mesh_ctx.axis_sizes[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    def inner(x):
        return jax.lax.ppermute(x, axis, perm)

    def run(x):
        sharded = shard_map(inner, mesh=mesh_ctx.mesh,
                            in_specs=P(axis), out_specs=P(axis), check_vma=False)
        return sharded(x)

    return run


def data_parallel_map(mesh_ctx: MeshContext, fn: Callable, reduce: str | None = "mean"):
    """jit `fn(batch)->val` with batch sharded over data axes; optionally psum/
    pmean the result — the one-liner DP pattern replacing horovod DP."""

    @functools.partial(jax.jit)
    def wrapped(batch):
        out = fn(batch)
        return out

    def run(batch: Any):
        placed = mesh_ctx.shard_batch(batch)
        out = wrapped(placed)
        if reduce == "mean":
            return jax.tree.map(lambda x: jnp.mean(x, axis=0) if jnp.ndim(x) > 0 else x, out)
        return out

    return run
