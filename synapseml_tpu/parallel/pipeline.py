"""Pipeline (stage) parallelism over the ``pipe`` mesh axis — GPipe schedule.

Net-new capability (the classical-Spark reference has no model parallelism at
all — SURVEY §2.7); completes the mesh-axis family so every parallelism
(dp/fsdp/tp/sp/ep/pp) is an axis of ONE ``jax.sharding.Mesh``.

Design (the standard SPMD pipelining recipe on TPU):

* Stage s's parameters live only on pipe-coordinate s: the stacked param
  pytree has a leading ``[n_stages, ...]`` axis sharded over ``pipe``, so
  per-device memory is one stage's weights.
* The microbatch stream flows through a rotating buffer: at schedule tick t,
  stage 0 ingests microbatch t (while t < n_micro), every stage applies its
  layer to whatever it holds, and activations ``ppermute`` one hop down the
  ring (ICI neighbor exchange — the same collective ring attention uses).
* After ``n_stages - 1 + n_micro`` ticks every microbatch has crossed all
  stages; outputs are collected on the LAST stage and psum-broadcast back
  (tiny tensors in the estimator use cases; callers that want them sharded
  can keep the last-stage copy).
* The whole schedule is a ``lax.scan`` over ticks — compile size independent
  of both ring length and microbatch count, and differentiable by autodiff
  (ppermute's transpose is the reverse permute; the scan transposes to the
  reverse-time scan — 1F1B-style memory comes from ``jax.checkpoint`` on the
  stage fn if needed).

The bubble fraction is the textbook (S-1)/(S-1+M): callers pick
``n_micro >> n_stages`` to amortize.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _shard_map(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` moved out of ``jax.experimental`` in newer
    versions; the version-spanning shim lives in ``.collectives``."""
    from .collectives import compat_shard_map

    return compat_shard_map(fn, mesh, in_specs, out_specs)

__all__ = ["pipeline_apply", "pipeline_apply_interleaved",
           "pipeline_apply_scattered", "pipeline_sharded",
           "stack_stage_params"]


def _axis_size(axis_name):
    """``jax.lax.axis_size`` is missing on older jax; ``psum(1, axis)``
    constant-folds to the same static int inside shard_map."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _pvary(x, axis_name):
    """Mark x as varying over axis_name (vma typing); tolerate jax versions
    where the API is pcast / pvary / absent, and values already varying
    over the axis (pcast rejects varying->varying)."""
    try:
        if hasattr(jax.lax, "pcast"):
            return jax.lax.pcast(x, axis_name, to="varying")
        if hasattr(jax.lax, "pvary"):
            return jax.lax.pvary(x, axis_name)
    except ValueError as e:
        # swallow only the already-varying case. pcast says "Unsupported
        # pcast from=varying"; pvary phrases it "invariant->variant
        # collective ... must not be present in jax.typeof(inp).vma"
        msg = str(e)
        if "varying" not in msg and "vma" not in msg:
            raise
    return x


def stack_stage_params(stage_params_list):
    """[params_stage0, ...] -> one pytree with a leading stage axis (shard it
    over ``pipe``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params_list)


def _stage_preamble(stage_fn, stacked_params, axis_name, remat):
    """Shared per-device setup for both schedules: optional remat wrap, axis
    geometry, and the one-stage-per-device check. Returns
    ``(stage_fn, n_stages, idx, my_params)``."""
    if remat:
        # recompute stage activations in the backward scan instead of saving
        # every tick's outputs — the GPipe memory trade
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    shard = jax.tree.leaves(stacked_params)[0].shape[0]
    if shard != 1:
        raise ValueError(
            f"pipeline: stage count must equal the {axis_name!r} axis size "
            f"({n_stages}); this device holds {shard} stages — only the "
            f"first would run (wrong results, not an error, if allowed)")
    my_params = jax.tree.map(lambda p: p[0], stacked_params)
    return stage_fn, n_stages, idx, my_params


def pipeline_apply(stage_fn, stacked_params, x_micro, axis_name: str = "pipe",
                   remat: bool = False):
    """Run ``n_micro`` microbatches through ``n_stages`` chained stages.

    Call INSIDE ``shard_map`` (or via :func:`pipeline_sharded`). Per-device
    arguments:

      stage_fn:       ``(params, x) -> y`` — one stage's computation; y must
                      have x's pytree structure/shapes/dtypes (chainable
                      stages). ``x`` may be ANY pytree — e.g. ``(h, mask)``
                      so attention masks travel with their microbatch (a
                      stage returns the mask unchanged).
      stacked_params: THIS device's stage params (leading stage axis already
                      consumed by sharding: ``[1, ...]`` per leaf).
      x_micro:        pytree of ``[n_micro, mb, ...]`` microbatches (stage 0
                      reads them; other devices pass zeros of the same
                      shapes).

    Returns the same pytree of ``[n_micro, mb, ...]`` outputs, valid on
    every device (psum off the last stage).
    """
    stage_fn, n_stages, idx, my_params = _stage_preamble(
        stage_fn, stacked_params, axis_name, remat)
    n_micro = jax.tree.leaves(x_micro)[0].shape[0]
    n_ticks = n_stages - 1 + n_micro
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    tmap = jax.tree.map

    def tick(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t; everyone else keeps the rotated state
        feed = tmap(lambda xm, st: jnp.where(
            t < n_micro, xm[jnp.minimum(t, n_micro - 1)], jnp.zeros_like(st)),
            x_micro, state)
        inp = tmap(lambda fd, st: jnp.where(idx == 0, fd, st), feed, state)
        y = stage_fn(my_params, inp)
        # the LAST stage finished microbatch t - (n_stages - 1) at this tick
        m = t - (n_stages - 1)
        take = (idx == n_stages - 1) & (m >= 0)
        outs = tmap(lambda os, yy: jax.lax.dynamic_update_index_in_dim(
            os, jnp.where(take, yy, os[jnp.maximum(m, 0)]),
            jnp.maximum(m, 0), axis=0), outs, y)
        state = tmap(lambda yy: jax.lax.ppermute(yy, axis_name, perm), y)
        return (state, outs), None

    # the carry becomes pipe-VARYING inside the loop (ppermute/idx-dependent
    # writes); the init must carry the same varying-axes type or scan rejects
    # the carry under shard_map's vma checking
    state0 = tmap(lambda xm: _pvary(jnp.zeros_like(xm[0]), axis_name), x_micro)
    outs0 = tmap(lambda xm: _pvary(jnp.zeros_like(xm), axis_name), x_micro)
    (_, outs), _ = jax.lax.scan(tick, (state0, outs0),
                                jnp.arange(n_ticks, dtype=jnp.int32))
    # only the last stage holds real outputs; zero elsewhere -> psum = bcast
    outs = tmap(lambda os: jnp.where(idx == n_stages - 1, os,
                                     jnp.zeros_like(os)), outs)
    return tmap(lambda os: jax.lax.psum(os, axis_name), outs)


def pipeline_apply_scattered(stage_fn, stacked_params, x_local,
                             axis_name: str = "pipe", remat: bool = False):
    """Memory-scaled variant of :func:`pipeline_apply`: microbatch inputs AND
    outputs are sharded over the pipe axis (device d owns microbatches
    ``[d*chunk, (d+1)*chunk)``), so per-device live memory is
    ``O(n_micro / n_stages)`` owned microbatches plus three in-flight slots —
    never the replicated ``O(n_micro)`` buffers of the GPipe entry point.

    Mechanics (all static-shape, all ICI neighbor traffic):

    * FEED ring (reverse rotation): slot d holds microbatch ``t + d`` at tick
      t; a device swaps in its own copy whenever that index falls in its
      chunk, and stage 0 consumes slot 0 — microbatch t arrives exactly on
      schedule without ever being replicated.
    * compute + forward rotation: identical to :func:`pipeline_apply`.
    * DRAIN ring (forward rotation): a finished microbatch enters at the last
      stage and rides the ring; every device sees it within S-1 hops and its
      owner copies it into the local output chunk (idempotent on later
      passes, so stale entries are harmless).

    Tick count grows from ``S-1+M`` to ``M + 2S - 2`` (the drain tail).
    """
    stage_fn, n_stages, idx, my_params = _stage_preamble(
        stage_fn, stacked_params, axis_name, remat)
    chunk = jax.tree.leaves(x_local)[0].shape[0]
    n_micro = chunk * n_stages
    n_ticks = n_micro + 2 * n_stages - 2
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    rev = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    tmap = jax.tree.map

    def tick(carry, t):
        state, feed, drain, drain_m, outs = carry
        # feed ring: this device's slot carries microbatch t + idx
        m_here = t + idx
        local_i = jnp.clip(m_here - idx * chunk, 0, chunk - 1)
        mine = (m_here >= idx * chunk) & (m_here < (idx + 1) * chunk)
        feed = tmap(lambda xl, f: jnp.where(mine, xl[local_i], f),
                    x_local, feed)
        inp = tmap(lambda f, st: jnp.where(idx == 0, f, st), feed, state)
        y = stage_fn(my_params, inp)
        # drain ring: the last stage finished microbatch t - (S-1) this tick
        m_done = t - (n_stages - 1)
        fresh = (idx == n_stages - 1) & (m_done >= 0) & (m_done < n_micro)
        drain = tmap(lambda yy, dr: jnp.where(fresh, yy, dr), y, drain)
        drain_m = jnp.where(fresh, m_done, drain_m)
        # owners copy passing microbatches into their local output chunk
        own = (drain_m >= idx * chunk) & (drain_m < (idx + 1) * chunk)
        slot = jnp.clip(drain_m - idx * chunk, 0, chunk - 1)
        outs = tmap(lambda os, dr: jax.lax.dynamic_update_index_in_dim(
            os, jnp.where(own, dr, os[slot]), slot, axis=0), outs, drain)
        state = tmap(lambda yy: jax.lax.ppermute(yy, axis_name, fwd), y)
        feed = tmap(lambda f: jax.lax.ppermute(f, axis_name, rev), feed)
        drain = tmap(lambda d: jax.lax.ppermute(d, axis_name, fwd), drain)
        drain_m = jax.lax.ppermute(drain_m, axis_name, fwd)
        return (state, feed, drain, drain_m, outs), None

    one = tmap(lambda xl: _pvary(jnp.zeros_like(xl[0]), axis_name), x_local)
    outs0 = tmap(lambda xl: _pvary(jnp.zeros_like(xl), axis_name), x_local)
    m0 = _pvary(jnp.int32(-1), axis_name)
    (_, _, _, _, outs), _ = jax.lax.scan(
        tick, (one, tmap(jnp.copy, one), tmap(jnp.copy, one), m0, outs0),
        jnp.arange(n_ticks, dtype=jnp.int32))
    return outs


def pipeline_apply_interleaved(stage_fn, stacked_params, x_micro,
                               axis_name: str = "pipe", remat: bool = False):
    """Interleaved (circular) schedule: device d holds ``v`` ROUND-ROBIN
    stage chunks (global stage ``d + c*S`` at local chunk c), so a payload
    hops to the next device every tick and wraps from the last device back
    to device 0 into its next chunk. With L = S*v total stages the bubble
    shrinks from GPipe's ``(S-1)/(S-1+M)`` (stages fused v-per-device) to
    ``~S/(M*v + S)`` — the Megatron interleaved-schedule effect, here as
    one ``lax.scan`` over a single rotating slot per device.

    Per-device arguments: ``stacked_params`` leading axis = v chunks in
    round-robin order (``pipeline_sharded`` does the permutation);
    ``x_micro`` replicated ``[M, mb, ...]`` with M divisible by S. Outputs
    are captured on device 0 (where completed payloads wrap to) and
    psum-broadcast, like :func:`pipeline_apply`.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    v = jax.tree.leaves(stacked_params)[0].shape[0]
    n_micro = jax.tree.leaves(x_micro)[0].shape[0]
    if n_micro % n_stages:
        raise ValueError(
            f"interleaved schedule needs n_micro ({n_micro}) divisible by "
            f"the {axis_name!r} axis size ({n_stages})")
    S, round_len = n_stages, n_stages * v
    n_ticks = n_micro * v + S
    fwd = [(i, (i + 1) % S) for i in range(S)]
    tmap = jax.tree.map

    def tick(carry, t):
        state, outs = carry
        in_round = (t % round_len) < S  # injection/arrival window
        # a payload arriving at device 0 in the window is COMPLETE: it was
        # chunk v-1 on device S-1 last tick. Its identity follows from the
        # deterministic schedule alone.
        m_done = (t // round_len) * S + t % round_len - S
        take = (idx == 0) & in_round & (m_done >= 0) & (m_done < n_micro)
        slot = jnp.clip(m_done, 0, n_micro - 1)
        outs = tmap(lambda os, st: jax.lax.dynamic_update_index_in_dim(
            os, jnp.where(take, st, os[slot]), slot, axis=0), outs, state)
        # device 0 injects a fresh microbatch in the same window
        m_in = (t // round_len) * S + t % round_len
        inject = (idx == 0) & in_round & (m_in < n_micro)
        inp = tmap(lambda xm, st: jnp.where(
            inject, xm[jnp.clip(m_in, 0, n_micro - 1)], st), x_micro, state)
        # local chunk this tick: ((t - d) // S) mod v
        c = jnp.mod(jnp.floor_divide(t - idx, S), v)
        params_c = tmap(lambda p: jax.lax.dynamic_index_in_dim(
            p, c, axis=0, keepdims=False), stacked_params)
        y = stage_fn(params_c, inp)
        state = tmap(lambda yy: jax.lax.ppermute(yy, axis_name, fwd), y)
        return (state, outs), None

    state0 = tmap(lambda xm: _pvary(jnp.zeros_like(xm[0]), axis_name), x_micro)
    outs0 = tmap(lambda xm: _pvary(jnp.zeros_like(xm), axis_name), x_micro)
    (_, outs), _ = jax.lax.scan(tick, (state0, outs0),
                                jnp.arange(n_ticks, dtype=jnp.int32))
    outs = tmap(lambda os: jnp.where(idx == 0, os, jnp.zeros_like(os)), outs)
    return tmap(lambda os: jax.lax.psum(os, axis_name), outs)


def pipeline_sharded(mesh_ctx, stage_fn, stacked_params, x_micro,
                     axis_name: str = "pipe", remat: bool = False,
                     io: str = "replicated", interleave: int = 1):
    """Full-array entry point: shard_map the pipeline schedule over the
    mesh's ``pipe`` axis (params stage-sharded). Falls back to a sequential
    stage chain when the axis is absent/size-1.

    ``io`` picks the microbatch layout:

    * ``"replicated"`` (GPipe default): microbatches replicated in, outputs
      psum-broadcast to every device — right for the estimator-sized
      tensors this library pipelines by default.
    * ``"sharded"``: microbatches and outputs sharded over the pipe axis
      (``n_micro`` must divide by it) via :func:`pipeline_apply_scattered` —
      per-device activation memory scales as 1/n_stages, the production
      layout for real model sizes.

    ``interleave=v`` (with ``n_stages == pipe_size * v``) runs the circular
    schedule instead: stages assigned round-robin (device d gets stages
    ``d, d+S, ...``), cutting the pipeline bubble by ~v at the cost of a
    param-chunk select per tick. Requires ``io='replicated'`` and
    ``n_micro`` divisible by the axis size.
    """
    from jax.sharding import PartitionSpec as P

    if io not in ("replicated", "sharded"):
        raise ValueError(f"io must be 'replicated' or 'sharded', got {io!r}")
    if interleave < 1:
        raise ValueError(f"interleave must be >= 1, got {interleave}")
    if interleave > 1 and io != "replicated":
        raise ValueError("interleave > 1 requires io='replicated'")
    mesh = getattr(mesh_ctx, "mesh", mesh_ctx)
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    pipe_size = sizes.get(axis_name, 1)
    if pipe_size > 1 and n_stages != pipe_size * interleave:
        raise ValueError(
            f"pipeline_sharded: {n_stages} stages cannot shard over a "
            f"{axis_name!r} axis of size {pipe_size}"
            + (f" with interleave={interleave} (need pipe*interleave "
               "stages)" if interleave > 1 else " (one stage per device)"))
    # validated BEFORE the size-1 fallback so misuse surfaces in
    # single-device dev runs, not first on the deployment mesh
    if interleave > 1:
        if n_stages % interleave:
            raise ValueError(
                f"pipeline_sharded: {n_stages} stages cannot interleave by "
                f"{interleave} (need pipe*interleave stages)")
        n_micro = jax.tree.leaves(x_micro)[0].shape[0]
        ring = pipe_size if pipe_size > 1 else n_stages // interleave
        if n_micro % ring:
            raise ValueError(
                f"interleaved schedule needs n_micro ({n_micro}) divisible "
                f"by the {axis_name!r} axis size ({ring})")
    if io == "sharded":
        n_micro = jax.tree.leaves(x_micro)[0].shape[0]
        if n_micro % max(pipe_size, n_stages):
            raise ValueError(
                f"io='sharded' needs n_micro ({n_micro}) divisible by the "
                f"{axis_name!r} axis size ({max(pipe_size, n_stages)})")
    if pipe_size <= 1:
        def seq_apply(params_all, xs):
            n_st = jax.tree.leaves(params_all)[0].shape[0]
            y = xs
            for s in range(n_st):
                y = jax.vmap(lambda x: stage_fn(
                    jax.tree.map(lambda p: p[s], params_all), x))(y)
            return y
        return seq_apply(stacked_params, x_micro)

    if interleave > 1:
        # shard_map splits the leading axis contiguously, so permute the
        # stack: position d*v + c must hold global stage d + c*S
        S, vv = pipe_size, interleave
        perm = [(i // vv) + (i % vv) * S for i in range(n_stages)]
        stacked_params = jax.tree.map(
            lambda p: jnp.take(p, jnp.asarray(perm), axis=0), stacked_params)
        fn = functools.partial(pipeline_apply_interleaved, stage_fn,
                               axis_name=axis_name, remat=remat)
        micro_spec = jax.tree.map(lambda _: P(), x_micro)
    elif io == "sharded":
        fn = functools.partial(pipeline_apply_scattered, stage_fn,
                               axis_name=axis_name, remat=remat)
        micro_spec = jax.tree.map(lambda _: P(axis_name), x_micro)
    else:
        fn = functools.partial(pipeline_apply, stage_fn, axis_name=axis_name,
                               remat=remat)
        micro_spec = jax.tree.map(lambda _: P(), x_micro)
    mapped = _shard_map(
        fn, mesh,
        (jax.tree.map(lambda _: P(axis_name), stacked_params), micro_spec),
        micro_spec,
    )
    return mapped(stacked_params, x_micro)
