"""Sharded checkpoint/resume keyed by mesh (orbax-backed, npz fallback).

Reference checkpointing is model-level: LightGBM ``modelString`` carry-over
(``LightGBMBase.scala:48-60``), VW ``initialModel`` bytes, pytorch-lightning
ModelCheckpoint (SURVEY.md §5). TPU equivalent: orbax sharded checkpoints that
restore onto a different mesh topology (host-side numpy round-trip when orbax
is unavailable or the target is single-process).
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import json
import logging
import os
import shutil
from typing import Any

import jax
import numpy as np

from ..core import serialization

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_step",
           "latest_verified_step", "verify_checkpoint",
           "CheckpointCorrupt", "checkpoint_sharding", "AsyncCheckpointer"]

_logger = logging.getLogger("synapseml_tpu.parallel.checkpoint")


class CheckpointCorrupt(RuntimeError):
    """A checkpoint payload failed its sha256 sidecar verification — the
    file is torn or bit-rotted, not merely incomplete."""


def _step_dir(path: str, step: int) -> str:
    return os.path.join(path, f"step_{step:010d}")


def _to_host(keypath, x):
    """Host-side numpy for one leaf. A leaf spanning other processes
    cannot be fetched by this npz checkpointer (no host holds the full
    value) — raise an actionable error naming the leaf instead of
    surfacing jax's generic non-addressable fetch failure mid-write."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from .partition import tree_path_name

        raise ValueError(
            f"checkpoint leaf {tree_path_name(keypath)!r} is sharded "
            "across processes — the npz checkpointer writes one "
            "host-side artifact and cannot gather it. Gather the state "
            "explicitly (or checkpoint with use_orbax=True on a backend "
            "with cross-process collectives); the RESTORE side of a "
            "sharded mesh works from any replicated artifact via "
            "restore_checkpoint(sharding_fn=...)")
    return np.asarray(x)


def save_checkpoint(path: str, tree: Any, step: int = 0, use_orbax: bool | None = None,
                    sharding: dict | None = None) -> str:
    """Save a pytree (params/opt state). Device arrays are fetched host-side
    first so the artifact is topology-independent. ``sharding`` (the
    partition-plane manifest section: rule table + mesh config) is written
    as ``sharding.json`` beside the state, so a restore on ANY topology
    knows the placement the run declared (``checkpoint_sharding`` reads
    it back; ``parallel.partition.checkpoint_sharding_fn`` turns it into
    per-leaf shard-slice restores)."""
    target = _step_dir(path, step)
    os.makedirs(target, exist_ok=True)
    host_tree = jax.tree_util.tree_map_with_path(_to_host, tree)
    if use_orbax is None:
        use_orbax = False  # npz path is deterministic + dependency-light; orbax opt-in
    if use_orbax:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(target, "orbax"), host_tree, force=True)
    else:
        serialization.save_pytree(host_tree, os.path.join(target, "state"))
    if sharding:
        with open(os.path.join(target, "sharding.json"), "w") as f:
            json.dump(sharding, f, indent=2, sort_keys=True)
    if not use_orbax:
        # sha256 sidecar per payload (npz AND the tree/sharding JSON —
        # save_pytree writes both, and a torn tree.json would otherwise
        # pass verification then die as an opaque JSONDecodeError),
        # written BEFORE the DONE marker: restore verifies against them
        # and demotes a torn step to the previous completed one
        for payload in ("state.npz", "state.tree.json", "sharding.json"):
            _write_digest_sidecar(os.path.join(target, payload))
    with open(os.path.join(target, "DONE"), "w") as f:
        f.write(str(step))
    return target


def _sidecar_path(payload_path: str) -> str:
    return payload_path + ".sha256"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_digest_sidecar(payload_path: str) -> None:
    if not os.path.isfile(payload_path):
        return
    with open(_sidecar_path(payload_path), "w") as f:
        f.write(_sha256_file(payload_path))


def verify_checkpoint(path: str, step: int) -> bool:
    """True iff every payload with a sha256 sidecar matches it. Payloads
    WITHOUT a sidecar (pre-sidecar checkpoints, orbax dirs) verify
    vacuously — verification tightens the contract, it must not brick
    every existing checkpoint on disk."""
    target = _step_dir(path, step)
    for name in os.listdir(target) if os.path.isdir(target) else ():
        if not name.endswith(".sha256"):
            continue
        payload = os.path.join(target, name[:-len(".sha256")])
        if not os.path.isfile(payload):
            return False
        with open(os.path.join(target, name)) as f:
            expected = f.read().strip()
        if _sha256_file(payload) != expected:
            return False
    return True


def latest_verified_step(path: str) -> int | None:
    """The newest completed step whose payloads pass sidecar verification —
    what a crash-safe resume (``continual.TrainSupervisor``) restores from.
    A failing step demotes to the previous completed one with ONE
    structured warning per corrupt step."""
    for step in reversed(_completed_steps(path)):
        if verify_checkpoint(path, step):
            return step
        _warn_corrupt(path, step)
    return None


_warned_corrupt: set = set()


def _warn_corrupt(path: str, step: int) -> None:
    """ONE structured warning per corrupt (path, step) per process — the
    supervisor and loop re-scan frequently and must not spam the log."""
    key = (os.path.abspath(path), int(step))
    if key in _warned_corrupt:
        return
    _warned_corrupt.add(key)
    _logger.warning(json.dumps({
        "event": "checkpoint_verification_failed",
        "path": path, "step": int(step),
        "action": "demoted to previous completed step"}))


def checkpoint_sharding(path: str, step: int | None = None) -> dict | None:
    """The ``sharding`` section saved with a checkpoint (None when the run
    declared no rule table, or for pre-sharding-plane checkpoints)."""
    if step is None:
        step = latest_step(path)
        if step is None:
            return None
    target = os.path.join(_step_dir(path, step), "sharding.json")
    if not os.path.isfile(target):
        return None
    import json

    with open(target) as f:
        return json.load(f)


def _is_complete(target: str) -> bool:
    """A step dir counts only when the DONE marker AND the state payload
    both exist — a crash between payload write and marker (or a marker left
    beside a vanished payload) must never be restorable as 'latest'."""
    if not os.path.exists(os.path.join(target, "DONE")):
        return False
    return (os.path.exists(os.path.join(target, "state.npz"))
            or os.path.isdir(os.path.join(target, "orbax")))


def _completed_steps(path: str) -> list[int]:
    """Steps with a fully written checkpoint. Partially-written dirs (no
    DONE / no payload — a crash mid-save) and malformed names are ignored,
    so ``latest_step``/``restore_checkpoint``/GC can never pick one up."""
    if not os.path.isdir(path):
        return []
    steps = []
    for d in os.listdir(path):
        if not d.startswith("step_"):
            continue
        try:
            step = int(d.split("_", 1)[1])
        except ValueError:
            continue  # foreign dir that merely looks like a step
        if _is_complete(os.path.join(path, d)):
            steps.append(step)
    return sorted(steps)


def latest_step(path: str) -> int | None:
    steps = _completed_steps(path)
    return steps[-1] if steps else None


def restore_checkpoint(path: str, step: int | None = None, sharding_fn=None,
                       verify: bool = True) -> Any:
    """Restore a checkpoint, optionally placing leaves as they load.

    ``verify`` (default on) checks every payload against its sha256
    sidecar first: with ``step=None`` a corrupt newest checkpoint demotes
    to the previous completed step (one structured warning — the "latest
    verified checkpoint" contract the training supervisor resumes on); an
    EXPLICITLY requested corrupt step raises :class:`CheckpointCorrupt`
    instead of returning garbage params.

    ``sharding_fn`` re-places leaves on the current mesh and accepts
    either signature:

    * ``fn(leaf) -> Sharding`` (legacy), or
    * ``fn(path_name, leaf) -> Sharding | None`` (path-aware — what
      ``parallel.partition.checkpoint_sharding_fn`` builds from a rule
      table; ``path_name`` is the slash-joined tree path, and returning
      None keeps that leaf host-side numpy, e.g. the loader's
      ``data_iter`` state).

    With a sharded target each ``device_put`` transfers only that
    device's shard slices — no host materializes a device-resident full
    copy of any leaf."""
    verified_already = False
    if step is None:
        if verify:
            step = latest_verified_step(path)
            verified_already = True  # don't re-hash the same payloads
        else:
            step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no completed checkpoint under {path}")
    target = _step_dir(path, step)
    if not _is_complete(target):
        raise FileNotFoundError(
            f"checkpoint step {step} under {path} is incomplete (crash "
            f"during save?) — latest completed: {latest_step(path)}")
    if verify and not verified_already and not verify_checkpoint(path, step):
        raise CheckpointCorrupt(
            f"checkpoint step {step} under {path} fails its sha256 sidecar "
            f"verification (torn or bit-rotted payload) — latest verified: "
            f"{latest_verified_step(path)}")
    orbax_dir = os.path.join(target, "orbax")
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        tree = ocp.PyTreeCheckpointer().restore(orbax_dir)
    else:
        tree = serialization.load_pytree(os.path.join(target, "state"))
    if sharding_fn is not None:
        import inspect

        try:
            # path-aware iff the callable REQUIRES two positional args —
            # a legacy one-leaf callback with extra defaulted params
            # (lambda leaf, mesh=m: ...) must keep its old contract
            sig = inspect.signature(sharding_fn)
            required = [p for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty]
            path_aware = len(required) >= 2
        except (TypeError, ValueError):
            path_aware = False
        if path_aware:
            from .partition import place_leaf, tree_path_name

            def place(keypath, x):
                sh = sharding_fn(tree_path_name(keypath), x)
                return x if sh is None else place_leaf(x, sh)

            tree = jax.tree_util.tree_map_with_path(place, tree)
        else:
            tree = jax.tree.map(
                lambda x: jax.device_put(x, sharding_fn(x)), tree)
    return tree


class AsyncCheckpointer:
    """Checkpoint writes that overlap with training.

    ``save`` is non-blocking on the device→host transfer: device leaves get
    an async on-device copy (``jnp.copy`` — an enqueued dispatch, so the
    caller may donate or mutate its own state the moment ``save`` returns)
    with ``copy_to_host_async`` started immediately; the blocking
    ``np.asarray`` fetch AND serialization + fsync run on a single
    background thread. This is the TPU-idiomatic replacement for the
    reference's synchronous pytorch-lightning ModelCheckpoint. Backpressure
    mirrors orbax's AsyncCheckpointer: at most ONE write is in flight — a
    ``save`` while the previous write is still running blocks until it
    completes (surfacing its error), so snapshots can never queue
    unboundedly and OOM the host on 7B-class states. One worker thread
    keeps saves ordered; ``keep`` retains only the most recent completed
    checkpoints (top-k retention, like the reference's ``save_top_k``).

    Call ``wait()`` (or use as a context manager) before reading checkpoints
    or exiting — the last write's errors surface there.
    """

    def __init__(self, path: str, keep: int = 3, use_orbax: bool = False,
                 sharding: dict | None = None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.path = path
        self.keep = keep
        self.use_orbax = use_orbax
        # the partition-plane manifest section written beside every step
        # (fit_source fills this in from the trainer's rule table)
        self.sharding = sharding
        self._exec = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        self._inflight: concurrent.futures.Future | None = None

    def save(self, tree: Any, step: int):
        """Snapshot (async dispatches only), write in the background;
        returns the Future. Blocks first iff the previous write is still
        running (single-pending backpressure)."""
        self.wait()  # at most one write in flight; surfaces prior errors

        import jax.numpy as jnp

        def snap(x):
            if isinstance(x, jax.Array):
                c = jnp.copy(x)  # async device-side copy; donation-safe
                try:
                    c.copy_to_host_async()  # start DMA; worker blocks on it
                except Exception:
                    pass  # some backends/shardings lack the fast path
                return c
            # np.array (not asarray) forces a copy for host-numpy leaves, so
            # callers may mutate their buffers the moment save() returns
            return np.array(x)

        snapshot = jax.tree.map(snap, tree)
        self._inflight = self._exec.submit(self._write, snapshot, step)
        return self._inflight

    def _write(self, snapshot: Any, step: int) -> str:
        # the blocking device→host fetch happens HERE, off the train loop
        host_tree = jax.tree_util.tree_map_with_path(_to_host, snapshot)
        target = save_checkpoint(self.path, host_tree, step,
                                 use_orbax=self.use_orbax,
                                 sharding=self.sharding)
        self._gc()
        return target

    def _gc(self) -> None:
        done = _completed_steps(self.path)
        for step in done[:-self.keep]:
            shutil.rmtree(_step_dir(self.path, step), ignore_errors=True)
        if done:
            # crash leftovers: partial dirs OLDER than the newest completed
            # checkpoint can never complete (saves are ordered on one worker
            # thread) — drop them so a restore tool listing the directory
            # sees only restorable steps
            for d in os.listdir(self.path):
                if not d.startswith("step_"):
                    continue
                try:
                    step = int(d.split("_", 1)[1])
                except ValueError:
                    continue
                target = os.path.join(self.path, d)
                if step < done[-1] and not _is_complete(target):
                    shutil.rmtree(target, ignore_errors=True)

    def wait(self) -> None:
        """Block until the in-flight write (if any) finishes; re-raises its
        error. With single-pending backpressure there is at most one."""
        fut, self._inflight = self._inflight, None
        if fut is not None:
            fut.result()

    def close(self) -> None:
        self.wait()
        self._exec.shutdown(wait=True)

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
