"""Sharded checkpoint/resume keyed by mesh (orbax-backed, npz fallback).

Reference checkpointing is model-level: LightGBM ``modelString`` carry-over
(``LightGBMBase.scala:48-60``), VW ``initialModel`` bytes, pytorch-lightning
ModelCheckpoint (SURVEY.md §5). TPU equivalent: orbax sharded checkpoints that
restore onto a different mesh topology (host-side numpy round-trip when orbax
is unavailable or the target is single-process).
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import hashlib
import json
import logging
import os
import shutil
import threading
from typing import Any

import jax
import numpy as np

from ..core import serialization

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_step",
           "latest_verified_step", "verify_checkpoint",
           "CheckpointCorrupt", "checkpoint_sharding", "AsyncCheckpointer",
           # coordinated multi-host checkpoints (two-phase commit)
           "save_checkpoint_shard", "commit_checkpoint", "checkpoint_world",
           "restore_host_states", "checkpoint_meta", "gc_checkpoints"]

_logger = logging.getLogger("synapseml_tpu.parallel.checkpoint")

# serializes the commit write side (sweep + DONE install): the emergency
# dance and the periodic commit scanner are different threads of one
# coordinator and can try to commit the SAME complete step concurrently
_commit_lock = threading.Lock()

# per-checkpoint-dir verification memo for the save_checkpoint(keep=) path
# (AsyncCheckpointer and GangCoordinator thread their own instance caches)
_gc_memo: dict[str, dict] = {}


class CheckpointCorrupt(RuntimeError):
    """A checkpoint payload failed its sha256 sidecar verification — the
    file is torn or bit-rotted, not merely incomplete."""


def _step_dir(path: str, step: int) -> str:
    return os.path.join(path, f"step_{step:010d}")


def _to_host(keypath, x):
    """Host-side numpy for one leaf. A leaf spanning other processes
    cannot be fetched by the SINGLE-host npz writer (no host holds the
    full value) — point the caller at the coordinated per-host shard
    writer instead of surfacing jax's generic non-addressable fetch
    failure mid-write."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from .partition import tree_path_name

        raise ValueError(
            f"checkpoint leaf {tree_path_name(keypath)!r} is sharded "
            "across processes — the single-host npz writer cannot gather "
            "it. Use the coordinated multi-host path: every process calls "
            "save_checkpoint_shard(...) (each writes only its locally-"
            "addressable shard slices) and the driver commits via "
            "commit_checkpoint(...); restore_checkpoint reassembles the "
            "shards on ANY number of surviving hosts")
    return np.asarray(x)


def save_checkpoint(path: str, tree: Any, step: int = 0, use_orbax: bool | None = None,
                    sharding: dict | None = None, keep: int | None = None) -> str:
    """Save a pytree (params/opt state). Device arrays are fetched host-side
    first so the artifact is topology-independent. ``sharding`` (the
    partition-plane manifest section: rule table + mesh config) is written
    as ``sharding.json`` beside the state, so a restore on ANY topology
    knows the placement the run declared (``checkpoint_sharding`` reads
    it back; ``parallel.partition.checkpoint_sharding_fn`` turns it into
    per-leaf shard-slice restores). ``keep`` runs :func:`gc_checkpoints`
    after the write — retain only the last ``keep`` verified steps."""
    target = _step_dir(path, step)
    os.makedirs(target, exist_ok=True)
    host_tree = jax.tree_util.tree_map_with_path(_to_host, tree)
    if use_orbax is None:
        use_orbax = False  # npz path is deterministic + dependency-light; orbax opt-in
    if use_orbax:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(target, "orbax"), host_tree, force=True)
    else:
        serialization.save_pytree(host_tree, os.path.join(target, "state"))
    if sharding:
        with open(os.path.join(target, "sharding.json"), "w") as f:
            json.dump(sharding, f, indent=2, sort_keys=True)
    if not use_orbax:
        # sha256 sidecar per payload (npz AND the tree/sharding JSON —
        # save_pytree writes both, and a torn tree.json would otherwise
        # pass verification then die as an opaque JSONDecodeError),
        # written BEFORE the DONE marker: restore verifies against them
        # and demotes a torn step to the previous completed one
        for payload in ("state.npz", "state.tree.json", "sharding.json"):
            _write_digest_sidecar(os.path.join(target, payload))
    with open(os.path.join(target, "DONE"), "w") as f:
        f.write(str(step))
    if keep is not None:
        # persistent per-path memo: committed checkpoints are immutable,
        # so without it every save would re-hash the full payload of all
        # retained steps ON THE TRAINING THREAD; the just-written step is
        # seeded (its sidecars were computed from the on-disk bytes)
        cache = _gc_memo.setdefault(os.path.abspath(path), {})
        if not use_orbax:
            cache[int(step)] = True
        gc_checkpoints(path, keep, verified_cache=cache)
    return target


def _sidecar_path(payload_path: str) -> str:
    return payload_path + ".sha256"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_digest_sidecar(payload_path: str) -> None:
    if not os.path.isfile(payload_path):
        return
    with open(_sidecar_path(payload_path), "w") as f:
        f.write(_sha256_file(payload_path))


# ---------------------------------------------------------------------------
# coordinated multi-host sharded checkpoints (two-phase commit)
# ---------------------------------------------------------------------------
#
# Layout of one committed N-host step dir:
#
#   step_0000000012/
#     state.shard00000-of-00004.npz    # rank 0: every fully-addressable
#     state.shard00000-of-00004.json   #   (replicated) leaf + its chunks
#     state.shard00001-of-00004.npz    # ranks > 0: only locally-addressable
#     ...                              #   chunks + their per-host payload
#     *.sha256                         # integrity sidecars per payload
#     state.tree.json                  # global tree structure (rank 0)
#     sharding.json                    # optional partition-plane section
#     ACK.00001-of-00004               # phase 1: rank i's payload is durable
#     DONE                             # phase 2: the driver's COMMIT marker
#
# Phase 1: each process writes its shard npz + manifest + sidecars, fsyncs,
# then drops its ACK. Phase 2: the driver (gang coordinator) sees the full
# ACK set and writes DONE (JSON: step + world). A write torn ANYWHERE —
# missing shard, missing ACK, no DONE, bit-rot — is never restorable:
# completeness requires DONE + every shard, and the sha256 sidecars make a
# torn payload surface as :class:`CheckpointCorrupt` instead of garbage.

def _shard_stem(rank: int, world: int) -> str:
    return f"state.shard{rank:05d}-of-{world:05d}"


def _ack_name(rank: int, world: int) -> str:
    return f"ACK.{rank:05d}-of-{world:05d}"


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _flatten_leaves(tree) -> dict:
    """Slash-joined path -> RAW leaf: the ONE shared serialization codec
    with an identity leaf_fn (no np.asarray — leaves may be cross-process
    jax arrays), so shard assembly rebuilds through the same structure
    JSON as the single-file format and the schemes cannot drift."""
    return serialization.flatten_pytree(tree, leaf_fn=lambda x: x)


def _local_chunks(leaf):
    """The locally-addressable pieces of a cross-process array as
    ``[(start_indices, stop_indices, np.ndarray)]`` (deduped — replicated-
    over-local-devices shards appear once)."""
    chunks, seen = [], set()
    for s in leaf.addressable_shards:
        idx = tuple(s.index)
        shape = leaf.shape
        key = tuple((sl.start or 0, sl.stop if sl.stop is not None else dim)
                    for sl, dim in zip(idx, shape))
        if key in seen:
            continue
        seen.add(key)
        chunks.append(([k[0] for k in key], [k[1] for k in key],
                       np.asarray(s.data)))
    return chunks


def save_checkpoint_shard(path: str, tree: Any, step: int, *,
                          process_index: int, process_count: int,
                          host_tree: Any | None = None,
                          sharding: dict | None = None,
                          meta: dict | None = None,
                          chunk_fn=None, run_id: str | None = None) -> str:
    """Phase 1 of a coordinated multi-host checkpoint: write THIS process's
    shard of ``tree`` (call on every process, same ``step``).

    Per leaf: a cross-process ``jax.Array`` contributes only this host's
    locally-addressable shard slices (index ranges recorded in the shard
    manifest); a fully-addressable leaf is written whole by rank 0 only.
    ``host_tree`` is per-host payload (e.g. the loader's ``data_iter``
    cursor) — every rank stores its own copy, and
    :func:`restore_host_states` returns all of them (the N→M elastic
    resume input). ``meta`` (rank 0) records run-level facts like the
    gang's original world size. ``chunk_fn(path_name, leaf) ->
    [(start, stop, array)] | None`` overrides chunk extraction (tests,
    host-side ZeRO states). ``run_id`` stamps the ACK with this launch's
    incarnation — the driver's :func:`commit_checkpoint` fences on it, so
    a STALE ack left by a killed previous run can never combine with the
    new run's acks into a commit over a payload still being overwritten.

    Ends by dropping this rank's ACK marker. NO ``DONE`` is written here —
    the checkpoint only becomes restorable when the driver, having seen
    every ACK, runs :func:`commit_checkpoint` (phase 2)."""
    if not 0 <= int(process_index) < int(process_count):
        raise ValueError(f"process_index {process_index} outside world "
                         f"{process_count}")
    rank, world = int(process_index), int(process_count)
    target = _step_dir(path, step)
    os.makedirs(target, exist_ok=True)
    stem = _shard_stem(rank, world)
    flat = _flatten_leaves(tree)
    payload: dict[str, np.ndarray] = {}
    manifest: dict = {"rank": rank, "world": world, "step": int(step),
                      "globals": [], "chunks": {}, "host": None}
    for name, leaf in flat.items():
        chunks = chunk_fn(name, leaf) if chunk_fn is not None else None
        if chunks is None and isinstance(leaf, jax.Array) \
                and not leaf.is_fully_addressable:
            chunks = _local_chunks(leaf)
        if chunks is not None:
            parts = []
            for k, (start, stop, arr) in enumerate(chunks):
                key = f"c:{name}#{k}"
                payload[key] = np.asarray(arr)
                parts.append({"key": key,
                              "start": [int(x) for x in start],
                              "stop": [int(x) for x in stop]})
            shape = getattr(leaf, "shape", None)
            if shape is None:
                shape = np.shape(leaf)
            manifest["chunks"][name] = {
                "shape": [int(s) for s in shape],
                "dtype": str(np.dtype(getattr(leaf, "dtype", np.float32))),
                "parts": parts}
        elif rank == 0:
            payload[f"g:{name}"] = np.asarray(leaf)
            manifest["globals"].append(name)
    if host_tree is not None:
        for name, leaf in serialization.flatten_pytree(host_tree).items():
            payload[f"h:{name}"] = leaf
        manifest["host"] = serialization.tree_structure(host_tree)
    if rank == 0 and meta:
        manifest["meta"] = dict(meta)
    written = [stem + ".npz", stem + ".json"]
    np.savez(os.path.join(target, stem + ".npz"), **payload)
    with open(os.path.join(target, stem + ".json"), "w") as f:
        json.dump(manifest, f, sort_keys=True)
    if rank == 0:
        with open(os.path.join(target, "state.tree.json"), "w") as f:
            json.dump(serialization.tree_structure(tree), f)
        written.append("state.tree.json")
        if sharding:
            with open(os.path.join(target, "sharding.json"), "w") as f:
                json.dump(sharding, f, indent=2, sort_keys=True)
            written.append("sharding.json")
    for name in written:
        _fsync_file(os.path.join(target, name))
        _write_digest_sidecar(os.path.join(target, name))
    ack = os.path.join(target, _ack_name(rank, world))
    payload = {"step": int(step), "rank": rank, "files": written}
    if run_id is not None:
        payload["run"] = str(run_id)
    # temp + rename, never in place: the driver's commit scanner may read
    # the ACK at any instant (an empty/partial ACK would fail the parse),
    # and the rename bumps the step dir's mtime — the scanner's
    # nothing-changed gate relies on it, including when a relaunch
    # overwrites a torn dir's files under their existing names
    tmp = ack + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    _fsync_file(tmp)
    os.replace(tmp, ack)
    return target


def commit_checkpoint(path: str, step: int, process_count: int,
                      run_id: str | None = None) -> str | None:
    """Phase 2 (driver side): verify the full ACK set for ``step`` — every
    rank's marker present, stamped with THIS run's ``run_id`` (when given),
    and every file each ACK lists on disk — then write the ``DONE`` COMMIT
    marker. Returns the step dir, or None when the set is still incomplete
    (commit later, or never: an uncommitted dir is invisible to
    ``latest_step``/restore). The run-id fence matters on resume: a killed
    run's leftover ACK in a torn dir must not combine with the new run's
    ACKs while the new incarnation is still overwriting the payload."""
    world = int(process_count)
    target = _step_dir(path, step)
    if not os.path.isdir(target):
        return None
    fenced = 0
    acked: set[str] = set()
    for rank in range(world):
        ack = os.path.join(target, _ack_name(rank, world))
        if not os.path.isfile(ack):
            return None
        try:
            with open(ack) as f:
                data = json.load(f)
            listed = data.get("files", [])
        except (OSError, json.JSONDecodeError):
            return None
        if run_id is not None and data.get("run") != str(run_id):
            fenced += 1  # stale ack from a previous incarnation
            continue
        if any(not os.path.isfile(os.path.join(target, name))
               for name in listed):
            return None
        acked.update(listed)
    if fenced:
        # The ACK set is otherwise complete — only the run-id fence blocks
        # the commit. A torn relaunch hits this transiently (the new
        # incarnation overwrites the acks), but a worker launched WITHOUT
        # the rendezvous run_id hits it forever: every checkpoint silently
        # stays uncommitted. Surface it once per (dir, step).
        _warn_run_fenced(path, step, fenced, world)
        return None
    # Serialize the write side: the emergency dance and the periodic
    # commit scanner run on different coordinator threads and can reach a
    # complete ACK set for the SAME step simultaneously — without the
    # lock, both would race on the sweep and the DONE install (a torn
    # half-written DONE, or one thread's tmp vanishing under the other).
    done = os.path.join(target, "DONE")
    with _commit_lock:
        if os.path.exists(done):  # already committed (idempotent success)
            return target
        # Drop anything a PREVIOUS incarnation left in this reused step
        # dir (an N-world shard + sidecar a killed run wrote before an
        # N→M resume re-reached the same step): the driver is the only
        # writer left (every rank's ACK is in), and verify_checkpoint
        # hashes EVERY sidecar'd payload in the dir — one stale torn file
        # would brick the recommitted step as CheckpointCorrupt forever.
        keep = set(acked)
        keep.update(name + ".sha256" for name in acked)
        keep.update(_ack_name(r, world) for r in range(world))
        keep.add("DONE")
        try:
            for name in os.listdir(target):
                if name not in keep:
                    with contextlib.suppress(OSError):
                        os.remove(os.path.join(target, name))
        except OSError:
            pass
        tmp = f"{done}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump({"step": int(step), "world": world}, f)
        _fsync_file(tmp)
        os.replace(tmp, done)  # a torn DONE must never look committed
    return target


def _done_world(target: str) -> int | None:
    """World size recorded in a step dir's DONE marker (None: legacy
    single-host marker, or no marker)."""
    try:
        with open(os.path.join(target, "DONE")) as f:
            raw = f.read()
    except OSError:
        return None
    try:
        data = json.loads(raw)
    except json.JSONDecodeError:
        return None  # legacy plain-int marker
    return int(data["world"]) if isinstance(data, dict) and "world" in data \
        else None


def checkpoint_world(path: str, step: int) -> int | None:
    """How many processes wrote a committed step (None = single-host)."""
    return _done_world(_step_dir(path, step))


def _assemble_sharded(target: str, world: int) -> Any:
    """Reassemble the global tree from N shard files, host-side — the
    reader may be ANY number of processes (each reads all shards off the
    shared checkpoint dir; with a ``sharding_fn`` each then device_puts
    only its own slices). Chunk coverage is validated element-exactly:
    a manifest whose parts don't tile the recorded shape means a rank's
    write was torn or lost -> :class:`CheckpointCorrupt`."""
    with open(os.path.join(target, "state.tree.json")) as f:
        structure = json.load(f)
    flat: dict[str, np.ndarray] = {}
    # per leaf: element-wise coverage mask. A REPLICATED leaf yields the
    # identical full-range chunk from every rank (harmless re-writes); a
    # count-based check would let OVERLAPPING partial chunks compensate
    # for an uncovered hole (4+4 elements over an 8-element leaf can leave
    # [6:8] as uninitialized np.empty garbage) — the mask cannot be fooled
    covered: dict[str, np.ndarray] = {}
    for rank in range(world):
        stem = _shard_stem(rank, world)
        with open(os.path.join(target, stem + ".json")) as f:
            manifest = json.load(f)
        with np.load(os.path.join(target, stem + ".npz"),
                     allow_pickle=False) as npz:
            for name in manifest.get("globals", ()):
                flat[name] = npz[f"g:{name}"]
            for name, info in manifest.get("chunks", {}).items():
                shape = tuple(int(s) for s in info["shape"])
                if name not in flat:
                    flat[name] = np.empty(shape, dtype=np.dtype(info["dtype"]))
                    covered[name] = np.zeros(shape, dtype=bool)
                for part in info["parts"]:
                    idx = tuple(slice(a, b) for a, b in
                                zip(part["start"], part["stop"]))
                    flat[name][idx] = npz[part["key"]]
                    covered[name][idx] = True
    for name, mask in covered.items():
        if not mask.all():
            got, want = int(np.count_nonzero(mask)), int(mask.size)
            raise CheckpointCorrupt(
                f"sharded checkpoint leaf {name!r} assembled {got} of "
                f"{want} elements from {world} shard(s) — a rank's chunk "
                "set is missing or does not tile the leaf")
    return serialization.rebuild_pytree(structure, flat)


def restore_host_states(path: str, step: int | None = None,
                        verify: bool = True) -> dict[int, Any]:
    """Every rank's per-host payload (``host_tree`` at save time) from a
    committed multi-host checkpoint: ``{rank: tree}``. For a single-host
    checkpoint returns ``{}`` — the per-host state rides inside the main
    tree there. This is the elastic-resume input: N ``data_iter`` cursors
    that :class:`~synapseml_tpu.data.state.ElasticPlan` redistributes
    over M survivors."""
    if step is None:
        # latest_verified_step already hashed the chosen step's payloads —
        # re-verifying below would be a second full sha256 pass over every
        # shard on the recovery-time path
        step = latest_verified_step(path) if verify else latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no completed checkpoint under {path}")
        verify = False
    target = _step_dir(path, step)
    world = _done_world(target)
    if world is None:
        return {}
    if verify and not verify_checkpoint(path, step):
        raise CheckpointCorrupt(
            f"checkpoint step {step} under {path} fails verification")
    out: dict[int, Any] = {}
    for rank in range(world):
        stem = _shard_stem(rank, world)
        with open(os.path.join(target, stem + ".json")) as f:
            manifest = json.load(f)
        if manifest.get("host") is None:
            continue
        with np.load(os.path.join(target, stem + ".npz"),
                     allow_pickle=False) as npz:
            flat = {k[2:]: npz[k] for k in npz.files if k.startswith("h:")}
        out[rank] = serialization.rebuild_pytree(manifest["host"], flat)
    return out


def checkpoint_meta(path: str, step: int | None = None) -> dict:
    """Rank 0's ``meta`` dict from a committed multi-host checkpoint
    (e.g. ``{"orig_world": N, "seed": s}``); ``{}`` for single-host."""
    if step is None:
        step = latest_verified_step(path)
        if step is None:
            return {}
    target = _step_dir(path, step)
    world = _done_world(target)
    if world is None:
        return {}
    with open(os.path.join(target, _shard_stem(0, world) + ".json")) as f:
        return json.load(f).get("meta") or {}


def verify_checkpoint(path: str, step: int) -> bool:
    """True iff every payload with a sha256 sidecar matches it. Payloads
    WITHOUT a sidecar (pre-sidecar checkpoints, orbax dirs) verify
    vacuously — verification tightens the contract, it must not brick
    every existing checkpoint on disk."""
    target = _step_dir(path, step)
    for name in os.listdir(target) if os.path.isdir(target) else ():
        if not name.endswith(".sha256"):
            continue
        payload = os.path.join(target, name[:-len(".sha256")])
        if not os.path.isfile(payload):
            return False
        with open(os.path.join(target, name)) as f:
            expected = f.read().strip()
        if _sha256_file(payload) != expected:
            return False
    return True


def latest_verified_step(path: str) -> int | None:
    """The newest completed step whose payloads pass sidecar verification —
    what a crash-safe resume (``continual.TrainSupervisor``) restores from.
    A failing step demotes to the previous completed one with ONE
    structured warning per corrupt step."""
    for step in reversed(_completed_steps(path)):
        if verify_checkpoint(path, step):
            return step
        _warn_corrupt(path, step)
    return None


_warned_run_fenced: set = set()


def _warn_run_fenced(path: str, step: int, fenced: int, world: int) -> None:
    """ONE structured warning per (path, step) whose complete ACK set is
    blocked from committing ONLY by the run-id fence — the scanner polls
    every tick and a persistent mismatch (a worker built without the
    rendezvous ``run_id``) would otherwise be an invisible no-commit."""
    key = (os.path.abspath(path), int(step))
    if key in _warned_run_fenced:
        return
    _warned_run_fenced.add(key)
    _logger.warning(json.dumps({
        "event": "checkpoint_commit_run_fenced",
        "path": path, "step": int(step),
        "fenced_acks": int(fenced), "world": int(world),
        "hint": "ACK run ids do not match this incarnation; pass the "
                "rendezvous reply's run_id to GangWorker/"
                "save_checkpoint_shard (transient during a torn relaunch)"}))


_warned_corrupt: set = set()


def _warn_corrupt(path: str, step: int) -> None:
    """ONE structured warning per corrupt (path, step) per process — the
    supervisor and loop re-scan frequently and must not spam the log."""
    key = (os.path.abspath(path), int(step))
    if key in _warned_corrupt:
        return
    _warned_corrupt.add(key)
    _logger.warning(json.dumps({
        "event": "checkpoint_verification_failed",
        "path": path, "step": int(step),
        "action": "demoted to previous completed step"}))


def checkpoint_sharding(path: str, step: int | None = None) -> dict | None:
    """The ``sharding`` section saved with a checkpoint (None when the run
    declared no rule table, or for pre-sharding-plane checkpoints). With
    ``step=None`` this reads the latest VERIFIED step — the same default
    every resume path uses, so a torn newest checkpoint cannot pair the
    previous step's params with the torn step's rule table."""
    if step is None:
        step = latest_verified_step(path)
        if step is None:
            return None
    target = os.path.join(_step_dir(path, step), "sharding.json")
    if not os.path.isfile(target):
        return None
    import json

    with open(target) as f:
        return json.load(f)


def _is_complete(target: str) -> bool:
    """A step dir counts only when the DONE marker AND the state payload
    both exist — a crash between payload write and marker (or a marker left
    beside a vanished payload) must never be restorable as 'latest'. A
    multi-host dir (DONE records a world size) additionally requires EVERY
    rank's ACK + shard payload: a commit marker beside a vanished shard is
    a torn write, not a checkpoint."""
    if not os.path.exists(os.path.join(target, "DONE")):
        return False
    world = _done_world(target)
    if world is not None:
        return all(
            os.path.isfile(os.path.join(target, _ack_name(r, world)))
            and os.path.isfile(os.path.join(
                target, _shard_stem(r, world) + ".npz"))
            for r in range(world))
    return (os.path.exists(os.path.join(target, "state.npz"))
            or os.path.isdir(os.path.join(target, "orbax")))


def _completed_steps(path: str) -> list[int]:
    """Steps with a fully written checkpoint. Partially-written dirs (no
    DONE / no payload — a crash mid-save) and malformed names are ignored,
    so ``latest_step``/``restore_checkpoint``/GC can never pick one up."""
    if not os.path.isdir(path):
        return []
    steps = []
    for d in os.listdir(path):
        if not d.startswith("step_"):
            continue
        try:
            step = int(d.split("_", 1)[1])
        except ValueError:
            continue  # foreign dir that merely looks like a step
        if _is_complete(os.path.join(path, d)):
            steps.append(step)
    return sorted(steps)


def latest_step(path: str) -> int | None:
    steps = _completed_steps(path)
    return steps[-1] if steps else None


def restore_checkpoint(path: str, step: int | None = None, sharding_fn=None,
                       verify: bool = True) -> Any:
    """Restore a checkpoint, optionally placing leaves as they load.

    ``verify`` (default on) checks every payload against its sha256
    sidecar first: with ``step=None`` a corrupt newest checkpoint demotes
    to the previous completed step (one structured warning — the "latest
    verified checkpoint" contract the training supervisor resumes on); an
    EXPLICITLY requested corrupt step raises :class:`CheckpointCorrupt`
    instead of returning garbage params.

    ``sharding_fn`` re-places leaves on the current mesh and accepts
    either signature:

    * ``fn(leaf) -> Sharding`` (legacy), or
    * ``fn(path_name, leaf) -> Sharding | None`` (path-aware — what
      ``parallel.partition.checkpoint_sharding_fn`` builds from a rule
      table; ``path_name`` is the slash-joined tree path, and returning
      None keeps that leaf host-side numpy, e.g. the loader's
      ``data_iter`` state).

    With a sharded target each ``device_put`` transfers only that
    device's shard slices — no host materializes a device-resident full
    copy of any leaf."""
    verified_already = False
    if step is None:
        if verify:
            step = latest_verified_step(path)
            verified_already = True  # don't re-hash the same payloads
        else:
            step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no completed checkpoint under {path}")
    target = _step_dir(path, step)
    if not _is_complete(target):
        if os.path.isdir(target) and (
                _done_world(target) is not None
                or any(n.startswith(("state.shard", "ACK."))
                       for n in os.listdir(target))):
            # a partially-written MULTI-HOST dir: some phase-1 shards (or
            # even a commit marker beside a vanished shard) exist — that
            # is a torn coordinated write, distinct from "no such step"
            # (a legacy DONE with a vanished single-host payload stays a
            # FileNotFoundError, as before)
            raise CheckpointCorrupt(
                f"checkpoint step {step} under {path} is a torn multi-host "
                f"write (phase-1 shards without a complete commit) — "
                f"latest completed: {latest_step(path)}")
        raise FileNotFoundError(
            f"checkpoint step {step} under {path} is incomplete (crash "
            f"during save?) — latest completed: {latest_step(path)}")
    if verify and not verified_already and not verify_checkpoint(path, step):
        raise CheckpointCorrupt(
            f"checkpoint step {step} under {path} fails its sha256 sidecar "
            f"verification (torn or bit-rotted payload) — latest verified: "
            f"{latest_verified_step(path)}")
    orbax_dir = os.path.join(target, "orbax")
    world = _done_world(target)
    if world is not None:
        tree = _assemble_sharded(target, world)
    elif os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        tree = ocp.PyTreeCheckpointer().restore(orbax_dir)
    else:
        tree = serialization.load_pytree(os.path.join(target, "state"))
    if sharding_fn is not None:
        import inspect

        try:
            # path-aware iff the callable REQUIRES two positional args —
            # a legacy one-leaf callback with extra defaulted params
            # (lambda leaf, mesh=m: ...) must keep its old contract
            sig = inspect.signature(sharding_fn)
            required = [p for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty]
            path_aware = len(required) >= 2
        except (TypeError, ValueError):
            path_aware = False
        if path_aware:
            from .partition import place_leaf, tree_path_name

            def place(keypath, x):
                sh = sharding_fn(tree_path_name(keypath), x)
                return x if sh is None else place_leaf(x, sh)

            tree = jax.tree_util.tree_map_with_path(place, tree)
        else:
            tree = jax.tree.map(
                lambda x: jax.device_put(x, sharding_fn(x)), tree)
    return tree


def gc_checkpoints(path: str, keep: int,
                   verified_cache: dict | None = None) -> list[int]:
    """Retention GC: keep the last ``keep`` VERIFIED step dirs; prune every
    completed step older than the oldest kept one. The newest verified step
    is never pruned, and nothing newer than it is touched (an unverified-
    but-newer completed dir may be a checkpoint another process is still
    committing — the restore path already demotes past it). Corrupt
    (completed-but-unverified) steps OLDER than the newest verified one are
    pruned too: they can never be restored, only re-warn on every scan.

    ``verified_cache`` (a mutable dict ``{step: bool}``) memoizes
    verification outcomes — committed checkpoints are immutable, so a
    week-long run doesn't re-hash its whole history every save, and a
    bit-rotted newest dir (kept by the newer-than-verified guard, FAILING
    verification) isn't re-hashed on every call either.
    Returns the pruned steps."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    done = _completed_steps(path)
    cache = verified_cache if verified_cache is not None else {}

    def _check(step: int) -> bool:
        if step not in cache:
            cache[step] = verify_checkpoint(path, step)
        return cache[step]

    pruned = []
    # torn dirs first: an INCOMPLETE step older than the newest COMPLETED
    # one can never commit (per-worker saves are ordered, so every rank
    # has already moved past it — a full ACK set can no longer form).
    # Phase-1 shards a killed run left behind, a vanished payload: without
    # this, a preemption-heavy week accumulates torn dirs unboundedly and
    # the gang's commit scanner re-stats them forever. This is the ONE
    # torn-dir retention policy — AsyncCheckpointer._gc rides it too.
    if done:
        for d in os.listdir(path) if os.path.isdir(path) else ():
            if not d.startswith("step_"):
                continue
            try:
                step = int(d.split("_", 1)[1])
            except ValueError:
                continue
            target = os.path.join(path, d)
            if step < done[-1] and not _is_complete(target):
                shutil.rmtree(target, ignore_errors=True)
                pruned.append(step)
    verified = [s for s in done if _check(s)]
    if not verified:
        return sorted(set(pruned))
    kept = set(verified[-keep:])
    newest_verified = verified[-1]
    for step in done:
        if step >= newest_verified or step in kept:
            continue
        shutil.rmtree(_step_dir(path, step), ignore_errors=True)
        cache.pop(step, None)
        pruned.append(step)
    return sorted(set(pruned))


class AsyncCheckpointer:
    """Checkpoint writes that overlap with training.

    ``save`` is non-blocking on the device→host transfer: device leaves get
    an async on-device copy (``jnp.copy`` — an enqueued dispatch, so the
    caller may donate or mutate its own state the moment ``save`` returns)
    with ``copy_to_host_async`` started immediately; the blocking
    ``np.asarray`` fetch AND serialization + fsync run on a single
    background thread. This is the TPU-idiomatic replacement for the
    reference's synchronous pytorch-lightning ModelCheckpoint. Backpressure
    mirrors orbax's AsyncCheckpointer: at most ONE write is in flight — a
    ``save`` while the previous write is still running blocks until it
    completes (surfacing its error), so snapshots can never queue
    unboundedly and OOM the host on 7B-class states. One worker thread
    keeps saves ordered; ``keep`` retains only the most recent completed
    checkpoints (top-k retention, like the reference's ``save_top_k``).

    Call ``wait()`` (or use as a context manager) before reading checkpoints
    or exiting — the last write's errors surface there.
    """

    def __init__(self, path: str, keep: int = 3, use_orbax: bool = False,
                 sharding: dict | None = None, process_index: int = 0,
                 process_count: int = 1, host_state_key: str = "data_iter",
                 meta: dict | None = None, coordinated: bool | None = None,
                 run_id: str | None = None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.path = path
        self.keep = keep
        self.use_orbax = use_orbax
        # the partition-plane manifest section written beside every step
        # (fit_source fills this in from the trainer's rule table)
        self.sharding = sharding
        # coordinated mode (process_count > 1, or coordinated=True for a
        # one-survivor elastic gang): each save writes THIS process's
        # shard via save_checkpoint_shard — the ``host_state_key`` subtree
        # (the loader cursor a _LoaderCheckpointer injects) moves into the
        # per-host payload, and the gang DRIVER commits/GCs once every
        # rank's ACK lands. Single-host mode is unchanged.
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.coordinated = (self.process_count > 1 if coordinated is None
                            else bool(coordinated))
        self.host_state_key = host_state_key
        self.meta = meta
        self.run_id = run_id  # launch incarnation; fences stale ACKs
        self._verified_cache: dict = {}  # step -> verification outcome
        self._exec = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        self._inflight: concurrent.futures.Future | None = None

    def save(self, tree: Any, step: int):
        """Snapshot (async dispatches only), write in the background;
        returns the Future. Blocks first iff the previous write is still
        running (single-pending backpressure)."""
        self.wait()  # at most one write in flight; surfaces prior errors

        import jax.numpy as jnp

        def snap(x):
            if isinstance(x, jax.Array):
                c = jnp.copy(x)  # async device-side copy; donation-safe
                try:
                    c.copy_to_host_async()  # start DMA; worker blocks on it
                except Exception:
                    pass  # some backends/shardings lack the fast path
                return c
            # np.array (not asarray) forces a copy for host-numpy leaves, so
            # callers may mutate their buffers the moment save() returns
            return np.array(x)

        snapshot = jax.tree.map(snap, tree)
        self._inflight = self._exec.submit(self._write, snapshot, step)
        return self._inflight

    def _write(self, snapshot: Any, step: int) -> str:
        if self.coordinated:
            # coordinated shard write: the per-host cursor leaves the
            # global tree (every rank keeps its own), non-addressable
            # leaves contribute only local slices, the DRIVER commits
            host_tree = None
            if isinstance(snapshot, dict) and self.host_state_key in snapshot:
                snapshot = dict(snapshot)
                host_tree = {self.host_state_key:
                             snapshot.pop(self.host_state_key)}
            return save_checkpoint_shard(
                self.path, snapshot, step,
                process_index=self.process_index,
                process_count=self.process_count,
                host_tree=host_tree, sharding=self.sharding, meta=self.meta,
                run_id=self.run_id)
        # the blocking device→host fetch happens HERE, off the train loop
        host_tree = jax.tree_util.tree_map_with_path(_to_host, snapshot)
        target = save_checkpoint(self.path, host_tree, step,
                                 use_orbax=self.use_orbax,
                                 sharding=self.sharding)
        # the digest sidecars were just computed FROM the on-disk bytes —
        # seeding the memo spares _gc a second full-payload hash per save
        # (on the single writer thread, where a long hash pass would stall
        # the next save()'s backpressure wait)
        self._verified_cache[int(step)] = True
        self._gc()
        return target

    def _gc(self) -> None:
        # keep-last-K VERIFIED retention: a week-long run must not fill
        # the disk, and the kept set must always include a restorable
        # (hash-clean) checkpoint — pruning by completion alone could
        # retain K torn dirs and nothing restorable
        # (gc_checkpoints also prunes crash-leftover partial dirs older
        # than the newest completed step — one torn-dir policy, one place)
        gc_checkpoints(self.path, self.keep,
                       verified_cache=self._verified_cache)

    def wait(self) -> None:
        """Block until the in-flight write (if any) finishes; re-raises its
        error. With single-pending backpressure there is at most one."""
        fut, self._inflight = self._inflight, None
        if fut is not None:
            fut.result()

    def close(self) -> None:
        self.wait()
        self._exec.shutdown(wait=True)

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
