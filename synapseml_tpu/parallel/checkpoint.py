"""Sharded checkpoint/resume keyed by mesh (orbax-backed, npz fallback).

Reference checkpointing is model-level: LightGBM ``modelString`` carry-over
(``LightGBMBase.scala:48-60``), VW ``initialModel`` bytes, pytorch-lightning
ModelCheckpoint (SURVEY.md §5). TPU equivalent: orbax sharded checkpoints that
restore onto a different mesh topology (host-side numpy round-trip when orbax
is unavailable or the target is single-process).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from ..core import serialization

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_step"]


def _step_dir(path: str, step: int) -> str:
    return os.path.join(path, f"step_{step:010d}")


def save_checkpoint(path: str, tree: Any, step: int = 0, use_orbax: bool | None = None) -> str:
    """Save a pytree (params/opt state). Device arrays are fetched host-side
    first so the artifact is topology-independent."""
    target = _step_dir(path, step)
    os.makedirs(target, exist_ok=True)
    host_tree = jax.tree.map(lambda x: np.asarray(x), tree)
    if use_orbax is None:
        use_orbax = False  # npz path is deterministic + dependency-light; orbax opt-in
    if use_orbax:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(target, "orbax"), host_tree, force=True)
    else:
        serialization.save_pytree(host_tree, os.path.join(target, "state"))
    with open(os.path.join(target, "DONE"), "w") as f:
        f.write(str(step))
    return target


def latest_step(path: str) -> int | None:
    if not os.path.isdir(path):
        return None
    steps = []
    for d in os.listdir(path):
        if d.startswith("step_") and os.path.exists(os.path.join(path, d, "DONE")):
            steps.append(int(d.split("_")[1]))
    return max(steps) if steps else None


def restore_checkpoint(path: str, step: int | None = None, sharding_fn=None) -> Any:
    """Restore; `sharding_fn(leaf_path) -> Sharding` re-places leaves on the
    current mesh (None = host numpy)."""
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no completed checkpoint under {path}")
    target = _step_dir(path, step)
    orbax_dir = os.path.join(target, "orbax")
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        tree = ocp.PyTreeCheckpointer().restore(orbax_dir)
    else:
        tree = serialization.load_pytree(os.path.join(target, "state"))
    if sharding_fn is not None:
        tree = jax.tree.map(lambda x: jax.device_put(x, sharding_fn(x)), tree)
    return tree
