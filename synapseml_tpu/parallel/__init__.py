from .backend import (
    DistributedBackend,
    DriverRendezvous,
    initialize_backend,
    reset_backend,
    worker_rendezvous,
)
from .batching import (
    DoubleBufferedFeeder,
    PaddedBatch,
    batches,
    bucket_size,
    pad_batch,
    pad_sequences,
    round_up_to_multiple,
    unpad,
)
from .checkpoint import (AsyncCheckpointer, CheckpointCorrupt,
                         checkpoint_meta, checkpoint_sharding,
                         checkpoint_world, commit_checkpoint, gc_checkpoints,
                         latest_step, latest_verified_step,
                         restore_checkpoint, restore_host_states,
                         save_checkpoint, save_checkpoint_shard,
                         verify_checkpoint)
from .gang import (EXIT_PREEMPTED, EXIT_RESIZE, ElasticResume, GangAborted,
                   GangCoordinator, GangWorker, Preempted, elastic_restore,
                   run_gang_member)
from .mesh import MeshConfig, MeshContext, P, create_mesh, logical_axis_rules, shard_params
from .partition import (PartitionRules, apply_manifest_sharding,
                        checkpoint_sharding_fn, default_llama_rules,
                        default_transformer_rules, emit_shard_metrics,
                        match_partition_rules, opt_state_specs,
                        shard_tree, sharding_manifest_section,
                        split_stage_params, stack_stages)
from .pipeline import pipeline_apply, pipeline_sharded, stack_stage_params

__all__ = [
    "DistributedBackend", "DriverRendezvous", "initialize_backend", "reset_backend",
    "worker_rendezvous",
    "DoubleBufferedFeeder", "PaddedBatch", "batches", "bucket_size", "pad_batch",
    "pad_sequences", "round_up_to_multiple", "unpad",
    "AsyncCheckpointer", "CheckpointCorrupt", "checkpoint_meta",
    "checkpoint_sharding", "checkpoint_world", "commit_checkpoint",
    "gc_checkpoints", "latest_step", "latest_verified_step",
    "restore_checkpoint", "restore_host_states", "save_checkpoint",
    "save_checkpoint_shard", "verify_checkpoint",
    "EXIT_PREEMPTED", "EXIT_RESIZE", "ElasticResume", "GangAborted",
    "GangCoordinator", "GangWorker", "Preempted", "elastic_restore",
    "run_gang_member",
    "MeshConfig", "MeshContext", "P", "create_mesh", "logical_axis_rules", "shard_params",
    "PartitionRules", "apply_manifest_sharding", "checkpoint_sharding_fn",
    "default_llama_rules", "default_transformer_rules", "emit_shard_metrics",
    "match_partition_rules", "opt_state_specs", "shard_tree",
    "sharding_manifest_section", "split_stage_params", "stack_stages",
    "pipeline_apply", "pipeline_sharded", "stack_stage_params",
]
