from .backend import (
    DistributedBackend,
    DriverRendezvous,
    initialize_backend,
    reset_backend,
    worker_rendezvous,
)
from .batching import (
    DoubleBufferedFeeder,
    PaddedBatch,
    batches,
    bucket_size,
    pad_batch,
    pad_sequences,
    round_up_to_multiple,
    unpad,
)
from .checkpoint import (AsyncCheckpointer, latest_step,
                         restore_checkpoint, save_checkpoint)
from .mesh import MeshConfig, MeshContext, P, create_mesh, logical_axis_rules, shard_params
from .pipeline import pipeline_apply, pipeline_sharded, stack_stage_params

__all__ = [
    "DistributedBackend", "DriverRendezvous", "initialize_backend", "reset_backend",
    "worker_rendezvous",
    "DoubleBufferedFeeder", "PaddedBatch", "batches", "bucket_size", "pad_batch",
    "pad_sequences", "round_up_to_multiple", "unpad",
    "AsyncCheckpointer", "latest_step", "restore_checkpoint", "save_checkpoint",
    "MeshConfig", "MeshContext", "P", "create_mesh", "logical_axis_rules", "shard_params",
    "pipeline_apply", "pipeline_sharded", "stack_stage_params",
]
