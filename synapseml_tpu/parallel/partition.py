"""Declarative sharding plane: regex partition rules over PLAIN pytrees.

The mesh module places Flax params that carry ``nn.Partitioned`` metadata
(``shard_params``) or reboxes plain trees through an ``eval_shape`` of the
module (``shard_inference_params`` — inference-side only). This module is
the third, declarative path (fmengine-style, SNIPPETS.md [1]/[3]): an
ordered table of ``(regex, PartitionSpec)`` rules matched against
slash-joined param-path names, so ANY plain pytree — trainer params,
``models.convert_hf`` checkpoints, optax optimizer state (whose tree paths
embed the param names: ``1/0/mu/dense/kernel``) — gets mesh placement
without module metadata. One table serves four consumers:

* training (``models/trainer.py``): param placement + ZeRO sharding of the
  optimizer state over the data-parallel replica axes (arXiv:2004.13336 —
  the weight update is sharded, gradients/params stay data-parallel);
* inference (``hf/causal_lm.py``): pretrained plain pytrees placed without
  the eval_shape rebox;
* pipeline stage splits (``models/pipeline_trainer.py``): the table's
  ``stage_regex`` names the cut points that partition a flat param tree
  into GPipe stages over the ``pipe`` axis;
* artifacts: the table serializes to JSON, rides registry manifests
  (``sharding`` section) and checkpoints, and re-applies at
  ``/admin/load`` — a mesh that cannot be built on the loading host
  demotes to a replicated load with ONE structured warning, never a
  failed swap.

Rules are first-match-wins; scalar / single-element leaves always
replicate (never worth a collective); unmatched leaves follow the table's
``unmatched`` policy (``replicate`` | ``error``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import math
import re
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import AXES, MeshConfig, MeshContext

__all__ = ["PartitionRules", "match_partition_rules", "tree_path_name",
           "shard_tree", "tree_shardings", "place_tree", "place_leaf",
           "opt_state_specs",
           "zero_shard_spec", "split_stage_params", "stack_stages",
           "pipeline_param_specs", "pipeline_opt_specs",
           "checkpoint_sharding_fn", "spec_digests",
           "sharding_manifest_section", "apply_manifest_sharding",
           "sharding_target",
           "emit_shard_metrics", "per_device_bytes", "total_bytes",
           "default_llama_rules", "default_transformer_rules"]

logger = logging.getLogger("synapseml_tpu.parallel.partition")


def tree_path_name(path: Sequence) -> str:
    """A ``tree_flatten_with_path`` key path -> slash-joined name
    (``DictKey`` -> key, ``SequenceKey`` -> index, ``GetAttrKey`` ->
    attribute — so optax NamedTuple states read ``1/0/mu/dense/kernel``).
    The ``value`` attribute component of a flax ``nn.Partitioned`` box is
    dropped (attribute access only — a dict key named ``value`` survives),
    so one rule table matches boxed init trees and the plain checkpoint
    pytrees they round-trip to."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            part = str(k.key)
        elif hasattr(k, "idx"):
            part = str(k.idx)
        elif hasattr(k, "name"):
            if str(k.name) == "value":
                continue  # flax nn.Partitioned box around the array
            part = str(k.name)
        else:
            part = str(k)
        if not part.startswith("."):
            parts.append(part)
    return "/".join(parts)


def _spec_entry_to_json(entry) -> Any:
    if entry is None or isinstance(entry, str):
        return entry
    return list(entry)


def _spec_entry_from_json(entry) -> Any:
    if entry is None or isinstance(entry, str):
        return entry
    return tuple(entry)


def _spec_axes(spec: P) -> set:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, str):
            used.add(entry)
        else:
            used.update(entry)
    return used


@dataclasses.dataclass(frozen=True)
class PartitionRules:
    """The serializable rule table.

    ``rules``: ordered ``(pattern, spec entries)`` pairs — ``pattern`` is a
    Python regex ``re.search``-ed against the slash-joined leaf path;
    ``spec entries`` is one mesh-axis entry per array dim (``None`` |
    ``"axis"`` | ``("axis", "axis")``), exactly ``PartitionSpec``'s
    constructor arguments. First match wins.

    ``unmatched``: ``"replicate"`` (default) or ``"error"`` — what happens
    to a non-scalar leaf no rule matches.

    ``zero_axes``: the replica axes the ZeRO weight-update sharding
    partitions optimizer state over (default: the data-parallel group).

    ``stage_regex``: optional regex with ONE capture group (the stage
    index) naming the pipeline cut points — see :func:`split_stage_params`.

    ``mesh``: optional :class:`~synapseml_tpu.parallel.mesh.MeshConfig`
    recorded so manifests/checkpoints can rebuild the intended topology.
    """

    rules: tuple = ()
    unmatched: str = "replicate"
    zero_axes: tuple = ("data", "fsdp")
    stage_regex: str | None = None
    mesh: MeshConfig | None = None

    def __post_init__(self):
        norm = []
        for pattern, entries in self.rules:
            re.compile(pattern)  # fail fast on a bad regex, at table build
            norm.append((str(pattern),
                         tuple(_spec_entry_from_json(e) for e in entries)))
        object.__setattr__(self, "rules", tuple(norm))
        object.__setattr__(self, "zero_axes", tuple(self.zero_axes))
        if self.unmatched not in ("replicate", "error"):
            raise ValueError(f"unmatched must be 'replicate' or 'error', "
                             f"got {self.unmatched!r}")
        if self.stage_regex is not None:
            rx = re.compile(self.stage_regex)
            if rx.groups != 1:
                raise ValueError(
                    f"stage_regex needs exactly ONE capture group (the "
                    f"stage index), got {rx.groups} in {self.stage_regex!r}")
        if self.mesh is not None and not isinstance(self.mesh, MeshConfig):
            object.__setattr__(self, "mesh", MeshConfig(**dict(self.mesh)))

    def spec_for(self, name: str, shape: Sequence[int]) -> P:
        """First-match-wins spec for one leaf. Scalars / single-element
        leaves replicate unconditionally."""
        shape = tuple(int(s) for s in shape)
        if len(shape) == 0 or math.prod(shape) == 1:
            return P()
        for pattern, entries in self.rules:
            if re.search(pattern, name) is not None:
                if len(entries) > len(shape):
                    raise ValueError(
                        f"partition rule {pattern!r} has {len(entries)} dim "
                        f"entries but {name!r} has rank {len(shape)}")
                return P(*entries)
        if self.unmatched == "replicate":
            return P()
        raise ValueError(f"no partition rule matches {name!r} "
                         f"(unmatched='error'); rules: "
                         f"{[p for p, _ in self.rules]}")

    # -- wire format (manifests, checkpoints, /admin/load) -----------------
    def to_json(self) -> dict:
        out = {"rules": [[p, [_spec_entry_to_json(e) for e in entries]]
                         for p, entries in self.rules],
               "unmatched": self.unmatched,
               "zero_axes": list(self.zero_axes)}
        if self.stage_regex is not None:
            out["stage_regex"] = self.stage_regex
        if self.mesh is not None:
            out["mesh"] = dataclasses.asdict(self.mesh)
        return out

    @classmethod
    def from_json(cls, data: dict) -> "PartitionRules":
        mesh = data.get("mesh")
        return cls(rules=tuple((p, tuple(_spec_entry_from_json(e)
                                         for e in entries))
                               for p, entries in data.get("rules", ())),
                   unmatched=data.get("unmatched", "replicate"),
                   zero_axes=tuple(data.get("zero_axes",
                                            ("data", "fsdp"))),
                   stage_regex=data.get("stage_regex"),
                   mesh=MeshConfig(**mesh) if mesh else None)

    def digest(self) -> str:
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def _leaf_shape(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    return tuple(shape) if shape is not None else tuple(np.shape(leaf))


def match_partition_rules(rules: PartitionRules, tree: Any) -> Any:
    """Pytree of :class:`PartitionSpec`, one per leaf of ``tree`` (arrays
    or ``ShapeDtypeStruct`` skeletons — only ``.shape`` is read)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: rules.spec_for(tree_path_name(path),
                                          _leaf_shape(leaf)), tree)


def _validate_spec(name: str, shape: tuple, spec: P, sizes: dict) -> None:
    for d, entry in enumerate(spec):
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        for a in axes:
            if a not in sizes:
                raise ValueError(f"{name}: spec axis {a!r} is not a mesh "
                                 f"axis (have {sorted(sizes)})")
        div = math.prod(sizes[a] for a in axes)
        if shape[d] % div:
            raise ValueError(
                f"{name}: dim {d} of shape {shape} is not divisible by "
                f"the {axes} axis product {div}")


def tree_shardings(mesh_ctx: MeshContext, spec_tree: Any,
                   value_tree: Any | None = None) -> Any:
    """Spec pytree -> ``NamedSharding`` pytree on the context's mesh.
    ``value_tree`` (same structure) enables divisibility validation with
    the failing leaf path in the error."""
    sizes = mesh_ctx.axis_sizes
    if value_tree is not None:
        def check(path, leaf, spec):
            _validate_spec(tree_path_name(path), _leaf_shape(leaf), spec,
                           sizes)
            return NamedSharding(mesh_ctx.mesh, spec)

        return jax.tree_util.tree_map_with_path(check, value_tree, spec_tree)
    return jax.tree.map(lambda s: NamedSharding(mesh_ctx.mesh, s), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def place_leaf(x: Any, sharding) -> Any:
    """Place ONE leaf onto a sharding. Host arrays destined for a sharding
    that spans other processes go through ``make_array_from_callback`` —
    each process materializes only its addressable shard slices (the
    multi-host "no host holds the full tree on device" path);
    ``device_put`` covers everything fully addressable."""
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        # already a cross-process array: re-placing onto the SAME layout
        # is a no-op; a different layout would need a collective reshard
        # (no host holds the full value to slice from)
        if x.sharding == sharding:
            return x
        raise ValueError(
            "cannot re-place a cross-process array onto a different "
            f"sharding ({x.sharding} -> {sharding}) without a collective "
            "reshard; restore/supply the leaf host-side instead")
    # cross-process sharding: device_put would need a collective equality
    # check (unavailable on some backends); build from local slices — the
    # callback reads ONLY this process's shard index ranges
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def place_tree(tree: Any, sharding_tree: Any) -> Any:
    """Place every leaf onto its sharding — per-device transfers move only
    that device's shard slices of a host array."""
    return jax.tree.map(place_leaf, tree, sharding_tree)


def shard_tree(tree: Any, mesh_ctx: MeshContext,
               rules: PartitionRules) -> Any:
    """Match + validate + place a plain pytree in one call (the
    ``shard_inference_params`` replacement for rule-table consumers)."""
    specs = match_partition_rules(rules, tree)
    return place_tree(tree, tree_shardings(mesh_ctx, specs, tree))


# ---- ZeRO: optimizer-state sharding over the replica group ---------------

def zero_shard_spec(spec: P, shape: Sequence[int], sizes: dict,
                    zero_axes: Sequence[str]) -> P:
    """Extend a leaf's spec with the ZeRO partitioning: shard the FIRST
    unsharded dim divisible by the replica-group size over the zero axes
    not already used by the spec. Leaves with no divisible free dim keep
    their spec (small biases etc. stay replicated — the epsilon in the
    per-replica byte bound)."""
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0 or math.prod(shape) == 1:
        return spec
    used = _spec_axes(spec)
    free = tuple(a for a in zero_axes if a not in used
                 and sizes.get(a, 1) > 1)
    if not free:
        return spec
    group = math.prod(sizes[a] for a in free)
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for d, entry in enumerate(entries):
        if entry is None and shape[d] % group == 0:
            entries[d] = free[0] if len(free) == 1 else free
            return P(*entries)
    return spec


def opt_state_specs(rules: PartitionRules, opt_state: Any,
                    mesh_ctx: MeshContext, zero: bool = False) -> Any:
    """Spec pytree for an optimizer state (or its ``eval_shape`` skeleton).
    The SAME rule table applies — optax state paths embed the param names
    (``1/0/mu/dense/kernel``), so a param's rule carries to its moments;
    ``count`` and other scalars replicate. ``zero=True`` adds the
    weight-update sharding over ``rules.zero_axes`` on top."""
    sizes = mesh_ctx.axis_sizes

    def pick(path, leaf):
        name = tree_path_name(path)
        shape = _leaf_shape(leaf)
        spec = rules.spec_for(name, shape)
        if zero:
            spec = zero_shard_spec(spec, shape, sizes, rules.zero_axes)
        return spec

    return jax.tree_util.tree_map_with_path(pick, opt_state)


# ---- pipeline stage splits (GPipe cut points from the rule table) --------

def _insert(tree: dict, parts: list, leaf) -> None:
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = leaf


def split_stage_params(params: Any, stage_regex: str
                       ) -> tuple[dict, list[dict]]:
    """Partition a flat param tree into pipeline stages by the declared cut
    regex (ONE capture group = the stage index, e.g. ``layer_(\\d+)``).
    Returns ``(shared, stages)``: ``shared`` holds every unmatched leaf
    (embeddings, heads — they run outside the pipeline ring), ``stages[i]``
    the i-th stage's subtree with the stage index normalized out of the
    path so every stage is structurally identical (the GPipe chainable
    requirement — validated here, with the offending paths named)."""
    rx = re.compile(stage_regex)
    if rx.groups != 1:
        raise ValueError(f"stage_regex needs exactly ONE capture group, "
                         f"got {rx.groups} in {stage_regex!r}")
    shared: dict = {}
    staged: dict[int, dict] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = tree_path_name(path)
        m = rx.search(name)
        if m is None:
            _insert(shared, name.split("/"), leaf)
            continue
        try:
            idx = int(m.group(1))
        except ValueError as e:
            raise ValueError(f"stage_regex capture on {name!r} is not an "
                             f"integer stage index: {m.group(1)!r}") from e
        norm = name[:m.start(1)] + "#" + name[m.end(1):]
        _insert(staged.setdefault(idx, {}), norm.split("/"), leaf)
    indices = sorted(staged)
    if indices != list(range(len(indices))):
        raise ValueError(f"stage indices must be contiguous from 0, got "
                         f"{indices}")
    if not indices:
        raise ValueError(f"stage_regex {stage_regex!r} matched no params")
    stages = [staged[i] for i in indices]
    ref = {tree_path_name(p): _leaf_shape(x) for p, x in
           jax.tree_util.tree_flatten_with_path(stages[0])[0]}
    for i, st in enumerate(stages[1:], start=1):
        got = {tree_path_name(p): _leaf_shape(x) for p, x in
               jax.tree_util.tree_flatten_with_path(st)[0]}
        if got != ref:
            raise ValueError(
                f"stage {i} structure differs from stage 0 (stages must be "
                f"chainable): {sorted(set(got) ^ set(ref)) or 'shape drift'}")
    return shared, stages


def stack_stages(params: Any, stage_regex: str) -> tuple[dict, Any]:
    """``split_stage_params`` + stack into the leading-stage-axis layout
    ``parallel.pipeline`` consumes (shard that axis over ``pipe``)."""
    from .pipeline import stack_stage_params

    shared, stages = split_stage_params(params, stage_regex)
    return shared, stack_stage_params(stages)


# ---- pipeline placement (stage-stacked trees) ----------------------------

def _is_stage_leaf(name: str) -> bool:
    """Leaf of the pipeline-stacked ``stages`` subtree (works for params
    — ``stages/...`` — and optimizer state — ``1/0/mu/stages/...``)."""
    return name.startswith("stages/") or "/stages/" in name


def pipeline_param_specs(rules: PartitionRules | None, params: Any,
                         axis_name: str = "pipe") -> Any:
    """Spec tree for a pipeline trainer's ``{"shared": ..., "stages":
    <leading-stage-axis stack>}`` param tree: stage leaves shard their
    leading axis over ``axis_name`` (per-device weights = ONE stage's),
    shared leaves (embeddings/heads) follow the rule table."""
    rules = rules or PartitionRules()

    def pick(path, leaf):
        name = tree_path_name(path)
        shape = _leaf_shape(leaf)
        if _is_stage_leaf(name) and len(shape) >= 1:
            return P(axis_name)
        return rules.spec_for(name, shape)

    return jax.tree_util.tree_map_with_path(pick, params)


def pipeline_opt_specs(rules: PartitionRules | None, opt_state: Any,
                       mesh_ctx: MeshContext, zero: bool = False,
                       axis_name: str = "pipe") -> Any:
    """Optimizer-state specs mirroring :func:`pipeline_param_specs` (the
    moments of a stage's weights live only on that stage's pipe
    coordinate), with the ZeRO weight-update sharding over the replica
    axes on top when enabled."""
    rules = rules or PartitionRules()
    sizes = mesh_ctx.axis_sizes

    def pick(path, leaf):
        name = tree_path_name(path)
        shape = _leaf_shape(leaf)
        if len(shape) == 0 or math.prod(shape) == 1:
            return P()
        if _is_stage_leaf(name):
            spec = P(axis_name)
        else:
            spec = rules.spec_for(name, shape)
        if zero:
            spec = zero_shard_spec(spec, shape, sizes, rules.zero_axes)
        return spec

    return jax.tree_util.tree_map_with_path(pick, opt_state)


# ---- checkpoint restore placement ----------------------------------------

def checkpoint_sharding_fn(rules: PartitionRules, mesh_ctx: MeshContext,
                           zero: bool = False,
                           pipeline_axis: str | None = None):
    """A path-aware ``sharding_fn`` for ``restore_checkpoint``: each leaf
    of a full train-state tree (``params``/``opt_state``/``step``/
    ``batch_stats``/``data_iter``) restores DIRECTLY onto its rule-table
    placement — per-device transfers move only that device's shard slices,
    so no host materializes a device-resident full copy. ``data_iter``
    (the loader's iterator state) stays host-side numpy (returns None).
    ``pipeline_axis`` routes stage-stacked ``stages`` subtrees (a
    :class:`~synapseml_tpu.models.pipeline_trainer.PipelineTrainer`
    state) onto their pipe-coordinate placement."""
    sizes = mesh_ctx.axis_sizes

    def fn(name: str, leaf):
        root, _, rest = name.partition("/")
        if root == "data_iter":
            return None  # IteratorState is host-side bookkeeping
        shape = _leaf_shape(leaf)
        if len(shape) == 0 or math.prod(shape) == 1:
            return NamedSharding(mesh_ctx.mesh, P())
        # strip the train-state root so rules match the SAME names live
        # placement saw ('params/w' -> 'w', 'opt_state/1/0/mu/...' ->
        # '1/0/mu/...') — an anchored rule must not silently replicate
        # on restore
        local = rest if root in ("params", "opt_state",
                                 "batch_stats") and rest else name
        if pipeline_axis is not None and _is_stage_leaf(local):
            spec = P(pipeline_axis)
        else:
            spec = rules.spec_for(local, shape)
        if zero and root == "opt_state":
            spec = zero_shard_spec(spec, shape, sizes, rules.zero_axes)
        return NamedSharding(mesh_ctx.mesh, spec)

    return fn


# ---- manifests (registry `sharding` section) -----------------------------

def spec_digests(rules: PartitionRules, tree: Any) -> dict:
    """Per-leaf spec digests for the manifest: ``{path: sha256(path +
    spec)[:12]}`` — a loader can detect a rule-table edit that re-places
    any leaf without shipping the spec tree itself."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = tree_path_name(path)
        spec = rules.spec_for(name, _leaf_shape(leaf))
        blob = json.dumps([name, [_spec_entry_to_json(e) for e in spec]],
                          sort_keys=True).encode()
        out[name] = hashlib.sha256(blob).hexdigest()[:12]
    return out


def sharding_manifest_section(rules: PartitionRules,
                              params: Any | None = None) -> dict:
    """The registry manifest's ``sharding`` section: rule table + the mesh
    topology it targets + per-leaf spec digests (when the param tree is
    available at publish time)."""
    section = {"rules": rules.to_json(), "digest": rules.digest()}
    if rules.mesh is not None:
        section["mesh"] = dataclasses.asdict(rules.mesh)
    if params is not None:
        section["spec_digests"] = spec_digests(rules, params)
    return section


def _log_demote(reason: str, **context) -> None:
    payload = {"event": "sharding_demoted_to_replicated", "reason": reason}
    payload.update({k: v for k, v in context.items() if v is not None})
    logger.warning(json.dumps(payload, sort_keys=True, default=str))


def _has_param(stage, name: str) -> bool:
    has = getattr(stage, "has_param", None)
    return bool(has(name)) if callable(has) else False


def sharding_target(stage):
    """The stage the rule table applies to: the stage itself when it
    declares both ``partition_rules`` and ``mesh_config`` params, else the
    first nested stage of a pipeline that does (depth-first). None when
    nothing in the tree is rule-table-capable."""
    if _has_param(stage, "partition_rules") and _has_param(stage,
                                                          "mesh_config"):
        return stage
    if _has_param(stage, "stages"):
        for child in (stage.get("stages") or []):
            found = sharding_target(child)
            if found is not None:
                return found
    return None


def apply_manifest_sharding(stage, section: dict, enabled: bool = True,
                            **context) -> tuple[bool, str | None]:
    """Apply a manifest's ``sharding`` section to a just-loaded stage
    BEFORE warmup (nested pipeline stages are searched for the first
    rule-table-capable stage). Returns ``(applied, reason)`` — any
    mismatch (mesh that cannot be built from this host's devices, stage
    without the rule-table params) demotes to a REPLICATED load: the
    stage's ``mesh_config``/``partition_rules`` params are cleared, one
    structured warning is logged, and the swap proceeds. Never raises for
    topology reasons."""
    target = sharding_target(stage)
    if target is not None:
        stage = target
    has_rules = _has_param(stage, "partition_rules")
    has_mesh = _has_param(stage, "mesh_config")

    def demote(reason: str, warn: bool = True) -> tuple[bool, str]:
        clear = {}
        if has_rules and stage.get("partition_rules") is not None:
            clear["partition_rules"] = None
        if has_mesh and stage.get("mesh_config") is not None:
            clear["mesh_config"] = None
        if clear:
            stage.set(**clear)
        if warn:
            _log_demote(reason, **context)
        return False, reason

    if not enabled:
        # a deliberate per-load opt-out, not a mismatch — no warning
        return demote("sharding disabled by request", warn=False)
    try:
        rules = PartitionRules.from_json(section.get("rules") or {})
    except (TypeError, ValueError) as e:
        return demote(f"unreadable rule table: {e}")
    mesh_sizes = section.get("mesh") or (dataclasses.asdict(rules.mesh)
                                         if rules.mesh else None)
    if not has_rules or not has_mesh:
        return demote(f"stage {type(stage).__name__} has no "
                      "partition_rules/mesh_config params")
    if mesh_sizes is None:
        return demote("manifest sharding section carries no mesh topology")
    try:
        cfg = MeshConfig(**{k: int(v) for k, v in mesh_sizes.items()
                            if k in AXES})
        cfg.resolve(len(jax.devices()))
    except (TypeError, ValueError) as e:
        return demote(f"mesh {mesh_sizes} does not fit this host's "
                      f"{len(jax.devices())} devices: {e}")
    stage.set(mesh_config=cfg, partition_rules=rules)
    return True, None


# ---- observability: the synapseml_shard_* gauge family -------------------

def total_bytes(tree: Any) -> int:
    return sum(int(np.prod(_leaf_shape(x)) or 1)
               * int(np.dtype(getattr(x, "dtype", np.float32)).itemsize)
               for x in jax.tree.leaves(tree))


def per_device_bytes(tree: Any) -> int:
    """Bytes ONE device holds for a placed tree (sharded leaves count one
    shard; host / unplaced leaves count whole — they replicate on use)."""
    out = 0
    for x in jax.tree.leaves(tree):
        shape = _leaf_shape(x)
        item = int(np.dtype(getattr(x, "dtype", np.float32)).itemsize)
        sharding = getattr(x, "sharding", None)
        if sharding is not None and shape:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:  # noqa: BLE001 - odd shardings count whole
                pass
        out += int(np.prod(shape) or 1) * item
    return out


def _axis_bytes(tree: Any, sizes: dict) -> dict:
    """Total bytes of leaves whose placement uses each mesh axis."""
    out = {a: 0 for a in sizes if sizes[a] > 1}
    for x in jax.tree.leaves(tree):
        spec = getattr(getattr(x, "sharding", None), "spec", None)
        if spec is None:
            continue
        nbytes = (int(np.prod(_leaf_shape(x)) or 1)
                  * int(np.dtype(getattr(x, "dtype", np.float32)).itemsize))
        for a in _spec_axes(spec):
            if a in out:
                out[a] += nbytes
    return out


def emit_shard_metrics(params: Any, opt_state: Any | None = None,
                       mesh_ctx: MeshContext | None = None,
                       engine: str = "trainer") -> dict:
    """Publish the ``synapseml_shard_*`` gauge family to the PR-2 registry:
    total vs per-device bytes per tree kind, per-axis placed bytes, and
    HBM headroom after params + optimizer state (device ``memory_stats``
    when the backend exposes them — TPU does, CPU typically not).
    Returns the snapshot dict (the bench reads it)."""
    from ..core import observability as obs

    reg = obs.get_registry()
    sizes = mesh_ctx.axis_sizes if mesh_ctx is not None else {}
    snapshot: dict = {}
    per_dev_total = 0
    for kind, tree in (("params", params), ("opt_state", opt_state)):
        if tree is None:
            continue
        tot = total_bytes(tree)
        dev = per_device_bytes(tree)
        per_dev_total += dev
        reg.gauge("synapseml_shard_total_bytes",
                  "global bytes of the placed tree", ("kind", "engine")
                  ).set(tot, kind=kind, engine=engine)
        reg.gauge("synapseml_shard_bytes_per_device",
                  "bytes ONE device holds for the placed tree (the ZeRO "
                  "denominator)", ("kind", "engine")
                  ).set(dev, kind=kind, engine=engine)
        snapshot[kind] = {"total_bytes": tot, "bytes_per_device": dev}
        for axis, nbytes in _axis_bytes(tree, sizes).items():
            reg.gauge("synapseml_shard_axis_bytes",
                      "bytes of leaves sharded over each mesh axis",
                      ("kind", "axis", "engine")
                      ).set(nbytes, kind=kind, axis=axis, engine=engine)
            snapshot[kind].setdefault("axis_bytes", {})[axis] = nbytes
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
    except Exception:  # noqa: BLE001 - CPU backends have no memory_stats
        limit = 0
    if limit:
        headroom = limit - per_dev_total
        reg.gauge("synapseml_shard_hbm_headroom_bytes",
                  "device memory limit minus per-device params+opt bytes",
                  ("engine",)).set(headroom, engine=engine)
        snapshot["hbm_headroom_bytes"] = headroom
    return snapshot


# ---- default rule tables --------------------------------------------------

def _model_axis_for(mesh: MeshConfig | None) -> str:
    """The model-parallel axis the default tables shard over: ``tensor``
    normally, ``fsdp`` when the mesh declares no tensor parallelism but
    does have an fsdp group — the fsdp-only sharded-inference layout the
    pre-rule-table logical rules supported must keep working."""
    if mesh is not None and mesh.tensor == 1 and mesh.fsdp != 1:
        return "fsdp"
    return "tensor"


def default_llama_rules(mesh: MeshConfig | None = None,
                        **overrides) -> PartitionRules:
    """Megatron-style table for the :class:`LlamaLM` param tree (and its
    GPT-2 cousin): embeddings/vocab over the model-parallel axis
    (``tensor``, or ``fsdp`` on a tensor-less mesh — see
    :func:`_model_axis_for`), attention heads likewise, MLP in-dim on the
    output projection, norms replicated. ``stage_regex`` names the
    decoder-layer cut points for pipeline splits."""
    mp = _model_axis_for(mesh)
    if mp == "fsdp":
        # tensor-less mesh: shard the HIDDEN dim of every projection (the
        # layout the pre-rule-table logical rules produced — head/kv dims
        # stay whole, so small-head models divide on any fsdp size)
        rules = (
            (r"embed/embedding$", (None, "fsdp")),
            (r"wpe/embedding$", (None, None)),
            (r"lm_head/kernel$", ("fsdp", None)),
            (r"attn/(q|k|v)/kernel$", ("fsdp", None, None)),
            (r"attn/o/kernel$", (None, None, "fsdp")),
            (r"mlp/(wi|wi_0|wi_1|gate|up)/kernel$", ("fsdp", None)),
            (r"mlp/(wo|down)/kernel$", (None, "fsdp")),
            (r"(norm|ln|scale)", (None,)),
        )
    else:
        rules = (
            (r"embed/embedding$", (mp, None)),
            (r"wpe/embedding$", (None, None)),
            (r"lm_head/kernel$", (None, mp)),
            # fused QKV/attention projections: (hidden, heads, head_dim)
            (r"attn/(q|k|v)/kernel$", (None, mp, None)),
            (r"attn/o/kernel$", (mp, None, None)),
            (r"mlp/(wi|wi_0|wi_1|gate|up)/kernel$", (None, mp)),
            (r"mlp/(wo|down)/kernel$", (mp, None)),
            (r"(norm|ln|scale)", (None,)),
        )
    kw: dict = dict(rules=rules, stage_regex=r"layer_(\d+)", mesh=mesh)
    kw.update(overrides)
    return PartitionRules(**kw)


def default_transformer_rules(mesh: MeshConfig | None = None,
                              **overrides) -> PartitionRules:
    """Generic encoder table (BERT/ViT classifiers): dense kernels split
    their output dim over the model-parallel axis, output projections
    their input dim, embeddings the vocab dim."""
    mp = _model_axis_for(mesh)
    if mp == "fsdp":
        rules = (
            (r"embedding$", (None, "fsdp")),
            (r"kernel$", ("fsdp", None)),
            (r"(bias|scale)$", (None,)),
        )
    else:
        rules = (
            (r"embedding$", (mp, None)),
            (r"(out|output|o|wo|down)/kernel$", (mp, None)),
            (r"kernel$", (None, mp)),
            (r"(bias|scale)$", (None,)),
        )
    kw: dict = dict(rules=rules, stage_regex=r"layer_(\d+)", mesh=mesh)
    kw.update(overrides)
    return PartitionRules(**kw)
